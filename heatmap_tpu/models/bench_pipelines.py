"""Per-config benchmark sweep: every BASELINE.json pipeline through the
full streaming runtime (synthetic source → device aggregation → memory
store), one JSON line per config.

``python -m heatmap_tpu.models.bench_pipelines [--events N] [--batch B]``

This complements the repo-root ``bench.py`` (the headline single-metric
backfill harness the driver runs): here every (res, window) topology —
single pair, multi-res pyramid, sliding multi-window — exercises the same
fused per-pair step the production runtime uses, including emit packing,
sink submission, and watermarking.  Sources are forced synthetic so the
sweep is hermetic; Kafka-facing behavior is benchmarked by bench.py's
ingest path and the kafka microbenches.
"""

from __future__ import annotations

import argparse
import json
import time


def bench_one(name: str, n_events: int, batch: int) -> dict:
    from heatmap_tpu.config import load_config
    from heatmap_tpu.models.pipelines import get_pipeline
    from heatmap_tpu.sink import MemoryStore
    from heatmap_tpu.stream import MicroBatchRuntime, SyntheticSource

    p = get_pipeline(name)
    cfg = load_config(
        {},
        resolutions=p.config.resolutions,
        windows_minutes=p.config.windows_minutes,
        h3_res=p.config.h3_res,
        tile_minutes=p.config.tile_minutes,
        speed_hist_bins=p.config.speed_hist_bins,
        state_capacity_log2=max(p.config.state_capacity_log2, 16),
        batch_size=batch,
        store="memory",
        checkpoint_dir=f"/tmp/bench-pipelines-{name}-{int(time.time())}",
    )
    src = SyntheticSource(n_events=n_events, n_vehicles=20_000,
                         t0=int(time.time()) - 300, events_per_second=batch)
    rt = MicroBatchRuntime(cfg, src, MemoryStore(), checkpoint_every=0)
    # warmup/compile outside the timed region: one batch (its events are
    # excluded from the throughput numerator below)
    rt.step_once()
    rt.flush_pending()  # stats are pulled one batch behind the dispatch
    warm = rt.metrics.snapshot().get("events_valid", 0)
    t0 = time.monotonic()
    rt.run()
    wall = time.monotonic() - t0
    snap = rt.metrics.snapshot()
    n_total = snap.get("events_valid", 0)
    n_timed = n_total - warm
    return {
        "pipeline": name,
        "pairs": len(cfg.resolutions) * len(cfg.windows_minutes),
        "events": n_total,
        "events_per_sec": (round(n_timed / wall, 1)
                           if wall > 0 and n_timed else None),
        "batch_p50_ms": snap.get("batch_latency_p50_ms"),
        "tiles_emitted": snap.get("tiles_emitted"),
    }


def main(argv=None) -> list[dict]:
    from heatmap_tpu.models.pipelines import PIPELINES

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", type=int, default=1 << 18)
    ap.add_argument("--batch", type=int, default=1 << 14)
    ap.add_argument("--pipelines", nargs="*", default=sorted(PIPELINES))
    args = ap.parse_args(argv)

    out = []
    for name in args.pipelines:
        r = bench_one(name, args.events, args.batch)
        print(json.dumps(r), flush=True)
        out.append(r)
    return out


if __name__ == "__main__":
    main()
