"""End-to-end demo: synthetic city traffic → TPU aggregation → live map.

``python -m heatmap_tpu.models.demo [--events N] [--port P]`` runs the whole
stack in one process: SyntheticSource → MicroBatchRuntime (device H3 snap +
windowed aggregation) → MemoryStore → HTTP API/UI at http://127.0.0.1:P/.
"""

from __future__ import annotations

import argparse
import logging
import time

# pin CPU if the accelerator link is dead — the stream import below
# touches jax at module level and would otherwise hang forever
from heatmap_tpu.utils.device_probe import ensure_reachable_backend

ensure_reachable_backend()

from heatmap_tpu.config import load_config  # noqa: E402
from heatmap_tpu.serve import start_background  # noqa: E402
from heatmap_tpu.sink import MemoryStore  # noqa: E402
from heatmap_tpu.stream import MicroBatchRuntime, SyntheticSource  # noqa: E402

log = logging.getLogger("demo")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", type=int, default=500_000)
    ap.add_argument("--batch", type=int, default=1 << 14)
    ap.add_argument("--vehicles", type=int, default=2000)
    ap.add_argument("--port", type=int, default=5000)
    ap.add_argument("--serve", action="store_true",
                    help="keep serving after the replay finishes")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    cfg = load_config(
        {}, batch_size=args.batch, store="memory",
        checkpoint_dir=f"/tmp/heatmap-demo-ckpt-{int(time.time())}",
    )
    store = MemoryStore()
    src = SyntheticSource(
        n_events=args.events, n_vehicles=args.vehicles,
        t0=int(time.time()) - 600, events_per_second=args.batch,
    )
    rt = MicroBatchRuntime(cfg, src, store)
    httpd, _, port = start_background(store, cfg, rt, port=args.port)
    log.info("UI at http://127.0.0.1:%d/ — replaying %d events", port, args.events)

    t0 = time.monotonic()
    rt.run()
    wall = time.monotonic() - t0
    snap = rt.metrics.snapshot()
    log.info(
        "done: %d events in %.2fs (%.0f ev/s), %d tiles, p50 batch %.1f ms",
        snap.get("events_valid", 0), wall,
        snap.get("events_valid", 0) / max(wall, 1e-9),
        snap.get("tiles_emitted", 0), snap.get("batch_latency_p50_ms", 0),
    )
    if args.serve:
        log.info("serving until interrupted (ctrl-c)")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    httpd.shutdown()
    return snap


if __name__ == "__main__":
    main()
