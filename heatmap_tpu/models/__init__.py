"""models — the five benchmark pipeline configurations (BASELINE.json).

Each "model" is a fully-wired pipeline: source + aggregation layout +
store, expressed as a Config plus a source factory.  These are the configs
the reference's BASELINE.json enumerates:

1. ``mbta_default``     — MBTA Boston feed, H3_RES=8, 5-min window
                          (the reference's defaults, heatmap_stream.py:21-37).
2. ``opensky_global``   — OpenSky aircraft, H3_RES=7, 5-min window.
3. ``synthetic_backfill`` — 10M-event single-city replay, H3_RES=9.
4. ``hex_pyramid``      — merged feeds, multi-resolution 7/8/9.
5. ``multi_window``     — sliding 1/5/15-min windows, count+avg+p95 stats.
"""

from heatmap_tpu.models.pipelines import (  # noqa: F401
    PIPELINES,
    Pipeline,
    get_pipeline,
)
