"""Named pipeline configurations mapping BASELINE.json's five configs onto
Config + source factories."""

from __future__ import annotations

import dataclasses
from typing import Callable

from heatmap_tpu.config import Config, load_config
from heatmap_tpu.stream.source import Source, SyntheticSource


@dataclasses.dataclass(frozen=True)
class Pipeline:
    name: str
    description: str
    config: Config
    make_source: Callable[[Config], Source]


def _kafka_or_synthetic(cfg: Config) -> Source:
    """Live pipelines consume the Kafka ingress when a broker is reachable
    (the reference contract; the framework's own wire client needs no
    client library); otherwise fall back to synthetic data so the pipeline
    still runs hermetically.

    ``HEATMAP_FEEDER=proc`` moves the fetch+decode leg into its own OS
    process over a shared-memory ring (stream/shmfeed.py) — the
    executor/driver split the reference gets from Spark; measured 7.3x
    end-to-end on a contended host (PERF_E2E.md).  The in-process source
    remains the default: one fewer moving part when the host has cores
    to spare."""
    import logging
    import os

    from heatmap_tpu.stream.source import KafkaSource

    try:
        if os.environ.get("HEATMAP_FEEDER") == "proc":
            from heatmap_tpu.stream.shmfeed import ShmFeederSource

            # probe reachability BEFORE spawning the feeder so the
            # synthetic fallback engages promptly.  Pinned to the wire
            # impl: it contacts the broker in its constructor and fails
            # fast, whereas a confluent client connects lazily and would
            # vacuously pass this probe
            KafkaSource(cfg.kafka_bootstrap, cfg.kafka_topic,
                        impl="wire").close()
            return ShmFeederSource(cfg.kafka_bootstrap, cfg.kafka_topic,
                                   batch_size=cfg.batch_size)
        return KafkaSource(cfg.kafka_bootstrap, cfg.kafka_topic)
    except (ImportError, ConnectionError, OSError, RuntimeError) as e:
        # RuntimeError covers KafkaError (unknown topic / leaderless)
        logging.getLogger(__name__).warning(
            "kafka unreachable (%s); using synthetic source", e)
        return SyntheticSource(n_vehicles=1000, events_per_second=1000)


def _synthetic_backfill(cfg: Config) -> Source:
    return SyntheticSource(
        n_events=10_000_000, n_vehicles=20_000, events_per_second=1_000_000,
    )


PIPELINES: dict[str, Pipeline] = {}


def _register(name, description, make_source, **cfg_overrides):
    # env (MONGO_URI, KAFKA_BOOTSTRAP, ...) applies like the reference's
    # import-time reads; the preset's own axes (res/windows/...) win on top
    cfg = load_config(None, **cfg_overrides)
    PIPELINES[name] = Pipeline(name, description, cfg, make_source)


# 1. the reference's default configuration (BASELINE config #1)
_register(
    "mbta_default",
    "MBTA Boston feed, H3_RES=8, TILE_MINUTES=5 (reference defaults)",
    _kafka_or_synthetic,
    # nothing pinned but the city: this is the "reference defaults"
    # preset, so H3_RES / TILE_MINUTES / etc. flow from env exactly as
    # they do in the reference (load_config derives the tuple axes)
    city="bos",
)

# 2. OpenSky global aircraft (BASELINE config #2)
_register(
    "opensky_global",
    "OpenSky global aircraft, H3_RES=7, 5-min window",
    _kafka_or_synthetic,
    city="global", h3_res=7, resolutions=(7,), windows_minutes=(5,),
    tile_minutes=5,
    state_capacity_log2=19,   # global cardinality
    # aircraft ground speeds run to ~1100 km/h; the default 256 km/h
    # range would saturate every cruise-speed cell's p95.  128 bins keep
    # the one-bin p95 error bound at 10 km/h over the wider range.
    speed_hist_bins=128, speed_hist_max_kmh=1280.0,
)

# 3. synthetic 10M-event backfill (BASELINE config #3)
_register(
    "synthetic_backfill",
    "Synthetic replay: 10M-event single-city backfill, H3_RES=9",
    _synthetic_backfill,
    city="bos", h3_res=9, resolutions=(9,), windows_minutes=(5,),
    tile_minutes=5,
    batch_size=1 << 19, state_capacity_log2=20,
)

# 4. multi-resolution hex pyramid (BASELINE config #4)
_register(
    "hex_pyramid",
    "Merged MBTA+OpenSky, multi-resolution 7/8/9 hex pyramid",
    _kafka_or_synthetic,
    city="bos", h3_res=8, resolutions=(7, 8, 9), windows_minutes=(5,),
    tile_minutes=5,
)

# 5. sliding multi-window with extended stats (BASELINE config #5)
_register(
    "multi_window",
    "Sliding multi-window (1/5/15-min), count + avgSpeed + p95-speed stats",
    _kafka_or_synthetic,
    city="bos", h3_res=8, resolutions=(8,), windows_minutes=(1, 5, 15),
    tile_minutes=5,  # the 5-min window keeps the reference grid/_id naming
)


def get_pipeline(name: str) -> Pipeline:
    if name not in PIPELINES:
        raise KeyError(f"unknown pipeline {name!r}; have {sorted(PIPELINES)}")
    return PIPELINES[name]
