"""TileMatView — the materialized tile view the API reads instead of the Store.

One in-memory view of (grid, windowStart, cell) → tile doc, maintained
two ways:

- **Writer-fed** (the streaming process): ``AsyncWriter`` calls
  ``apply_packed``/``apply_docs`` on its own thread immediately AFTER a
  sink write has durably applied, so the view never exposes rows that
  aren't in the store.  Each applied batch bumps one monotonic
  ``view_seq``.
- **Store-fed** (serve-only processes): ``StoreViewRefresher`` rebuilds
  a grid from a Store scan, triggered by write-version polling plus a
  TTL for deployments where other processes write the backing store.
  An unchanged rebuild bumps nothing, so ETags stay stable across
  polls of an idle store.

The view powers:

- ``/api/tiles/latest`` renders (O(window), no Store traffic),
- strong ETags — ``etag()`` is a pure view lookup, so an If-None-Match
  hit answers 304 without invoking the renderer at all,
- ``/api/tiles/delta?since=seq`` — changed cells only, from a bounded
  per-grid changelog (mode="full" resync when the client's ``since``
  predates the log horizon, a window switch, or an eviction),
- ``/api/tiles/stream`` SSE pushes (``wait_changed`` blocks on the
  view's condition variable),
- ``/api/tiles/topk`` + bbox filtering, and ``?res=`` zoom-out via the
  incremental pyramid rollup (query.pyramid).

Window eviction mirrors the store's ``staleAt`` TTL semantics lazily at
read time; evicting the grid's LATEST window forces delta clients
through a full resync (their baseline vanished).

Thread model: one lock + condition per view.  Writers (writer thread or
refresher) and readers (HTTP threads) all serialize on it; every
critical section is dict surgery, no I/O, no rendering.
"""

from __future__ import annotations

import collections
import datetime as dt
import logging
import os
import threading
import time

from heatmap_tpu.query.pyramid import Pyramid

log = logging.getLogger(__name__)

UTC = dt.timezone.utc


def _grid_base_res(grid: str) -> int | None:
    """Base H3 resolution of a sink grid label ("h3r8" / "h3r8m1"), or
    None for labels the runtime never writes (junk ?grid= values)."""
    if not grid or not grid.startswith("h3r"):
        return None
    digits = grid[3:].split("m", 1)[0]
    try:
        res = int(digits)
    except ValueError:
        return None
    return res if 0 <= res <= 15 else None


class _Grid:
    """Per-grid view state (all access under the owning view's lock)."""

    __slots__ = ("windows", "meta", "log", "dropped_seq", "window_seq",
                 "mod_seq", "pyramid")

    def __init__(self, grid: str, delta_log: int, pyramid_levels: int):
        self.windows: dict[int, dict[str, dict]] = {}   # ws -> cell -> doc
        self.meta: dict[int, tuple] = {}  # ws -> (ws_dt, we_dt, stale_epoch)
        self.log: collections.deque = collections.deque(maxlen=delta_log)
        self.dropped_seq = 0     # newest changelog seq lost to the bound
        self.window_seq = 0      # seq when the latest window last changed
        self.mod_seq = 0         # seq of the last visible change
        base = _grid_base_res(grid)
        self.pyramid = (Pyramid(base, pyramid_levels)
                        if base is not None and pyramid_levels > 0 else None)

    def latest_ws(self) -> int | None:
        return max(self.windows) if self.windows else None


class TileMatView:
    def __init__(self, delta_log: int = 4096, pyramid_levels: int = 2,
                 registry=None, now_fn=None, replica: bool = False,
                 audit=None):
        self._delta_log = max(1, int(delta_log))
        self._pyramid_levels = max(0, int(pyramid_levels))
        self._now = now_fn or time.time
        self._grids: dict[str, _Grid] = {}
        self._seq = 0
        # Integrity observatory (obs.audit, HEATMAP_AUDIT=1): an
        # order-independent per-(grid, windowStart) content digest
        # maintained incrementally alongside every mutation below.
        # Observe-only: nothing reads it on the apply path.  The writer
        # view publishes the post-apply digest of every touched window
        # inside its repl records (``"dg"``) so replicas can verify
        # their own applied state per seq advance.
        self.audit_table = audit
        # Replica mode (query.repl): the view is a seq-exact FOLLOWER of
        # a writer's replication feed.  Local clock-driven eviction of
        # the LATEST window is disabled — the seq advance it implies
        # must come from the writer's feed marker, or the replica's seq
        # stream would diverge from the writer's and /api/tiles/delta
        # responses would stop being byte-interchangeable across the
        # fleet.  Non-latest stale windows still evict locally (they
        # never advance seq on the writer either).
        self._replica = bool(replica)
        # mutation hook (query.repl.DeltaLogPublisher): called under
        # the view lock with one record per seq-advancing mutation, in
        # seq order — the replication feed is exactly this stream
        self._hook = None
        # mutation WATCHERS (query.continuous): secondary observers of
        # the same stream, enqueue-only like the hook, but (1) there can
        # be several, (2) they additionally see a synthetic
        # {"kind": "reset"} record when replica_reset replaces the whole
        # view (the publisher hook must NOT see one — a reset is not a
        # feed record), so an observer can rebuild derived state without
        # minting phantom transitions for the bootstrap diff
        self._watchers: list = []
        # per-boot nonce folded into every ETag: seq counters restart at
        # 0 each process, so without it a post-restart ETag string could
        # equal a pre-restart one while naming DIFFERENT content — and a
        # strong ETag must never repeat across representations
        self._nonce = os.urandom(4).hex()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.poisoned = False  # an apply blew up; serving falls back
        self._h_apply = None
        if registry is not None:
            self._h_apply = registry.histogram(
                "heatmap_view_apply_seconds",
                "wall time applying one durable write batch (or one "
                "serve-only rebuild diff) to the materialized tile view")
            registry.gauge(
                "heatmap_view_seq",
                "monotonic materialized-view sequence (bumps once per "
                "applied batch / rebuild that changed the view)",
                fn=lambda: self._seq)
            registry.gauge(
                "heatmap_view_cells",
                "live (window, cell) entries held by the materialized "
                "tile view across all grids",
                fn=self.cells_live)

    def set_hook(self, fn) -> None:
        """Attach the replication mutation hook (one per view).  ``fn``
        receives {"kind": "apply"|"evict"|"resync", "seq": int, ...}
        under the view lock — it must only enqueue (the publisher
        drains on its own thread)."""
        with self._lock:
            self._hook = fn

    def add_watcher(self, fn) -> None:
        """Attach a secondary mutation observer (continuous-query
        engine).  Same discipline as the hook — called under the view
        lock, must only enqueue — plus the synthetic reset record."""
        with self._lock:
            if fn not in self._watchers:
                self._watchers.append(fn)

    def remove_watcher(self, fn) -> None:
        with self._lock:
            if fn in self._watchers:
                self._watchers.remove(fn)

    def _emit(self, rec: dict) -> None:
        """Fire the mutation hook + watchers (callers hold the lock).
        A hook failure detaches it and is logged — replication trouble
        must never poison the apply path the sink depends on; the
        detached publisher's feed goes stale, which is exactly what the
        replicas' staleness handling exists to absorb."""
        if self._hook is not None:
            try:
                self._hook(rec)
            except Exception:
                log.exception("view mutation hook failed; detaching "
                              "replication publisher")
                self._hook = None
        self._notify_watchers(rec)

    def _notify_watchers(self, rec: dict) -> None:
        for fn in list(self._watchers):
            try:
                fn(rec)
            except Exception:
                log.exception("view mutation watcher failed; detaching")
                try:
                    self._watchers.remove(fn)
                except ValueError:
                    pass

    def _dg_of(self, docs) -> dict | None:
        """{grid: {str(ws): hex-digest}} for every (grid, windowStart)
        the docs touched, read from the audit table AFTER the applies
        (callers hold the lock) — the writer's published truth a
        replica verifies its own recomputation against.  None when
        auditing is off, so feed bytes are identical to an unaudited
        run."""
        if self.audit_table is None:
            return None
        out: dict = {}
        for d in docs:
            grid = d.get("grid")
            ws_dt = d.get("windowStart")
            if not grid or not isinstance(ws_dt, dt.datetime):
                continue
            ws = int(ws_dt.timestamp())
            out.setdefault(grid, {})[str(ws)] = format(
                self.audit_table.digest(grid, ws) or 0, "016x")
        return out or None

    # ---- write side ----------------------------------------------------
    def apply_packed(self, body, meta) -> int:
        """Apply packed emit BODY rows (engine layout) — the writer-thread
        hook for the packed sink path.  Decodes with the same oracle the
        portable store write path uses, so view content is exactly what
        a Store read-back would return."""
        from heatmap_tpu.sink.base import packed_tile_docs

        return self.apply_docs(packed_tile_docs(body, meta))

    def apply_docs(self, docs) -> int:
        """Upsert tile docs into the view; one view_seq bump per call.
        Returns the number of cells whose visible doc changed."""
        if not docs:
            return 0
        t0 = time.perf_counter()
        with self._cond:
            seq = self._seq + 1
            changed_docs: list = []
            touched: set = set()
            for doc in docs:
                if self._apply_one(doc, seq):
                    changed_docs.append(doc)
                if doc.get("grid"):
                    touched.add(doc["grid"])
            changed = len(changed_docs)
            if changed:
                self._seq = seq
                self._cond.notify_all()
                rec = {"kind": "apply", "seq": seq,
                       "docs": changed_docs}
                dg = self._dg_of(changed_docs)
                if dg:
                    rec["dg"] = dg
                self._emit(rec)
            # evict on the WRITE path too: a grid nobody polls over
            # HTTP (replica behind an LB, secondary grid of a pyramid)
            # would otherwise retain every expired window's cell docs
            # and rollups forever — read-side lazy eviction alone is an
            # unbounded leak for unread grids
            for grid in touched:
                g = self._grids.get(grid)
                if g is not None:
                    self._evict(grid, g)
        if self._h_apply is not None:
            self._h_apply.observe(time.perf_counter() - t0)
        return changed

    def _grid(self, grid: str) -> _Grid:
        g = self._grids.get(grid)
        if g is None:
            g = self._grids[grid] = _Grid(grid, self._delta_log,
                                          self._pyramid_levels)
        return g

    def _apply_one(self, doc: dict, seq: int, g: _Grid | None = None) -> int:
        if g is None:
            grid = doc.get("grid")
            if not grid:
                return 0
            g = self._grid(grid)
        ws_dt = doc["windowStart"]
        ws = int(ws_dt.timestamp())
        w = g.windows.get(ws)
        if w is None:
            w = g.windows[ws] = {}
            stale = doc.get("staleAt")
            g.meta[ws] = (ws_dt, doc.get("windowEnd"),
                          stale.timestamp() if stale is not None else None)
            if ws == g.latest_ws():
                # a NEW latest window: delta clients baselined on the
                # previous window must resync
                g.window_seq = seq
        cid = doc["cellId"]
        old = w.get(cid)
        if old == doc:
            return 0
        w[cid] = doc
        if self.audit_table is not None:
            self.audit_table.update(doc.get("grid"), ws, cid, old, doc)
        if len(g.log) == g.log.maxlen and g.log:
            g.dropped_seq = g.log[0][0]
        g.log.append((seq, ws, cid))
        if ws == g.latest_ws():
            # mod_seq drives ETags and SSE wakeups: late events landing
            # in a NON-latest window change nothing a client can see, so
            # they must not flap every poller's If-None-Match (their log
            # entries are filtered out of deltas the same way)
            g.mod_seq = seq
        if g.pyramid is not None:
            try:
                g.pyramid.apply(ws, int(cid, 16), old, doc)
            except ValueError:
                g.pyramid = None  # un-H3 cell ids: rollup off for grid
        return 1

    def replace_grid(self, grid: str, docs) -> int:
        """Serve-only rebuild: make the view's ``grid`` equal a Store
        scan of its latest window.  Diffs against the current state so
        an unchanged store bumps nothing (stable ETags) and a same-window
        change flows out as a DELTA, not a full resync.  Returns changed
        cells."""
        t0 = time.perf_counter()
        docs = list(docs)
        with self._cond:
            g = self._grids.get(grid)
            if g is None:
                if not docs:
                    return 0  # junk ?grid= probes must not grow state
                g = self._grid(grid)
            new_ws = int(docs[0]["windowStart"].timestamp()) if docs else None
            self._evict(grid, g)
            cur_ws = g.latest_ws()
            changed = 0
            if new_ws is None:
                if g.windows:
                    changed = self._full_resync(grid, g, None, [])
            elif new_ws != cur_ws:
                changed = self._full_resync(grid, g, new_ws, docs)
            else:
                w = g.windows[cur_ws]
                new_cells = {d["cellId"]: d for d in docs}
                if set(w) - set(new_cells):
                    # cells vanished inside one window (an external
                    # writer replaced the store) — full resync
                    changed = self._full_resync(grid, g, new_ws, docs)
                else:
                    delta = [d for cid, d in new_cells.items()
                             if w.get(cid) != d]
                    if delta:
                        seq = self._seq + 1
                        applied = [d for d in delta
                                   if self._apply_one(d, seq, g)]
                        changed = len(applied)
                        if changed:
                            self._seq = seq
                            self._cond.notify_all()
                            rec = {"kind": "apply", "seq": seq,
                                   "docs": applied}
                            dg = self._dg_of(applied)
                            if dg:
                                rec["dg"] = dg
                            self._emit(rec)
        if self._h_apply is not None:
            self._h_apply.observe(time.perf_counter() - t0)
        return changed

    def _advance(self) -> int:
        self._seq += 1
        return self._seq

    def _full_resync(self, grid: str, g: _Grid, ws: int | None,
                     docs) -> int:
        """Replace a grid's whole state (empty when ws is None) and force
        delta clients through mode=full — the one resync sequence every
        replace_grid branch shares (callers hold the lock)."""
        seq = self._advance()
        self._drop_all_windows(grid, g)
        if ws is not None:
            self._install_window(grid, g, ws, docs)
        g.window_seq = g.mod_seq = seq
        g.log.clear()
        g.dropped_seq = seq
        self._cond.notify_all()
        rec = {"kind": "resync", "seq": seq, "grid": grid,
               "ws": ws, "docs": list(docs)}
        dg = self._dg_of(docs)
        if dg:
            rec["dg"] = dg
        self._emit(rec)
        return max(1, len(docs))

    def _drop_all_windows(self, grid: str, g: _Grid) -> None:
        for ws in list(g.windows):
            del g.windows[ws]
            del g.meta[ws]
            if g.pyramid is not None:
                g.pyramid.drop_window(ws)
            if self.audit_table is not None:
                self.audit_table.drop_window(grid, ws)

    def _install_window(self, grid: str, g: _Grid, ws: int,
                        docs) -> None:
        d0 = docs[0]
        stale = d0.get("staleAt")
        g.meta[ws] = (d0["windowStart"], d0.get("windowEnd"),
                      stale.timestamp() if stale is not None else None)
        w = g.windows[ws] = {}
        for d in docs:
            w[d["cellId"]] = d
            if self.audit_table is not None:
                self.audit_table.update(grid, ws, d["cellId"], None, d)
            if g.pyramid is not None:
                try:
                    g.pyramid.apply(ws, int(d["cellId"], 16), None, d)
                except ValueError:
                    g.pyramid = None

    def seed_grid(self, grid: str, docs) -> int:
        """One-shot warm-up of a grid the view has never seen (a
        writer-fed process restarting against a durable store): UPSERT
        the scanned docs, but only while the grid is still unknown —
        if the writer thread materialized it first, the scan is stale
        and loses.  Never removes cells, so racing a concurrent writer
        apply cannot un-expose a durable row (unlike replace_grid's
        diff, which serve-only rebuilds use as the sole feeder)."""
        with self._cond:
            if grid in self._grids:
                return 0
            docs = list(docs)
            if not docs:
                return 0
            g = self._grid(grid)
            seq = self._seq + 1
            applied = [doc for doc in docs if self._apply_one(doc, seq, g)]
            if applied:
                self._seq = seq
                self._cond.notify_all()
                rec = {"kind": "apply", "seq": seq, "docs": applied}
                dg = self._dg_of(applied)
                if dg:
                    rec["dg"] = dg
                self._emit(rec)
            return len(applied)

    def publish_anomalies(self, grid: str, events: list) -> None:
        """Fan an inference anomaly batch (infer.engine event dicts)
        into the mutation feed: one seq bump, one ``kind="anomaly"``
        record through the hook + watchers.  Runs on the writer thread
        via submit_mark, AFTER the batch's tile writes — an anomaly is
        never announced before the window state that produced it is
        durable.  Deliberately does NOT touch mod_seq / window_seq or
        the digest table: events are not tile content, so tile ETags,
        delta logs, and window digests stay byte-identical to a run
        with the reducer off.  Replicas relay the record verbatim
        (replica_apply advances seq on unknown kinds), so a replica's
        continuous-query engine sees the same stream as the writer's."""
        if not events:
            return
        with self._cond:
            self._seq += 1
            rec = {"kind": "anomaly", "seq": self._seq, "grid": grid,
                   "events": list(events)}
            self._cond.notify_all()
            self._emit(rec)

    def poison(self) -> None:
        """An apply failed: the view may have diverged from the store.
        Serving falls back to direct Store renders; SSE waiters wake."""
        with self._cond:
            self.poisoned = True
            self._cond.notify_all()

    # ---- replication (query.repl) --------------------------------------
    # The follower half of the mutation-hook contract: apply records at
    # the WRITER'S seq values, so a replica's delta/ETag seq stream is
    # interchangeable with the writer's.  Records at or below the
    # replica's seq are skipped (idempotent replay: snapshot + tail may
    # overlap).

    def replica_apply(self, rec: dict) -> int:
        """Apply one replication feed record; returns changed cells."""
        kind = rec.get("kind")
        seq = int(rec.get("seq", 0))
        with self._cond:
            if seq <= self._seq:
                return 0
            changed = 0
            if kind == "apply":
                for doc in rec.get("docs") or []:
                    changed += self._apply_one(doc, seq)
            elif kind == "evict":
                grid = rec.get("grid") or ""
                g = self._grids.get(grid)
                if g is not None:
                    for ws in rec.get("ws") or []:
                        if ws in g.windows:
                            del g.windows[ws]
                            del g.meta[ws]
                            if g.pyramid is not None:
                                g.pyramid.drop_window(ws)
                            if self.audit_table is not None:
                                self.audit_table.drop_window(grid, ws)
                    g.window_seq = g.mod_seq = seq
                    changed = 1
            elif kind == "resync":
                grid = rec.get("grid") or ""
                g = self._grid(grid)
                self._drop_all_windows(grid, g)
                ws = rec.get("ws")
                docs = rec.get("docs") or []
                if ws is not None and docs:
                    self._install_window(grid, g, int(ws), docs)
                g.window_seq = g.mod_seq = seq
                g.log.clear()
                g.dropped_seq = seq
                changed = max(1, len(docs))
            # the seq tracks the writer even when nothing changed
            # locally (replayed no-ops): lag accounting and delta
            # "since > seq -> full" behavior depend on it
            self._seq = seq
            if changed:
                self._cond.notify_all()
            self._emit(rec)  # relay topologies republish verbatim
        return changed

    def replica_reset(self, state: dict) -> None:
        """Replace the whole view with a publisher snapshot
        (``export_state`` shape): the follower's bootstrap, epoch
        switch, and post-fallback resync path.  Mints a fresh ETag
        nonce — after a reset the seq counter may move BACKWARD (a
        restarted writer), and a strong ETag must never name two
        representations."""
        with self._cond:
            self._grids.clear()
            if self.audit_table is not None:
                self.audit_table.clear()
            seq = int(state.get("seq", 0))
            for grid, gs in (state.get("grids") or {}).items():
                g = self._grid(grid)
                for ws_key, cells in (gs.get("windows") or {}).items():
                    ws = int(ws_key)
                    w = g.windows[ws] = {}
                    meta = (gs.get("meta") or {}).get(ws_key)
                    if meta:
                        g.meta[ws] = (meta[0], meta[1], meta[2])
                    else:
                        any_doc = next(iter(cells.values()), None)
                        stale = (any_doc or {}).get("staleAt")
                        g.meta[ws] = (
                            (any_doc or {}).get("windowStart"),
                            (any_doc or {}).get("windowEnd"),
                            stale.timestamp() if stale is not None
                            else None)
                    for cid, doc in cells.items():
                        w[cid] = doc
                        if self.audit_table is not None:
                            self.audit_table.update(grid, ws, cid,
                                                    None, doc)
                        if g.pyramid is not None:
                            try:
                                g.pyramid.apply(ws, int(cid, 16),
                                                None, doc)
                            except ValueError:
                                g.pyramid = None
                g.window_seq = int(gs.get("window_seq", seq))
                g.mod_seq = int(gs.get("mod_seq", seq))
                # the snapshot carries no changelog: anything before
                # its seq is beyond this replica's delta horizon
                g.dropped_seq = seq
            self._seq = seq
            self._nonce = os.urandom(4).hex()
            self._cond.notify_all()
            # watchers (not the feed hook): derived state must rebuild
            # from the replaced view instead of diffing across the
            # bootstrap — a resync never mints phantom transitions
            self._notify_watchers({"kind": "reset", "seq": seq})

    def backfill_window(self, grid: str, ws: int, docs,
                        stale_ts: float | None = None) -> bool:
        """History cold-start backfill (query/history.py): install one
        PRE-LATEST window's docs without advancing seq, firing the
        replication hook/watchers, or touching the audit table — the
        window is historical context, not a new mutation, so the
        replica's seq/ETag/delta stream stays byte-interchangeable
        with the writer's.  Refused (False) when the grid is unknown
        or empty, the window already exists, or ``ws`` would become
        the latest window (backfill must never change what /latest
        serves)."""
        docs = list(docs)
        if not docs:
            return False
        ws = int(ws)
        with self._cond:
            g = self._grids.get(grid)
            if g is None:
                return False
            latest = g.latest_ws()
            if latest is None or ws >= latest or ws in g.windows:
                return False
            d0 = docs[0]
            w = g.windows[ws] = {}
            g.meta[ws] = (d0.get("windowStart"), d0.get("windowEnd"),
                          stale_ts)
            for d in docs:
                w[d["cellId"]] = d
                if g.pyramid is not None:
                    try:
                        g.pyramid.apply(ws, int(d["cellId"], 16),
                                        None, d)
                    except ValueError:
                        g.pyramid = None
            return True

    def has_window(self, grid: str, ws: int) -> bool:
        with self._lock:
            g = self._grids.get(grid)
            return g is not None and int(ws) in g.windows

    def window_docs(self, grid: str) -> dict:
        """{ws: (ws_dt, we_dt, docs)} of the grid's live windows under
        ONE lock acquisition — the live overlay /api/tiles/range
        merges over the compacted chunk store (the view is always
        fresher than any chunk covering the same window)."""
        with self._lock:
            g = self._grids.get(grid)
            if g is None:
                return {}
            self._evict(grid, g)
            return {ws: (g.meta[ws][0], g.meta[ws][1], list(w.values()))
                    for ws, w in g.windows.items()}

    def export_state(self) -> dict:
        """The publisher's snapshot of the whole view under ONE lock
        acquisition (``replica_reset``'s input).  Window dicts are
        shallow-copied — docs are replaced, never mutated in place, so
        sharing the doc dicts with concurrent appliers is safe."""
        with self._lock:
            grids = {}
            for grid, g in self._grids.items():
                grids[grid] = {
                    "windows": {str(ws): dict(w)
                                for ws, w in g.windows.items()},
                    "meta": {str(ws): list(m)
                             for ws, m in g.meta.items()},
                    "window_seq": g.window_seq,
                    "mod_seq": g.mod_seq,
                }
            return {"seq": self._seq, "grids": grids}

    # ---- eviction (lazy, under the lock) -------------------------------
    def _evict(self, grid: str, g: _Grid) -> None:
        """Drop windows past their staleAt, mirroring the store's TTL
        index.  Evicting the LATEST window is a visible change: the seq
        advances and delta clients resync (their baseline is gone).  A
        replica never evicts its latest window locally — that seq
        advance arrives as the writer's feed marker (or not at all,
        which is what its staleness SLO is for)."""
        now = self._now()
        latest_before = g.latest_ws()
        dead = [ws for ws, (_, _, stale) in g.meta.items()
                if stale is not None and stale <= now]
        if self._replica:
            dead = [ws for ws in dead if ws != latest_before]
        for ws in dead:
            del g.windows[ws]
            del g.meta[ws]
            if g.pyramid is not None:
                g.pyramid.drop_window(ws)
            if self.audit_table is not None:
                self.audit_table.drop_window(grid, ws)
        if dead and g.latest_ws() != latest_before:
            seq = self._advance()
            g.window_seq = g.mod_seq = seq
            self._cond.notify_all()
            self._emit({"kind": "evict", "seq": seq, "grid": grid,
                        "ws": dead})

    # ---- read side -----------------------------------------------------
    def known_grid(self, grid: str) -> bool:
        with self._lock:
            return grid in self._grids

    def latest_ws_of(self, grid: str) -> int | None:
        """Epoch-seconds windowStart of the grid's latest window (the
        serving-visible one digest verification covers); None when the
        grid is unknown or empty."""
        with self._lock:
            g = self._grids.get(grid)
            return g.latest_ws() if g is not None else None

    def audit_digest(self, grid: str, ws: int) -> int | None:
        """This view's own content digest for (grid, windowStart) —
        what a replica compares against the writer's published value
        (obs.audit.AuditState.verify_record).  None when auditing is
        off or the window is absent."""
        if self.audit_table is None:
            return None
        return self.audit_table.digest(grid, int(ws))

    def etag(self, grid: str, res: int | None = None) -> str:
        """Strong ETag for the grid's current latest-window view (and
        rollup resolution) — a pure lookup; computing it never renders."""
        with self._lock:
            g = self._grids.get(grid)
            if g is None:
                return f'"{self._nonce}.{grid}.{res}.none.0"'
            self._evict(grid, g)
            return (f'"{self._nonce}.{grid}.{res}.'
                    f'{g.latest_ws()}.{g.mod_seq}"')

    def latest_docs(self, grid: str,
                    res: int | None = None) -> tuple[object, list]:
        """(window_start datetime | None, docs) of the grid's latest
        window; ``res`` selects a pyramid rollup level.  Raises KeyError
        on a resolution the pyramid does not maintain."""
        _, ws_dt, docs = self.snapshot(grid, res)
        return ws_dt, docs

    def snapshot(self, grid: str,
                 res: int | None = None) -> tuple[str, object, list]:
        """(etag, window_start, docs) captured under ONE lock
        acquisition — the pair the serving layer labels responses with.
        Reading them separately would let a concurrent writer apply
        land between the two, pairing a stale strong ETag with newer
        content (one ETag must never name two representations)."""
        etag, ws_dt, docs, _seq = self.snapshot_seq(grid, res)
        return etag, ws_dt, docs

    def snapshot_seq(self, grid: str,
                     res: int | None = None) -> tuple:
        """(etag, window_start, docs, view_seq) under ONE lock
        acquisition — the binary wire frame stamps the view seq into
        every /latest response (the same seq a delta client would feed
        back as ``since=``), so it must be captured atomically with
        the ETag and docs it describes."""
        with self._lock:
            g = self._grids.get(grid)
            if g is None:
                self._check_res(None, grid, res)
                return (f'"{self._nonce}.{grid}.{res}.none.0"', None,
                        [], self._seq)
            self._evict(grid, g)
            ws = g.latest_ws()
            self._check_res(g, grid, res)
            etag = (f'"{self._nonce}.{grid}.{res}.'
                    f'{ws}.{g.mod_seq}"')
            if ws is None:
                return etag, None, [], self._seq
            ws_dt, we_dt, _ = g.meta[ws]
            if res is None or res == _grid_base_res(grid):
                return (etag, ws_dt, list(g.windows[ws].values()),
                        self._seq)
            return (etag, ws_dt, g.pyramid.docs(res, ws, we_dt, ws_dt),
                    self._seq)

    def _check_res(self, g: _Grid | None, grid: str,
                   res: int | None) -> None:
        if res is None or res == _grid_base_res(grid):
            return
        pyr = g.pyramid if g is not None else None
        if pyr is None or res not in pyr.resolutions:
            raise KeyError(res)

    def delta(self, grid: str, since: int) -> dict:
        """Changed cells of the grid's latest window after view seq
        ``since``.  Returns {"mode": "delta"|"full", "seq": next-since,
        "window_start": datetime|None, "docs": [...]}.

        mode="full" (docs = the entire latest window; the client
        REPLACES its set) whenever ``since`` predates the changelog
        horizon, the latest-window switch, an eviction/rebuild, or the
        view itself (a restarted server).  mode="delta" guarantees: the
        client's set at ``since`` plus these upserts == the latest
        window now."""
        with self._lock:
            g = self._grids.get(grid)
            if g is None:
                return {"mode": "full", "seq": self._seq,
                        "window_start": None, "docs": []}
            self._evict(grid, g)
            ws = g.latest_ws()
            if ws is None:
                return {"mode": "full", "seq": self._seq,
                        "window_start": None, "docs": []}
            ws_dt = g.meta[ws][0]
            w = g.windows[ws]
            if (since <= 0 or since > self._seq
                    or since < g.window_seq or since < g.dropped_seq):
                return {"mode": "full", "seq": self._seq,
                        "window_start": ws_dt, "docs": list(w.values())}
            cids: dict[str, None] = {}
            for seq, e_ws, cid in reversed(g.log):
                if seq <= since:
                    break
                if e_ws == ws:
                    cids.setdefault(cid)
            docs = [w[cid] for cid in cids if cid in w]
            return {"mode": "delta", "seq": self._seq,
                    "window_start": ws_dt, "docs": docs}

    def changed_since(self, grid: str, since: int) -> bool:
        with self._lock:
            g = self._grids.get(grid)
            if g is None:
                return False
            self._evict(grid, g)
            return g.mod_seq > since

    def wait_changed(self, grid: str, since: int, timeout: float) -> bool:
        """Block until the grid's view advances past ``since`` (SSE
        push), the view poisons, or the timeout lapses."""
        with self._cond:
            def ready():
                if self.poisoned:
                    return True
                g = self._grids.get(grid)
                return g is not None and g.mod_seq > since

            return self._cond.wait_for(ready, timeout=timeout)

    def topk(self, grid: str, k: int, res: int | None = None,
             bbox: tuple[float, float, float, float] | None = None) -> list:
        """Top-k docs of the latest window by count (count desc, cellId
        asc tiebreak), optionally bbox-filtered (min_lon, min_lat,
        max_lon, max_lat) on the tile centroid."""
        import heapq

        _, docs = self.latest_docs(grid, res)
        if bbox is not None:
            lo_lon, lo_lat, hi_lon, hi_lat = bbox
            kept = []
            for d in docs:
                try:
                    lon, lat = d["centroid"]["coordinates"]
                except (KeyError, TypeError, ValueError):
                    continue
                if lo_lon <= lon <= hi_lon and lo_lat <= lat <= hi_lat:
                    kept.append(d)
            docs = kept
        return heapq.nsmallest(k, docs,
                               key=lambda d: (-int(d.get("count", 0)),
                                              d.get("cellId", "")))

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def cells_live(self) -> int:
        with self._lock:
            return sum(len(w) for g in self._grids.values()
                       for w in g.windows.values())


class StoreViewRefresher:
    """Keeps a TileMatView equal to a Store for serve-only processes.

    ``refresh(grid)`` is called at the top of every view-backed request:
    it rebuilds the grid from a Store scan when the store's write
    version moved, or when ``poll_s`` elapsed — the TTL that covers
    deployments where OTHER processes write the backing store and a
    local version counter cannot see them (same bound the render cache
    uses).  Rebuild scans only the grid's latest window: exactly what
    the serving surface exposes."""

    def __init__(self, store, view: TileMatView, poll_s: float = 1.0,
                 registry=None, max_grids: int = 256):
        self.store = store
        self.view = view
        self.poll_s = poll_s
        self._max_grids = max_grids
        self._lock = threading.Lock()
        self._st: dict[str, tuple] = {}  # grid -> (ver, next_eligible_t)
        self._fails: dict[str, int] = {}  # grid -> consecutive failures
        # catch-up health for /healthz: a replica whose FIRST scan
        # failed must report degraded, not ok-but-empty — ever_ok flips
        # on the first successful rebuild (even of an empty store, which
        # is a legitimate fresh deployment, not a failure)
        self.ever_ok = False
        self.ever_failed = False
        self._c_rebuilds = None
        if registry is not None:
            self._c_rebuilds = registry.counter(
                "heatmap_view_rebuilds_total",
                "serve-only materialized-view rebuild scans (store "
                "version moved or the poll TTL lapsed)")

    def health(self) -> dict:
        """One /healthz check fragment: not-ok while the view has never
        successfully caught up from the store AND a scan has failed —
        the serves-empty-until-recovery window an LB must see as
        degraded.  Steady-state transient failures keep serving the
        bounded-stale view (ok), as before."""
        catching_up = self.ever_failed and not self.ever_ok
        fails = max(self._fails.values(), default=0)
        return {"value": ("catching up" if catching_up
                          else f"{fails} consecutive scan failures"
                          if fails else "ok"),
                "ok": not catching_up}

    def refresh(self, grid: str) -> None:
        try:
            ver = self.store.version()
        except Exception:
            ver = None
        with self._lock:
            now = time.monotonic()
            st = self._st.get(grid)
            # one guard covers both regimes: st[1] is the next-eligible
            # deadline — poll TTL after a success, the exponential
            # backoff deadline after a failure (retry SOONER than the
            # TTL at first, 0.2 s doubling toward a 30 s cap: a replica
            # must not serve empty for a full TTL because one boot-time
            # scan flaked, nor hammer a down store at request rate).  A
            # MOVED version bypasses either wait: the store is
            # answering again (or changed) and a rescan is due.
            if (st is not None and now < st[1]
                    and (ver is None or ver == st[0])):
                return
            # claim the poll slot BEFORE scanning and scan outside the
            # lock: single-flight per grid without serializing every
            # reader/SSE loop behind one slow store scan
            if len(self._st) >= self._max_grids and grid not in self._st:
                # bounded against client-controlled ?grid= values; evict
                # ONE arbitrary entry, like the serve render cache
                self._st.pop(next(iter(self._st)))
            self._st[grid] = (ver, now + self.poll_s)
        try:
            ws = self.store.latest_window_start(grid)
            docs = (list(self.store.tiles_in_window(ws, grid))
                    if ws is not None else [])
            self.view.replace_grid(grid, docs)
        except Exception:
            # a rebuild scan is idempotent: a transient store error
            # must NOT poison the view — serve the (bounded-stale)
            # current state and retry with backoff
            with self._lock:
                n = self._fails.get(grid, 0) + 1
                self._fails[grid] = n
                self.ever_failed = True
                retry = min(30.0, 0.1 * (2 ** min(n, 9)))
                if grid in self._st:
                    self._st[grid] = (self._st[grid][0],
                                      time.monotonic() + retry)
            log.warning("view rebuild failed for grid %r (attempt %d); "
                        "serving the last materialized state, retrying "
                        "in %.1fs", grid, n, retry, exc_info=True)
            return
        with self._lock:
            self._fails.pop(grid, None)
            self.ever_ok = True
        if self._c_rebuilds is not None:
            self._c_rebuilds.inc()
