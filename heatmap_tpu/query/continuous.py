"""Continuous spatial query engine over the materialized-view stream.

The serving tier answered exactly one question (latest-window
choropleth + top-k); GeoFlink's continuous spatial queries and
CheetahGIS's grid-partitioned query processing (PAPERS.md) define the
missing workload: *standing* queries — register once, get pushed
matches forever.  This module evaluates them on the replica fleet,
where the PR 8 replication feed already delivers every view mutation
in dense seq order — so query load scales horizontally with serve
workers at ZERO writer cost (the writer carries no watcher, no index,
no per-mutation work until a query is registered on it).

Query menu (one registered spec each, compiled once by query.geom):

- ``range``     — bbox/polygon subscription: every count change to a
                  matching cell in the latest window pushes a match.
- ``topk``      — regional (or whole-grid) hottest-k cells; a push
                  whenever the ranked list changes.
- ``geofence``  — ENTER/EXIT edge alerts: a cell inside the fence
                  becoming live in the serving-visible window pushes
                  ``enter``; leaving it (window advance, eviction,
                  resync) pushes ``exit``.  Granularity is the cell at
                  snap res — the replicated stream is tile-granular,
                  so "entity" here means "occupied cell".
- ``threshold`` — per-cell count threshold: ``above``/``below`` edge
                  alerts for cells crossing it.
- ``anomaly``   — per-entity anomaly subscription over the streaming
                  inference engine's event feed (infer.engine): a
                  reason-tagged event (stopped / teleport / deviation)
                  whose cell falls inside the registered region pushes
                  a match naming the entity and reason.  Events ride
                  the same replicated mutation stream as tile applies
                  (``kind="anomaly"`` records, matview.publish_
                  anomalies), so the zero-writer-cost property holds
                  identically: the writer carries no per-anomaly work
                  for queries registered on replicas.  Unlike the four
                  tile-shaped types, an anomaly query keeps NO edge
                  state — it is a pure filtered event stream, so
                  resync/reset mints nothing and replays skip on seq
                  idempotently.

Evaluation is O(changed), never O(registered): each query's compiled
``CellSet`` is filed in two per-grid inverted indexes — sliver cells
at snap res, promoted interior parents at the coarse res (the same
bit surgery as the pyramid rollup) — both EXACT, so a view mutation
for cell ``c`` touches only queries whose region actually contains
``c``, with no per-candidate geometry on the hot path.  The engine keeps its own per-grid shadow of window
cell counts, maintained purely from the mutation records — which is
what makes the load-bearing invariant provable: **a query registered
then replayed from seq 0 yields, at every seq, exactly the one-shot
evaluation of the same query against the view at that seq** (pinned in
tests/test_cq.py across window advance, eviction, epoch restart, and
pruned-horizon resync).  A replica snapshot resync arrives as the
view's synthetic ``reset`` record: derived state rebuilds from the
replaced view silently — an epoch restart or catch-up never mints
phantom enter/exit transitions.

Hook discipline: the engine attaches a view WATCHER (same contract as
the replication hook — called under the view lock, enqueue-only) and
drains on its own thread.  Attachment is LAZY: until the first
register() the view carries no watcher at all, which is how "zero
writer cost" is a metric assertion, not a claim (tools/bench_cq.py).
"""

from __future__ import annotations

import collections
import datetime as dt
import heapq
import logging
import threading
import time
import uuid

from heatmap_tpu.query import geom
from heatmap_tpu.query.matview import _grid_base_res
from heatmap_tpu.query.pyramid import cell_to_parent

log = logging.getLogger(__name__)

QUERY_TYPES = ("range", "topk", "geofence", "threshold", "anomaly")


def _chain_ids(fine, coarse, all_q):
    """Iterate the candidate query ids of one cell: its snap-index
    entry, its parent-index entry, and the whole-grid set.  The two
    indexes are disjoint per query (a sliver cell's parent was, by
    construction, NOT promoted), so no dedup is needed."""
    if fine:
        yield from fine
    if coarse:
        yield from coarse
    if all_q:
        yield from all_q

# shadow windows retained per grid: non-latest windows evict silently
# on the view (no mutation record), so the shadow bounds itself instead
_MAX_SHADOW_WINDOWS = 32


class Query:
    """One registered standing query (all mutation under the engine
    lock).  ``state`` is the incrementally-maintained edge set the
    replay invariant is about: occupied cells (geofence), above-cells
    (threshold), the ranked list (topk); range keeps none (its
    evaluation is a pure shadow scan)."""

    __slots__ = ("id", "spec", "type", "grid", "cellset", "k",
                 "threshold", "reasons", "expires_mono", "created_unix",
                 "state", "counts", "events", "ev_next", "matches",
                 "index_keys")

    def __init__(self, qid: str, spec: dict, grid: str, cellset,
                 k: int, threshold: int, expires_mono: float | None,
                 events_cap: int):
        self.id = qid
        self.spec = spec
        self.type = spec["type"]
        self.grid = grid
        self.cellset = cellset          # geom.CellSet | None (whole grid)
        self.k = k
        self.threshold = threshold
        # anomaly: accepted reason tags (None = every reason)
        self.reasons = (frozenset(spec["reasons"])
                        if spec.get("reasons") else None)
        self.expires_mono = expires_mono
        self.created_unix = time.time()
        self.state: set = set()         # geofence occupied / threshold above
        self.counts: dict = {}          # topk: cid -> count (region only)
        self.events: collections.deque = collections.deque(maxlen=events_cap)
        self.ev_next = 1
        self.matches = 0
        self.index_keys: tuple | None = None  # (sliver cells, parents)

    def contains(self, cell_int: int) -> bool:
        return self.cellset is None or self.cellset.contains(cell_int)

    def describe(self) -> dict:
        d = {"id": self.id, "type": self.type, "grid": self.grid,
             "created_unix": round(self.created_unix, 3),
             "matches": self.matches,
             "cells": (self.cellset.size() if self.cellset is not None
                       else None)}
        if self.type == "topk":
            d["k"] = self.k
        if self.type == "threshold":
            d["threshold"] = self.threshold
        if self.type == "anomaly" and self.reasons is not None:
            d["reasons"] = sorted(self.reasons)
        if self.expires_mono is not None:
            d["expires_in_s"] = round(
                max(0.0, self.expires_mono - time.monotonic()), 1)
        for key in ("bbox", "polygon"):
            if key in self.spec:
                d[key] = self.spec[key]
        return d


class _GridState:
    """Per-grid engine state: the inverted indexes and the shadow.

    Two EXACT indexes (a candidate from either is a member by
    construction — no per-candidate geometry on the hot path):
    ``index`` keys each query's sliver cells at SNAP res, ``pindex``
    keys its promoted interior parents at the coarse res.  A tiny
    fence (no parents) therefore has snap-exact selectivity — filing
    slivers under their coarse parent instead was measured ~9x worse
    at 100k-fence density (every mutation dragged in every fence
    within the parent's 49-cell footprint)."""

    __slots__ = ("index_res", "index", "pindex", "all", "wins",
                 "active")

    def __init__(self, index_res: int):
        self.index_res = index_res
        self.index: dict[int, set] = {}     # snap cell -> query ids
        self.pindex: dict[int, set] = {}    # coarse parent -> query ids
        self.all: set = set()               # whole-grid queries
        self.wins: dict[int, dict] = {}     # ws -> cid -> count
        self.active: set = set()            # qids with non-empty state

    def latest(self) -> int | None:
        return max(self.wins) if self.wins else None


class ContinuousQueryEngine:
    def __init__(self, view, registry=None, max_queries: int = 1 << 20,
                 events_per_query: int = 256, max_cells: int = 4096,
                 index_levels: int = 2, default_ttl_s: float = 3600.0,
                 clock=time.monotonic):
        self.view = view
        self.max_queries = int(max_queries)
        self.events_per_query = max(1, int(events_per_query))
        self.max_cells = int(max_cells)
        self.index_levels = max(0, int(index_levels))
        self.default_ttl_s = float(default_ttl_s)
        self.clock = clock
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # drain is single-flight: two concurrent drainers would pop
        # queue records and could acquire the engine lock out of seq
        # order — the later seq would then win and the earlier record's
        # docs would be silently skipped by the idempotency guard
        self._drain_lock = threading.Lock()
        self._queries: dict[str, Query] = {}
        self._grids: dict[str, _GridState] = {}
        self._pending: collections.deque = collections.deque()
        self._wake = threading.Event()
        self._attached = False
        self._seq = 0
        self._sweep_last = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._c_evals = self._c_matches = self._h_eval = None
        self._g_lag = None
        if registry is not None:
            registry.gauge(
                "heatmap_cq_registered",
                "standing continuous spatial queries currently "
                "registered on this worker (range / topk / geofence / "
                "threshold subscriptions)",
                fn=lambda: len(self._queries))
            self._c_evals = registry.counter(
                "heatmap_cq_evaluations_total",
                "per-query incremental evaluations performed by the "
                "continuous-query engine (one per query actually "
                "touched by a view mutation — O(changed), never "
                "O(registered))")
            self._c_matches = registry.counter(
                "heatmap_cq_matches_total",
                "match/alert records pushed by standing queries "
                "(range matches, topk changes, geofence enter/exit, "
                "threshold above/below)")
            self._h_eval = registry.histogram(
                "heatmap_cq_eval_seconds",
                "wall time evaluating one view mutation record against "
                "the touched standing queries",
                buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0))
            registry.gauge(
                "heatmap_cq_index_cells",
                "live coarse-cell keys in the continuous-query "
                "inverted index (cell -> subscribed query ids) across "
                "grids",
                fn=lambda: sum(len(g.index) + len(g.pindex)
                               for g in self._grids.values()))
            self._g_lag = registry.gauge(
                "heatmap_cq_eval_lag_seconds",
                "age of the oldest view mutation record still queued "
                "for continuous-query evaluation (0 when drained; the "
                "HEATMAP_SLO_CQ_LAG_S /healthz budget)",
                fn=self.eval_lag_s)

    # ------------------------------------------------------------ wiring
    def _ingest(self, rec: dict) -> None:
        """The view watcher: called under the VIEW lock — append-only
        (deque.append is atomic), never the engine lock."""
        self._pending.append((time.monotonic(), rec))
        self._wake.set()

    def _attach(self) -> None:
        """First register(): hook the view and seed the shadow.  Order
        matters the same way the repl publisher's does — watcher first,
        snapshot second, so a mutation in the gap is in the queue, the
        snapshot, or both (re-applies are idempotent: the shadow stores
        counts, not deltas)."""
        if self._attached:
            return
        self.view.add_watcher(self._ingest)
        self._attached = True
        self._seed_from_view()

    def _seed_from_view(self) -> None:
        state = self.view.export_state()
        self._seq = int(state.get("seq", 0))
        for grid, gs in (state.get("grids") or {}).items():
            g = self._grid(grid)
            g.wins.clear()
            for ws_key, cells in (gs.get("windows") or {}).items():
                g.wins[int(ws_key)] = {cid: int(doc.get("count", 0))
                                       for cid, doc in cells.items()}

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._attached:
            self.view.remove_watcher(self._ingest)
            self._attached = False

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="cq-engine")
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            try:
                self.drain()
            except Exception:
                log.exception("continuous-query drain failed")
            self._maybe_sweep()

    # ---------------------------------------------------------- register
    def _grid(self, grid: str) -> _GridState:
        g = self._grids.get(grid)
        if g is None:
            base = _grid_base_res(grid)
            index_res = max(0, (base if base is not None else 8)
                            - self.index_levels)
            g = self._grids[grid] = _GridState(index_res)
        return g

    def validate(self, spec: dict, default_grid: str | None) -> dict:
        """Normalize + validate a registration spec; raises ValueError
        with an operator-shaped message (the API answers 400 with it)."""
        if not isinstance(spec, dict):
            raise ValueError("query spec must be a JSON object")
        qtype = spec.get("type")
        if qtype not in QUERY_TYPES:
            raise ValueError(
                f"type must be one of {'/'.join(QUERY_TYPES)}, "
                f"got {qtype!r}")
        grid = spec.get("grid") or default_grid
        if not grid or _grid_base_res(str(grid)) is None:
            raise ValueError(f"grid {grid!r} is not a sink grid label "
                             f"(h3r<res>[m<min>])")
        out = {"type": qtype, "grid": str(grid)}
        if "bbox" in spec and "polygon" in spec:
            raise ValueError("give bbox OR polygon, not both")
        if "bbox" in spec:
            b = spec["bbox"]
            if not (isinstance(b, (list, tuple)) and len(b) == 4):
                raise ValueError(
                    "bbox must be [min_lon, min_lat, max_lon, max_lat]")
            out["bbox"] = [float(v) for v in b]
        elif "polygon" in spec:
            p = spec["polygon"]
            if not (isinstance(p, (list, tuple)) and len(p) >= 3):
                raise ValueError(
                    "polygon must be [[lon, lat], ...] with >= 3 points")
            out["polygon"] = [[float(x), float(y)] for x, y in p]
        elif qtype in ("geofence", "anomaly"):
            raise ValueError(f"{qtype} queries need a bbox or polygon")
        if qtype == "anomaly":
            reasons = spec.get("reasons")
            if reasons is not None:
                from heatmap_tpu.infer import ANOMALY_REASONS

                if (not isinstance(reasons, (list, tuple)) or not reasons
                        or any(r not in ANOMALY_REASONS for r in reasons)):
                    raise ValueError(
                        f"reasons must be a non-empty list drawn from "
                        f"{'/'.join(ANOMALY_REASONS)}, got {reasons!r}")
                out["reasons"] = sorted(set(reasons))
        if qtype == "topk":
            k = spec.get("k", 10)
            if not isinstance(k, int) or not 1 <= k <= 1000:
                raise ValueError(f"k must be an int in 1..1000, got {k!r}")
            out["k"] = k
        if qtype == "threshold":
            t = spec.get("threshold")
            if not isinstance(t, int) or t < 1:
                raise ValueError(
                    f"threshold must be an int >= 1, got {t!r}")
            out["threshold"] = t
        ttl = spec.get("ttl_s", self.default_ttl_s)
        if not isinstance(ttl, (int, float)) or ttl < 0:
            raise ValueError(f"ttl_s must be a number >= 0 (0 = no "
                             f"expiry), got {ttl!r}")
        out["ttl_s"] = float(ttl)
        return out

    def register(self, spec: dict,
                 default_grid: str | None = None) -> dict:
        """Compile + index one standing query; returns its description
        (id included).  Raises ValueError on a bad spec or a full
        engine."""
        norm = self.validate(spec, default_grid)
        grid = norm["grid"]
        base_res = _grid_base_res(grid)
        with self._lock:
            if len(self._queries) >= self.max_queries:
                raise ValueError(
                    f"query limit reached ({self.max_queries}; "
                    f"HEATMAP_CQ_MAX_QUERIES)")
            g = self._grid(grid)
            cellset = None
            if "bbox" in norm:
                cellset = geom.compile_bbox(
                    norm["bbox"], base_res, coarse_res=g.index_res,
                    max_cells=self.max_cells)
            elif "polygon" in norm:
                cellset = geom.compile_polygon(
                    norm["polygon"], base_res, coarse_res=g.index_res,
                    max_cells=self.max_cells)
            qid = uuid.uuid4().hex[:16]
            q = Query(qid, norm, grid, cellset,
                      k=norm.get("k", 10),
                      threshold=norm.get("threshold", 1),
                      expires_mono=(self.clock() + norm["ttl_s"]
                                    if norm["ttl_s"] > 0 else None),
                      events_cap=self.events_per_query)
            self._attach()
            if cellset is None:
                g.all.add(qid)
            else:
                q.index_keys = (cellset.cells, cellset.parents)
                for key in cellset.cells:
                    g.index.setdefault(key, set()).add(qid)
                for key in cellset.parents:
                    g.pindex.setdefault(key, set()).add(qid)
            self._queries[qid] = q
            # seed the edge state from the CURRENT one-shot evaluation,
            # silently: registration is not a transition, so a fence
            # over an already-occupied cell must not alert "enter"
            self._seed_query(q, g)
        self._ensure_thread()
        return q.describe()

    def _members_of(self, q: Query, g: _GridState, win: dict) -> dict:
        """{cid: count} of the window cells inside the query's region.
        A sliver-only compiled set (tiny fence, the common case at
        registration-storm scale) probes its OWN few cells against the
        window instead of scanning the window — O(|fence|), not
        O(|city|)."""
        cs = q.cellset
        if cs is None:
            return dict(win)
        if not cs.parents and len(cs.cells) * 4 < len(win):
            out = {}
            for ci in cs.cells:
                cid = format(ci, "x")
                c = win.get(cid)
                if c is not None:
                    out[cid] = c
            return out
        cells, parents, ires = cs.cells, cs.parents, g.index_res
        out = {}
        for cid, c in win.items():
            ci = int(cid, 16)
            if ci in cells or cell_to_parent(ci, ires) in parents:
                out[cid] = c
        return out

    def _bulk_members(self, g: _GridState, win: dict) -> dict:
        """{qid: {cid: count}} for EVERY query the window's cells
        touch, built in one pass over the window through the inverted
        index — the resync/advance path must never be O(registered ×
        window)."""
        out: dict = {}
        for cid, c in win.items():
            ci = int(cid, 16)
            fine = g.index.get(ci)
            coarse = g.pindex.get(cell_to_parent(ci, g.index_res))
            for qid in _chain_ids(fine, coarse, g.all):
                out.setdefault(qid, {})[cid] = c
        return out

    def _seed_from_members(self, q: Query, g: _GridState,
                           members: dict) -> None:
        """Silently install a query's edge state from its current
        region members (registration and resync are not transitions)."""
        if q.type == "geofence":
            q.state = set(members)
        elif q.type == "threshold":
            q.state = {cid for cid, c in members.items()
                       if c >= q.threshold}
        elif q.type == "topk":
            q.counts = dict(members)
            # seed the last-pushed ranking signature too: the
            # incremental state must equal the one-shot list right
            # after a registration or resync, and the next real change
            # must push exactly one update
            q.state = {tuple((e["cell"], e["count"]) for e in
                             self._topk_of(q.counts, q.k))}
        if q.state or q.counts:
            g.active.add(q.id)
        else:
            g.active.discard(q.id)

    def _seed_query(self, q: Query, g: _GridState) -> None:
        latest = g.latest()
        if latest is None:
            return
        self._seed_from_members(q, g,
                                self._members_of(q, g, g.wins[latest]))

    def remove(self, qid: str) -> bool:
        with self._lock:
            q = self._queries.pop(qid, None)
            if q is None:
                return False
            g = self._grids.get(q.grid)
            if g is not None:
                g.all.discard(qid)
                g.active.discard(qid)
                fine, coarse = q.index_keys or ((), ())
                for keys, idx in ((fine, g.index), (coarse, g.pindex)):
                    for key in keys:
                        ids = idx.get(key)
                        if ids is not None:
                            ids.discard(qid)
                            if not ids:
                                del idx[key]
            self._cond.notify_all()
            return True

    def _maybe_sweep(self) -> None:
        now = self.clock()
        with self._lock:
            if now - self._sweep_last < 1.0:
                return
            self._sweep_last = now
            dead = [qid for qid, q in self._queries.items()
                    if q.expires_mono is not None
                    and q.expires_mono <= now]
        for qid in dead:
            self.remove(qid)

    # ------------------------------------------------------------- drain
    def eval_lag_s(self) -> float:
        try:
            head = self._pending[0]
        except IndexError:
            return 0.0  # drained between the scrape's check and read
        return max(0.0, time.monotonic() - head[0])

    def drain(self, max_n: int = 100000) -> int:
        """Apply queued mutation records in order; returns records
        processed.  Tests drive this synchronously for per-seq
        determinism; production drains on the engine thread."""
        n = 0
        with self._drain_lock:
            while self._pending and n < max_n:
                t_enq, rec = self._pending.popleft()
                t0 = time.perf_counter()
                try:
                    with self._lock:
                        self._process(rec)
                except Exception:
                    log.exception("continuous-query record eval failed "
                                  "(kind=%s seq=%s)", rec.get("kind"),
                                  rec.get("seq"))
                if self._h_eval is not None:
                    self._h_eval.observe(time.perf_counter() - t0)
                n += 1
        if n:
            with self._cond:
                self._cond.notify_all()
        return n

    def _process(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "reset":
            # replica snapshot resync / epoch switch: rebuild the
            # shadow AND every query's edge state from the replaced
            # view, emitting nothing — the records between the old and
            # new state were never observed, so diffing across the gap
            # would mint phantom transitions.  One bulk pass per grid
            # through the index (never O(registered x window)).
            self._seed_from_view()
            for q in self._queries.values():
                q.state = set()
                q.counts = {}
            for grid, g in self._grids.items():
                g.active.clear()
                latest = g.latest()
                if latest is None:
                    continue
                by_q = self._bulk_members(g, g.wins[latest])
                for qid, members in by_q.items():
                    q = self._queries.get(qid)
                    if q is not None and q.grid == grid:
                        self._seed_from_members(q, g, members)
            return
        seq = int(rec.get("seq", 0))
        if seq <= self._seq:
            return  # snapshot/tail overlap replay — idempotent skip
        self._seq = seq
        if kind == "apply":
            self._apply_record(rec.get("docs") or [], seq)
        elif kind == "evict":
            grid = rec.get("grid") or ""
            g = self._grids.get(grid)
            if g is None:
                return
            for ws in rec.get("ws") or []:
                g.wins.pop(int(ws), None)
            self._retarget(grid, g, seq)
        elif kind == "resync":
            grid = rec.get("grid") or ""
            g = self._grid(grid)
            g.wins.clear()
            ws = rec.get("ws")
            docs = rec.get("docs") or []
            if ws is not None and docs:
                g.wins[int(ws)] = {d["cellId"]: int(d.get("count", 0))
                                   for d in docs}
            self._retarget(grid, g, seq)
        elif kind == "anomaly":
            self._anomaly_record(rec, seq)

    def _anomaly_record(self, rec: dict, seq: int) -> None:
        """Match one inference anomaly batch against anomaly
        subscribers through the same inverted indexes the tile types
        use — O(events x candidates-of-their-cells), never
        O(registered).  Event cells are snapped at the grid's base res
        by the inference engine (infer.engine._raise_events), so index
        membership is exact here too."""
        grid = rec.get("grid") or ""
        g = self._grids.get(grid)
        if g is None:
            return
        ws = g.latest() or 0
        for ev in rec.get("events") or []:
            cid = ev.get("cell")
            reason = ev.get("reason")
            if not cid or not reason:
                continue
            try:
                ci = int(cid, 16)
            except ValueError:
                continue
            fine = g.index.get(ci)
            coarse = g.pindex.get(cell_to_parent(ci, g.index_res))
            for qid in list(_chain_ids(fine, coarse, g.all)):
                q = self._queries.get(qid)
                if q is None or q.type != "anomaly":
                    continue
                if q.reasons is not None and reason not in q.reasons:
                    continue
                if self._c_evals is not None:
                    self._c_evals.inc()
                self._emit(q, "anomaly", seq, grid, ws, cid=cid,
                           extra={"entity": ev.get("entity"),
                                  "reason": reason,
                                  "score": ev.get("score"),
                                  "lat": ev.get("lat"),
                                  "lon": ev.get("lon"),
                                  "speedKmh": ev.get("speedKmh"),
                                  "eventT": ev.get("t")})

    def _apply_record(self, docs, seq: int) -> None:
        """One apply record, evaluated at RECORD granularity.  A window
        advance is detected against the record's per-grid max ws and
        handled after the WHOLE record's docs are in the shadow —
        diffing edge state against a partially-installed new window
        would flap exit/enter pairs for cells occupied in both windows
        (and push truncated topk lists) whenever the advancing record
        carries more than one doc."""
        staged: dict[str, list] = {}
        for doc in docs:
            grid = doc.get("grid")
            ws_dt_v = doc.get("windowStart")
            cid = doc.get("cellId")
            if not grid or cid is None \
                    or not isinstance(ws_dt_v, dt.datetime):
                continue
            staged.setdefault(grid, []).append(
                (int(ws_dt_v.timestamp()), cid,
                 int(doc.get("count", 0)), doc))
        for grid, items in staged.items():
            g = self._grids.get(grid)
            if g is None:
                # no queries ever touched this grid: keep a shadow
                # anyway (cheap — counts only), so a query registered
                # later has state to seed from without a view export
                g = self._grid(grid)
            latest_before = g.latest()
            rec_max_ws = max(ws for ws, _, _, _ in items)
            if latest_before is not None and rec_max_ws > latest_before:
                # window advance: install everything first, then diff
                # edge state ONCE against the complete new window
                for ws, cid, count, _doc in items:
                    self._shadow_put(g, ws, cid, count)
                self._retarget(grid, g, seq)
                # _retarget deliberately pushes no per-cell range
                # deltas; the new window's docs ARE count changes the
                # range contract promises to push
                latest = g.latest()
                self._range_matches(
                    grid, g, seq, latest,
                    [(cid, count) for ws, cid, count, _ in items
                     if ws == latest])
                continue
            for ws, cid, count, doc in items:
                old = self._shadow_put(g, ws, cid, count)
                if ws == g.latest():
                    self._touch(grid, g, seq, ws, cid, old, count, doc)
                # else: late event into a non-latest window, invisible

    def _shadow_put(self, g: _GridState, ws: int, cid: str,
                    count: int):
        """Install one count into the shadow; returns the previous
        count (None when new)."""
        win = g.wins.get(ws)
        if win is None:
            win = g.wins[ws] = {}
            while len(g.wins) > _MAX_SHADOW_WINDOWS:
                del g.wins[min(g.wins)]
        old = win.get(cid)
        win[cid] = count
        return old

    def _range_matches(self, grid: str, g: _GridState, seq: int,
                       ws: int | None, pairs) -> None:
        """Push ``match`` events to range subscribers for freshly
        installed latest-window docs (the window-advance path)."""
        if ws is None:
            return
        for cid, count in pairs:
            ci = int(cid, 16)
            fine = g.index.get(ci)
            coarse = g.pindex.get(cell_to_parent(ci, g.index_res))
            for qid in list(_chain_ids(fine, coarse, g.all)):
                q = self._queries.get(qid)
                if q is None or q.type != "range":
                    continue
                if self._c_evals is not None:
                    self._c_evals.inc()
                self._emit(q, "match", seq, grid, ws, cid=cid,
                           count=count)

    def _touch(self, grid: str, g: _GridState, seq: int, ws: int,
               cid: str, old: int | None, count: int, doc: dict) -> None:
        # the engine's only hot path: one changed cell against its
        # candidate queries.  Both indexes are EXACT (a query appears
        # under a snap cell or its promoted parent only if the cell is
        # a member), so there is no per-candidate geometry here at all
        cell_int = int(cid, 16)
        fine = g.index.get(cell_int)
        coarse = g.pindex.get(cell_to_parent(cell_int, g.index_res))
        if not fine and not coarse and not g.all:
            return
        for qid in list(_chain_ids(fine, coarse, g.all)):
            q = self._queries.get(qid)
            if q is None:
                continue
            if self._c_evals is not None:
                self._c_evals.inc()
            if q.type == "range":
                if old != count:
                    self._emit(q, "match", seq, grid, ws, cid=cid,
                               count=count)
            elif q.type == "geofence":
                if cid not in q.state:
                    q.state.add(cid)
                    g.active.add(qid)
                    self._emit(q, "enter", seq, grid, ws, cid=cid,
                               count=count)
            elif q.type == "threshold":
                above = count >= q.threshold
                was = cid in q.state
                if above and not was:
                    q.state.add(cid)
                    g.active.add(qid)
                    self._emit(q, "above", seq, grid, ws, cid=cid,
                               count=count)
                elif was and not above:
                    q.state.discard(cid)
                    self._emit(q, "below", seq, grid, ws, cid=cid,
                               count=count)
            elif q.type == "topk":
                if q.counts.get(cid) != count:
                    q.counts[cid] = count
                    g.active.add(qid)
                    self._retopk(q, seq, grid, ws)

    @staticmethod
    def _topk_of(counts: dict, k: int) -> list:
        return [{"cell": cid, "count": counts[cid]}
                for cid in heapq.nsmallest(
                    k, counts, key=lambda c: (-counts[c], c))]

    def _retopk(self, q: Query, seq: int, grid: str, ws: int) -> None:
        # q.state holds the last pushed ranking signature (the set slot
        # reused as a one-element container) — a count change inside
        # the region that does not reorder the published list pushes
        # nothing
        top = self._topk_of(q.counts, q.k)
        sig = tuple((e["cell"], e["count"]) for e in top)
        if q.state and next(iter(q.state)) == sig:
            return
        q.state = {sig}
        self._emit(q, "topk", seq, grid, ws, topk=top)

    def _retarget(self, grid: str, g: _GridState, seq: int) -> None:
        """The serving-visible window changed wholesale (advance /
        eviction / feed resync): rebuild every touched query's edge
        state against the new latest window and emit the DIFF — cells
        present in both windows transition nothing."""
        latest = g.latest()
        win = g.wins.get(latest, {}) if latest is not None else {}
        ws = latest if latest is not None else 0
        # one bulk pass over the new window through the index, then
        # diff every touched query — plus everything with PRIOR state
        # (its cells may have vanished entirely)
        by_q = self._bulk_members(g, win)
        cands = set(g.active) | set(by_q)
        for qid in cands:
            q = self._queries.get(qid)
            if q is None:
                continue
            if self._c_evals is not None:
                self._c_evals.inc()
            members = by_q.get(qid, {})
            if q.type == "geofence":
                new = set(members)
                for cid in sorted(q.state - new):
                    self._emit(q, "exit", seq, grid, ws, cid=cid)
                for cid in sorted(new - q.state):
                    self._emit(q, "enter", seq, grid, ws, cid=cid,
                               count=members.get(cid))
                q.state = new
            elif q.type == "threshold":
                new = {cid for cid, c in members.items()
                       if c >= q.threshold}
                for cid in sorted(q.state - new):
                    self._emit(q, "below", seq, grid, ws, cid=cid,
                               count=members.get(cid))
                for cid in sorted(new - q.state):
                    self._emit(q, "above", seq, grid, ws, cid=cid,
                               count=members.get(cid))
                q.state = new
            elif q.type == "topk":
                q.counts = dict(members)
                self._retopk(q, seq, grid, ws)
            # range: per-cell applies to the new window emit their own
            # matches; a wholesale switch has no per-cell delta to push
            if q.state or q.counts:
                g.active.add(qid)
            else:
                g.active.discard(qid)

    def _emit(self, q: Query, kind: str, seq: int, grid: str, ws: int,
              cid: str | None = None, count: int | None = None,
              topk: list | None = None,
              extra: dict | None = None) -> None:
        ev = {"id": q.ev_next, "query": q.id, "kind": kind, "seq": seq,
              "grid": grid, "windowStart": ws,
              "t": round(time.time(), 3)}
        if cid is not None:
            ev["cell"] = cid
        if count is not None:
            ev["count"] = int(count)
        if topk is not None:
            ev["topk"] = topk
        if extra:
            ev.update({k: v for k, v in extra.items() if v is not None})
        q.ev_next += 1
        q.matches += 1
        q.events.append(ev)
        if self._c_matches is not None:
            self._c_matches.inc()

    # -------------------------------------------------------------- read
    def evaluate(self, qid: str) -> dict | None:
        """One-shot evaluation of a registered query against the
        engine's shadow (== the view at the last drained seq): the
        differential replay invariant's left-hand side, and the
        /api/queries?id= detail payload."""
        with self._lock:
            q = self._queries.get(qid)
            if q is None:
                return None
            g = self._grids.get(q.grid)
            latest = g.latest() if g is not None else None
            win = g.wins.get(latest, {}) if latest is not None else {}
            out = {"id": q.id, "type": q.type, "grid": q.grid,
                   "seq": self._seq, "windowStart": latest}
            members = self._members_of(q, g, win)
            if q.type == "topk":
                out["topk"] = self._topk_of(members, q.k)
            elif q.type == "threshold":
                out["cells"] = sorted(cid for cid, c in members.items()
                                      if c >= q.threshold)
            else:  # range / geofence: the matched/occupied cell set
                out["cells"] = sorted(members)
            return out

    @staticmethod
    def oneshot(spec: dict, docs) -> dict:
        """The invariant's right-hand side: evaluate a (validated) spec
        against one latest-window doc list directly — no engine, no
        shadow, no incremental state.  tests/test_cq.py compares this
        against ``evaluate`` at every seq."""
        base_res = _grid_base_res(spec["grid"])
        coarse = max(0, base_res - 2)
        cellset = None
        if "bbox" in spec:
            cellset = geom.compile_bbox(spec["bbox"], base_res,
                                        coarse_res=coarse)
        elif "polygon" in spec:
            cellset = geom.compile_polygon(spec["polygon"], base_res,
                                           coarse_res=coarse)

        def member(cid: str) -> bool:
            return cellset is None or cellset.contains(int(cid, 16))

        counts = {d["cellId"]: int(d.get("count", 0)) for d in docs
                  if member(d["cellId"])}
        if spec["type"] == "topk":
            k = spec.get("k", 10)
            return {"topk": [
                {"cell": cid, "count": counts[cid]}
                for cid in heapq.nsmallest(
                    k, counts, key=lambda c: (-counts[c], c))]}
        if spec["type"] == "threshold":
            t = spec.get("threshold", 1)
            return {"cells": sorted(c for c, n in counts.items()
                                    if n >= t)}
        return {"cells": sorted(counts)}

    def get(self, qid: str) -> Query | None:
        with self._lock:
            return self._queries.get(qid)

    def state_of(self, qid: str):
        """The INCREMENTALLY-maintained edge state (vs ``evaluate``'s
        shadow scan): sorted occupied/above cells, or the last pushed
        topk list — what the differential replay test pins against the
        one-shot evaluation at every seq."""
        with self._lock:
            q = self._queries.get(qid)
            if q is None:
                return None
            if q.type == "topk":
                sig = next(iter(q.state), ())
                return [{"cell": c, "count": n} for c, n in sig]
            return sorted(q.state)

    def describe(self, qid: str) -> dict | None:
        with self._lock:
            q = self._queries.get(qid)
            return q.describe() if q is not None else None

    def list(self, limit: int = 100) -> dict:
        with self._lock:
            qs = sorted(self._queries.values(),
                        key=lambda q: q.created_unix)
            return {"registered": len(qs),
                    "queries": [q.describe() for q in qs[:limit]]}

    def events_since(self, qid: str, last_id: int,
                     max_n: int = 256) -> list:
        with self._lock:
            q = self._queries.get(qid)
            if q is None:
                return []
            return [ev for ev in q.events if ev["id"] > last_id][:max_n]

    def wait_events(self, qid: str, last_id: int,
                    timeout: float) -> bool:
        """Block until the query has events past ``last_id``, was
        removed, or the timeout lapses (the SSE push wait)."""
        with self._cond:
            def ready():
                q = self._queries.get(qid)
                return q is None or (len(q.events) > 0
                                     and q.events[-1]["id"] > last_id)

            return self._cond.wait_for(ready, timeout=timeout)

    @property
    def registered(self) -> int:
        with self._lock:
            return len(self._queries)

    # --------------------------------------------------------- surfaces
    def healthz_checks(self, lag_budget_s: float) -> tuple[dict, bool]:
        """({check: ...}, degraded): evaluation lag past the
        HEATMAP_SLO_CQ_LAG_S budget degrades — standing subscribers are
        being pushed stale matches."""
        lag = self.eval_lag_s()
        ok = lag <= lag_budget_s
        return ({"cq_lag_s": {"value": round(lag, 3),
                              "budget": lag_budget_s, "ok": ok,
                              "registered": self.registered}},
                not ok)

    def member_block(self) -> dict:
        """The compact ``cq`` block a fleet member snapshot publishes
        (obs.xproc) — what obs_top --fleet renders per member."""
        with self._lock:
            evals = (self._c_evals.value
                     if self._c_evals is not None else 0)
            matches = (self._c_matches.value
                       if self._c_matches is not None else 0)
            return {
                "registered": len(self._queries),
                "evaluations": int(evals),
                "matches": int(matches),
                "eval_lag_s": round(self.eval_lag_s(), 3),
                "index_cells": sum(len(g.index) + len(g.pindex)
                                   for g in self._grids.values()),
            }
