"""Delta-log view replication: writer-published feed, zero-store-read replicas.

The PR 4 matview decoupled reads from the Store for ONE process; a
serve-only replica still rebuilt its view by store-scan polling
(``StoreViewRefresher``), re-coupling the read fleet to the Store
exactly when fan-out matters.  This module ships the view's own
mutation stream — the same bounded per-grid delta protocol
``/api/tiles/delta`` already replays byte-exactly from ``since=0`` —
over a replication channel, so any number of serve workers hold a hot,
seq-consistent ``TileMatView`` with zero steady-state store reads
(WarpFlow's serving-tier shape, PAPERS.md: precomputed, replicated,
delta-refreshed views in front of the compute tier).

Feed anatomy (one directory per writer, ``HEATMAP_REPL_DIR``):

- ``meta.json`` — the feed header, atomically rewritten
  (obs.xproc.atomic_write_json): ``epoch`` (a per-boot nonce), the
  newest published ``last_seq``, the oldest record seq still retained
  (``min_seq``), the latest snapshot's seq, and ``updated_unix`` (the
  staleness signal every channel artifact carries).
- ``snapshot-<epoch>.json`` — the full view state at one seq
  (``TileMatView.export_state``), atomically rewritten on every
  segment rotation.  Catch-up is snapshot-then-tail: a follower that
  predates the oldest retained segment re-bootstraps from here.
- ``seg-<epoch>-<startseq>.jsonl`` — the mutation records themselves,
  one JSON line per seq-advancing view mutation ({"kind":
  "apply"|"evict"|"resync", "seq", ...}), appended by the publisher
  thread and rotated at ``HEATMAP_REPL_SEG_BYTES``; the newest
  ``HEATMAP_REPL_SEGMENTS`` segments are retained (older ones are
  covered by the rotation-time snapshot).

Epoch/seq invariants:

- seqs are the writer view's own ``view_seq`` — strictly increasing
  within an epoch, never reused, so a replica's ``/api/tiles/delta``
  seq stream is interchangeable with the writer's;
- the epoch nonce changes on every writer boot and prefixes every
  artifact, so a restarted writer (whose seq counter restarts) can
  never splice stale records into a new feed: a follower that sees the
  epoch change discards EVERYTHING and re-bootstraps from the new
  epoch's snapshot — the stale tail is unreachable by construction;
- records ≤ the replica's applied seq are skipped (snapshot + tail
  overlap is idempotent).

Transports: :class:`FileFeedSource` tails the directory directly
(same-host fleets — the file-per-writer, atomic-rename,
staleness-detectable discipline of obs/xproc.py); for remote replicas
the writer's serve app exposes the same three artifacts over HTTP
(``/api/repl/meta``, ``/api/repl/snapshot``, ``/api/repl/feed`` —
serve/api.py) and :class:`HttpFeedSource` consumes them over plain
TCP long-polls.  Records ride JSON with tagged datetimes
(``{"$dt": iso}``) that round-trip exactly, so a replica's rendered
bytes equal the writer's.

``ReplicaViewFollower`` drives a replica-mode ``TileMatView`` from any
source: snapshot bootstrap, tail apply through the same
``TileMatView`` mutation path the writer uses (ETag/delta/SSE/topk/
pyramid all work unchanged), seq-lag + staleness gauges, and a
degraded-until-first-snapshot /healthz contract with exponential
retry backoff.
"""

from __future__ import annotations

import collections
import datetime as dt
import glob
import json
import logging
import os
import threading
import time
import uuid

from heatmap_tpu.obs.delivery import delivery_enabled
from heatmap_tpu.obs.xproc import atomic_write_json, fleet_max_age_s

log = logging.getLogger(__name__)

META = "meta.json"


# ---------------------------------------------------------------- codec
def _enc_default(o):
    if isinstance(o, dt.datetime):
        return {"$dt": o.isoformat()}
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


def _dec_hook(d: dict):
    if len(d) == 1 and "$dt" in d:
        return dt.datetime.fromisoformat(d["$dt"])
    return d


def dumps(obj) -> str:
    """Feed-record JSON: compact, with datetimes tagged ``{"$dt": iso}``
    so they round-trip to equal datetime objects — the replica's
    rendered response bytes must equal the writer's."""
    return json.dumps(obj, separators=(",", ":"), default=_enc_default)


def loads(s: str):
    return json.loads(s, object_hook=_dec_hook)


# ------------------------------------------------------------- publisher
class DeltaLogPublisher:
    """Publishes a ``TileMatView``'s mutation stream as the replication
    feed.  The view's hook (called under the view lock) only enqueues;
    a daemon thread drains to the segment log every ``flush_s`` and
    heartbeats ``meta.json`` so followers can tell a quiet writer from
    a dead one.  One publisher per feed directory — the boot sweep
    removes every prior epoch's artifacts."""

    def __init__(self, view, feed_dir: str, seg_bytes: int = 1 << 22,
                 segments: int = 4, flush_s: float = 0.05,
                 registry=None, start: bool = True, hist=None,
                 clock=time.time, event_age_fn=None):
        self.view = view
        self.dir = feed_dir
        # delivery lineage (obs.delivery, HEATMAP_DELIVERY=1): stamp a
        # writer-clock triple pt=[enqueue, publish, event_age] into each
        # feed record so replicas can telescope delivered freshness back
        # to the event.  Knob-gated at construction: with it off the
        # hook stays the deque's bare append and flush writes the exact
        # bytes an uninstrumented build would — the feed is pinned
        # byte-identical by tests/test_delivery.py.
        self.clock = clock
        self._event_age_fn = event_age_fn
        self._delivery = delivery_enabled()
        # space-time history hand-off (query/history.py HistoryLog,
        # HEATMAP_HIST_DIR): with it, rotated segments are RETIRED into
        # the durable log instead of deleted, and every snapshot is
        # adopted as a view-at-seq replay base — the feed becomes the
        # system's log of record instead of a replication detail
        self.hist = hist
        self.seg_bytes = max(4096, int(seg_bytes))
        self.segments = max(1, int(segments))
        self.flush_s = flush_s
        self.epoch = uuid.uuid4().hex[:12]
        self._q: collections.deque = collections.deque()
        self._io_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._fh = None
        self._fh_bytes = 0
        self._last_seq = 0
        self._min_seq = 1          # oldest record seq still on disk
        self._snapshot_seq = 0
        self._meta_beat = 0.0
        self._c_published = self._g_feed_seq = None
        if registry is not None:
            self._c_published = registry.counter(
                "heatmap_repl_published_total",
                "view mutation records appended to the replication "
                "feed (one per seq-advancing view apply/evict/resync)")
            self._g_feed_seq = registry.gauge(
                "heatmap_repl_feed_seq",
                "newest view seq published to the replication feed",
                fn=lambda: self._last_seq)
        os.makedirs(feed_dir, exist_ok=True)
        # boot sweep: a restarted writer's stale epoch must be
        # unreachable — followers pin the epoch, and these files would
        # otherwise accumulate forever.  With history attached, the
        # dead epoch's segments (including its never-rotated live
        # tail, which a crash left behind) RETIRE into the durable log
        # instead of vanishing — a writer crash loses no history.
        for p in glob.glob(os.path.join(glob.escape(feed_dir),
                                        "seg-*.jsonl")):
            if self.hist is not None:
                self.hist.retire(p)
            else:
                try:
                    os.remove(p)
                except OSError:
                    pass
        for p in glob.glob(os.path.join(glob.escape(feed_dir),
                                        "snapshot-*.json")):
            try:
                os.remove(p)
            except OSError:
                pass
        # hook BEFORE the boot snapshot: a mutation landing between the
        # two would otherwise be in neither (not exported, not hooked) —
        # a permanent seq gap no follower could cross.  With this order
        # a mutation is in the snapshot, the queue, or both (overlap is
        # idempotent: followers skip records ≤ their seq).
        view.set_hook(self._enqueue if self._delivery
                      else self._q.append)
        with self._io_lock:
            self._write_snapshot()
            self._open_segment(self._last_seq + 1)
            self._write_meta()
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="repl-publisher")
            self._thread.start()

    # the hook target is the deque's own append (atomic, lock-free, and
    # safe under the view lock); everything below runs on the publisher
    # thread or the closing caller

    def _enqueue(self, rec: dict) -> None:
        """Delivery-knob hook: stamp enqueue time (and the PR 3
        lineage's newest committed event age, when wired) before the
        append.  Runs under the view lock — one clock read, one
        optional watermark read, no I/O."""
        rec = dict(rec)
        rec["_eq"] = self.clock()
        if self._event_age_fn is not None:
            try:
                rec["_ea"] = float(self._event_age_fn())
            except Exception:  # noqa: BLE001 - lineage must not block
                pass
        self._q.append(rec)

    def _seg_path(self, start_seq: int) -> str:
        return os.path.join(self.dir,
                            f"seg-{self.epoch}-{start_seq:012d}.jsonl")

    def _open_segment(self, start_seq: int) -> None:
        self._fh_path = self._seg_path(start_seq)
        self._fh = open(self._fh_path, "a", encoding="utf-8")
        self._fh_bytes = 0

    def _write_snapshot(self) -> None:
        state = self.view.export_state()
        self._snapshot_seq = state["seq"]
        self._last_seq = max(self._last_seq, state["seq"])
        payload = json.loads(dumps({"epoch": self.epoch,
                                    "seq": state["seq"],
                                    "state": state}))
        atomic_write_json(
            os.path.join(self.dir, f"snapshot-{self.epoch}.json"),
            payload)
        if self.hist is not None:
            # every snapshot (boot + each rotation) is a replay base:
            # retention can then prune old segments without orphaning
            # view-at-seq reconstruction of the retained tail
            self.hist.adopt_snapshot(self.epoch, state["seq"], payload)

    def _write_meta(self, closed: bool = False) -> None:
        payload = {
            "epoch": self.epoch,
            "last_seq": self._last_seq,
            "min_seq": self._min_seq,
            "snapshot_seq": self._snapshot_seq,
            "updated_unix": round(time.time(), 3),
        }
        if closed:
            payload["closed"] = True
        atomic_write_json(os.path.join(self.dir, META), payload)
        self._meta_beat = time.monotonic()

    def _rotate(self) -> None:
        self._fh.close()
        # snapshot FIRST: every record in the segments about to be
        # pruned is ≤ the snapshot's seq, so a follower that lost the
        # tail race re-bootstraps without a gap
        self._write_snapshot()
        segs = sorted(glob.glob(os.path.join(glob.escape(self.dir),
                                             f"seg-{self.epoch}-*.jsonl")))
        # the bound counts the live segment about to open: keep the
        # newest (segments - 1) rotated ones
        keep = self.segments - 1
        drop = segs if keep == 0 else segs[:-keep]
        for p in drop:
            # hand rotated segments to the history tier instead of
            # deleting them (query/history.py): the chunk compactor
            # owns their lifetime from here, and prune ordering (chunk
            # written + digest-verified first) guarantees zero loss
            if self.hist is not None:
                self.hist.retire(p)
            else:
                try:
                    os.remove(p)
                except OSError:
                    pass
        segs = segs[len(drop):]
        self._min_seq = (_seg_start(segs[0]) if segs
                         else self._last_seq + 1)
        self._open_segment(self._last_seq + 1)

    def flush(self) -> int:
        """Drain the queue to the segment log; returns records written.
        Called by the publisher thread, close(), and tests (which drive
        the feed synchronously)."""
        wrote = 0
        with self._io_lock:
            if self._fh is None:
                return 0
            while self._q:
                # peek-then-pop: an encode/write/rotate failure leaves
                # the record QUEUED for the next flush — popping first
                # would drop it and punch a permanent seq gap into the
                # feed (every follower would loop bootstrap→gap until
                # the next rotation snapshot finally covered the hole)
                rec = dict(self._q[0])
                eq = rec.pop("_eq", None)
                ea = rec.pop("_ea", 0.0)
                rec["t"] = round(time.time(), 3)
                if eq is not None:
                    # full precision, no rounding: the telescoping
                    # residual is exactly 0 only if these floats
                    # round-trip bit-exact through the feed
                    rec["pt"] = [eq, self.clock(), ea]
                line = dumps(rec) + "\n"
                if (self._fh_bytes and
                        self._fh_bytes + len(line) > self.seg_bytes):
                    self._rotate()
                self._fh.write(line)
                self._fh_bytes += len(line)
                self._q.popleft()
                self._last_seq = max(self._last_seq, int(rec["seq"]))
                wrote += 1
                if self._c_published is not None:
                    self._c_published.inc()
            if wrote:
                self._fh.flush()
            if wrote or time.monotonic() - self._meta_beat >= 1.0:
                # heartbeat even when idle: followers must be able to
                # tell "quiet writer" from "dead writer"
                try:
                    self._write_meta()
                except OSError as e:
                    log.warning("repl meta write failed: %s", e)
        return wrote

    def _run(self) -> None:
        while not self._stop.wait(self.flush_s):
            try:
                self.flush()
            except Exception:
                log.exception("replication feed flush failed")

    def close(self) -> None:
        """Final drain + a ``closed`` meta marker (planned shutdown:
        replicas keep serving the last state without alarming on feed
        staleness the way they would for a vanished writer)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        try:
            self.flush()
        except Exception:
            log.exception("replication feed final flush failed")
        with self._io_lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError as e:
                    # never raise out of close(): the runtime's
                    # teardown finally still has work to do after us
                    log.warning("repl segment close failed: %s", e)
                self._fh = None
                if self.hist is not None:
                    # clean shutdown completes the history: snapshot
                    # FIRST (so a late follower still catches up
                    # without the retired tail), then retire the live
                    # segment into the durable log
                    try:
                        self._write_snapshot()
                        self.hist.retire(self._fh_path)
                        self._min_seq = self._last_seq + 1
                    except OSError as e:
                        log.warning("history tail retire failed: %s",
                                    e)
            try:
                self._write_meta(closed=True)
            except OSError as e:
                log.warning("repl close meta write failed: %s", e)


def _seg_start(path: str) -> int:
    try:
        return int(os.path.basename(path).rsplit("-", 1)[1]
                   .split(".", 1)[0])
    except (IndexError, ValueError):
        return 1 << 62


# --------------------------------------------------------------- readers
def read_meta(feed_dir: str) -> dict:
    """The feed header; {} when absent/corrupt (never raises — the
    same contract as every channel read)."""
    try:
        with open(os.path.join(feed_dir, META), encoding="utf-8") as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) and d.get("epoch") else {}
    except (OSError, ValueError):
        return {}


def read_snapshot(feed_dir: str, epoch: str) -> dict | None:
    """The epoch's snapshot ({"epoch", "seq", "state"}) or None."""
    try:
        with open(os.path.join(feed_dir, f"snapshot-{epoch}.json"),
                  encoding="utf-8") as fh:
            d = loads(fh.read())
    except (OSError, ValueError):
        return None
    if not isinstance(d, dict) or d.get("epoch") != epoch:
        return None
    return d


def read_records(feed_dir: str, epoch: str, since: int,
                 max_n: int = 512) -> list:
    """Decoded feed records with seq > ``since``, in seq order, capped
    at ``max_n``.  A torn tail line (mid-append read) stops the scan —
    the next poll completes it.  Stale-epoch segments never match the
    glob, so a restarted writer's old tail is unreachable."""
    segs = sorted(glob.glob(os.path.join(
        glob.escape(feed_dir), f"seg-{glob.escape(epoch)}-*.jsonl")))
    # start at the newest segment that can contain since+1
    starts = [_seg_start(p) for p in segs]
    first = 0
    for i, s in enumerate(starts):
        if s <= since + 1:
            first = i
    out: list = []
    for p in segs[first:]:
        try:
            with open(p, encoding="utf-8") as fh:
                raw = fh.read()
        except OSError:
            continue
        for line in raw.splitlines():
            if not line:
                continue
            # cheap prefilter: a caught-up follower re-reads the live
            # segment every poll tick, and fully JSON-decoding
            # thousands of already-applied lines just to discard them
            # on seq is the dominant steady-state cost — records are
            # written {"kind": ..., "seq": N, ...}, so the seq parses
            # out of the prefix without touching the doc payload
            pos = line.find('"seq":')
            if pos > 0:
                end = line.find(",", pos + 6)
                try:
                    if int(line[pos + 6:end if end > 0 else None]) \
                            <= since:
                        continue
                except ValueError:
                    pass  # odd framing: fall through to the full parse
            try:
                rec = loads(line)
            except ValueError:
                # torn tail of the live segment; retry next poll
                return out
            if not isinstance(rec, dict):
                continue
            if int(rec.get("seq", 0)) <= since:
                continue
            out.append(rec)
            if len(out) >= max_n:
                return out
    return out


class FileFeedSource:
    """Same-host transport: tail the feed directory directly."""

    def __init__(self, feed_dir: str):
        self.dir = feed_dir

    def meta(self) -> dict:
        return read_meta(self.dir)

    def snapshot(self, epoch: str) -> dict | None:
        return read_snapshot(self.dir, epoch)

    def records(self, epoch: str, since: int, max_n: int = 512) -> list:
        return read_records(self.dir, epoch, since, max_n)


class HttpFeedSource:
    """Remote transport: the writer's serve app re-exposes the feed at
    /api/repl/* (serve/api.py); this polls it over plain TCP.  Errors
    raise to the follower, which counts them and backs off.  Each poll
    is one urllib request — a fresh connection per call — so the feed
    endpoints work identically behind either serve core (the epoll
    core, like wsgiref, answers HTTP/1.0 close-per-request; nothing
    here assumes keep-alive)."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _get(self, path: str):
        import urllib.request

        req = urllib.request.Request(self.base + path)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return loads(r.read().decode("utf-8"))

    def meta(self) -> dict:
        d = self._get("/api/repl/meta")
        return d if isinstance(d, dict) and d.get("epoch") else {}

    def snapshot(self, epoch: str) -> dict | None:
        from urllib.parse import quote

        try:
            d = self._get(f"/api/repl/snapshot?epoch={quote(epoch)}")
        except OSError:
            return None
        if not isinstance(d, dict) or d.get("epoch") != epoch:
            return None
        return d

    def records(self, epoch: str, since: int, max_n: int = 512) -> list:
        from urllib.parse import quote

        d = self._get(f"/api/repl/feed?epoch={quote(epoch)}"
                      f"&since={int(since)}&max={int(max_n)}")
        recs = d.get("records") if isinstance(d, dict) else None
        return recs if isinstance(recs, list) else []


def feed_source(feed: str):
    """``HEATMAP_REPL_FEED`` value -> transport: an http(s):// URL gets
    the TCP transport, anything else is a same-host directory."""
    if feed.startswith("http://") or feed.startswith("https://"):
        return HttpFeedSource(feed)
    return FileFeedSource(feed)


# --------------------------------------------------------------- follower
class ReplicaViewFollower:
    """Drives a replica-mode ``TileMatView`` from a feed source.

    Snapshot-then-tail: bootstrap from the epoch's snapshot, then apply
    records through ``TileMatView.replica_apply`` — the same mutation
    path the writer's own applies take, so every serving surface works
    unchanged on the replica.  Re-bootstraps on: epoch change (writer
    restart — the stale tail is rejected wholesale), falling behind the
    oldest retained segment, or a view seq that moved underneath us
    (the store-scan fallback touched the view while we were unhealthy).

    Catch-up failures retry with exponential backoff, and /healthz
    stays DEGRADED until the first snapshot applies — a replica must
    never report ok-but-empty (r9 satellite)."""

    def __init__(self, view, source, poll_s: float = 0.2,
                 registry=None, clock=time.time, audit=None,
                 hist_source=None, delivery=None):
        self.view = view
        self.source = source
        self.poll_s = max(0.01, float(poll_s))
        self.clock = clock
        # delivery lineage (obs.delivery): when the writer stamped
        # ``pt`` into a record (HEATMAP_DELIVERY=1), hand the tracker
        # the record's upstream stamps plus this replica's receipt and
        # apply times — receipt is stamped once per fetched BATCH
        # (receipt of a change, the PR 8 skew anchor), apply per record.
        self.delivery = delivery
        # space-time history cold-start backfill (query/history.py):
        # after every snapshot bootstrap, pre-snapshot windows still
        # inside their TTL are restored into the view from the chunk
        # store — a writer restart that shrank the snapshot no longer
        # silently narrows this replica's history.  The pending flag
        # keeps retrying while the bootstrapped view is still empty (a
        # fresh writer's boot snapshot has no grids to anchor on yet).
        self.hist_source = hist_source
        self._backfill_pending = False
        self._backfill_tries = 0
        # integrity observatory (obs.audit, HEATMAP_AUDIT=1): per
        # applied record, recompute this replica's own (grid, window)
        # digest and verify it against the writer's published ``dg`` —
        # a corrupted segment record or diverged replica is detected
        # within ONE seq advance, not at the next full resync.
        self.audit = audit
        self.epoch: str | None = None
        self.applied = 0
        self.synced = False
        self.closed_feed = False
        self._need_resync = False
        self._last_seq_seen = 0
        self._last_rec_t: float | None = None
        self._meta_updated: float | None = None
        # staleness is anchored to the LOCAL monotonic receipt time of
        # a meta heartbeat CHANGE, never to the writer's wall clock —
        # on the cross-host HTTP transport a skewed writer clock must
        # not mark a perfectly synced replica permanently unhealthy
        self._meta_seen_mono: float | None = None
        self._backoff = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.c_applied = self.c_snapshots = self.c_errors = None
        self.c_fallback = self.c_backfill = None
        self._g_lag = self._g_lag_s = self._g_synced = None
        if registry is not None:
            self.c_applied = registry.counter(
                "heatmap_repl_applied_total",
                "replication feed records applied to this replica's "
                "materialized view")
            self.c_snapshots = registry.counter(
                "heatmap_repl_snapshot_loads_total",
                "full snapshot bootstraps (first catch-up, writer "
                "epoch change, log-horizon overrun, post-fallback "
                "resync)")
            self.c_errors = registry.counter(
                "heatmap_repl_errors_total",
                "replication catch-up attempts that failed (feed "
                "unreadable, transport error, missing snapshot) and "
                "were retried with backoff")
            self.c_fallback = registry.counter(
                "heatmap_repl_fallback_total",
                "requests served through the demoted store-scan "
                "fallback because the replication follower was not "
                "synced or its feed went stale — 0 in a healthy "
                "replicated fleet")
            self._g_lag = registry.gauge(
                "heatmap_repl_seq_lag",
                "view seqs the replica is behind the writer's "
                "published feed head")
            self._g_lag_s = registry.gauge(
                "heatmap_repl_lag_seconds",
                "replication lag in seconds: 0 when caught up to a "
                "fresh feed, else the age of the newest applied record")
            self._g_synced = registry.gauge(
                "heatmap_repl_synced",
                "1 once the first snapshot applied (until then the "
                "replica reports degraded, never ok-but-empty)")
            self.c_backfill = registry.counter(
                "heatmap_hist_backfill_total",
                "pre-snapshot windows cold-start backfilled into this "
                "replica's view from the space-time history chunks "
                "(query/history.py) after a snapshot bootstrap")

    # ------------------------------------------------------------- state
    def seq_lag(self) -> int:
        return max(0, self._last_seq_seen - self.applied)

    def lag_s(self) -> float:
        """0 when fully caught up; while behind, how far the replica's
        content trails the writer — a WRITER-clock difference (feed
        head publish time minus the newest applied record's publish
        time), so cross-host clock skew cancels out."""
        if self.applied >= self._last_seq_seen:
            return 0.0
        if self._meta_updated is None:
            return float("inf")
        anchor = self._last_rec_t
        if anchor is None:
            return float("inf")
        return max(0.0, self._meta_updated - anchor)

    def feed_age_s(self) -> float | None:
        """Seconds since a meta heartbeat CHANGE was last observed, on
        the follower's own monotonic clock (skew-immune)."""
        if self._meta_seen_mono is None:
            return None
        return max(0.0, time.monotonic() - self._meta_seen_mono)

    def healthy(self) -> bool:
        """Synced and the feed is fresh (or cleanly closed) — the gate
        for serving from the replica WITHOUT the store-scan fallback.
        A lagging-but-alive feed stays healthy here (the replica's
        bounded-stale view beats a store scan that would fork its seq
        stream); the lag SLO degrades /healthz instead."""
        if not self.synced:
            return False
        if self.closed_feed:
            return True
        age = self.feed_age_s()
        return age is not None and age <= fleet_max_age_s()

    def healthz_checks(self, lag_budget_s: float) -> tuple[dict, bool]:
        """({check: ...}, degraded) for /healthz: not-synced degrades
        (never ok-but-empty), replication lag past the SLO degrades,
        and a stale (not closed) feed degrades."""
        checks: dict = {}
        degraded = False
        checks["repl_synced"] = {"value": bool(self.synced),
                                 "ok": bool(self.synced)}
        degraded |= not self.synced
        lag = self.lag_s()
        ok = lag <= lag_budget_s
        checks["repl_lag_s"] = {
            "value": round(lag, 3) if lag != float("inf") else "inf",
            "budget": lag_budget_s, "ok": ok,
            "seq_lag": self.seq_lag()}
        degraded |= not ok
        age = self.feed_age_s()
        if age is not None and not self.closed_feed:
            budget = fleet_max_age_s()
            ok = age <= budget
            checks["repl_feed_age_s"] = {"value": round(age, 3),
                                         "budget": budget, "ok": ok}
            degraded |= not ok
        return checks, degraded

    # ------------------------------------------------------------- drive
    def step(self, max_n: int = 512) -> int:
        """One catch-up round; returns records applied.  Raises on feed
        trouble (the thread loop counts + backs off; tests drive this
        synchronously)."""
        meta = self.source.meta()
        if not meta:
            raise OSError("replication feed has no readable meta")
        upd = meta.get("updated_unix")
        if upd != self._meta_updated or self._meta_seen_mono is None:
            self._meta_seen_mono = time.monotonic()
        self._meta_updated = upd
        self.closed_feed = bool(meta.get("closed"))
        self._last_seq_seen = max(self._last_seq_seen
                                  if meta.get("epoch") == self.epoch
                                  else 0,
                                  int(meta.get("last_seq", 0)))
        if (meta.get("epoch") != self.epoch or self._need_resync
                or self.view.seq != self.applied):
            snap = self.source.snapshot(meta["epoch"])
            if snap is None:
                raise OSError(f"no snapshot for epoch {meta['epoch']!r}")
            self.view.replica_reset(snap["state"])
            self.epoch = snap["epoch"]
            self.applied = int(snap["state"].get("seq", 0))
            # the snapshot is as fresh as the meta we just read: seed
            # the lag anchor so a just-bootstrapped-but-behind replica
            # reports a finite lag instead of flapping on "unknown"
            self._last_rec_t = self._meta_updated
            self._need_resync = False
            self.synced = True
            if self.c_snapshots is not None:
                self.c_snapshots.inc()
            log.info("replica bootstrapped from snapshot: epoch=%s "
                     "seq=%d", self.epoch, self.applied)
            self._backfill_pending = self.hist_source is not None
            self._backfill_tries = 0
        min_seq = int(meta.get("min_seq", 1))
        if self.applied + 1 < min_seq and self._last_seq_seen > self.applied:
            # fell behind the retained log: records we need were
            # pruned — the rotation-time snapshot covers them
            self._need_resync = True
            raise OSError(f"behind the feed horizon (applied "
                          f"{self.applied} < min {min_seq}); "
                          f"re-bootstrapping")
        n = 0
        recs = self.source.records(self.epoch, self.applied, max_n)
        if self.delivery is not None and not isinstance(recs, list):
            recs = list(recs)
        # receipt stamp: once per fetched batch, the moment the records
        # are in hand — the feed_transit leg anchors to receipt of a
        # CHANGE (PR 8 skew discipline), so every record in the batch
        # shares this rx
        t_rx = self.clock() if (self.delivery is not None and recs) \
            else None
        for rec in recs:
            # feed seqs are DENSE within an epoch (every view seq
            # advance publishes exactly one record), so a gap here
            # means records were lost (pruned mid-read, corrupt line):
            # applying past it would silently diverge — re-bootstrap
            if int(rec.get("seq", 0)) != self.applied + 1:
                self._need_resync = True
                raise OSError(
                    f"feed gap: expected seq {self.applied + 1}, got "
                    f"{rec.get('seq')}; re-bootstrapping from snapshot")
            if self.view.seq != self.applied:
                # someone else (a late store-scan fallback racing the
                # first bootstrap) claimed a seq under us: replica_apply
                # would silently skip the writer's record for that seq
                # and the divergence would become undetectable — resync
                self._need_resync = True
                raise OSError("view seq forked under the follower; "
                              "re-bootstrapping from snapshot")
            self.view.replica_apply(rec)
            self.applied = max(self.applied, int(rec.get("seq", 0)))
            if self.audit is not None:
                self.audit.add("repl_applied")
                self.audit.verify_record(self.view, rec)
            t = rec.get("t")
            if isinstance(t, (int, float)):
                self._last_rec_t = t
            if self.delivery is not None and "pt" in rec:
                self.delivery.record_applied(
                    int(rec.get("seq", 0)), rec.get("pt"), t_rx,
                    self.clock())
            n += 1
            if self.c_applied is not None:
                self.c_applied.inc()
        self._last_seq_seen = max(self._last_seq_seen, self.applied)
        if self._backfill_pending:
            # AFTER the tail applies: additive only (never touches
            # latest/seq), and a failure must not fail the catch-up
            # round that just succeeded.  Stays pending until the view
            # has at least one anchorable grid — a fresh writer's boot
            # snapshot is empty, and its first windows arrive by tail.
            try:
                n_bf, anchored = self._backfill()
                self._backfill_tries += 1
                # bounded retries: a chunk store holding only grids
                # this feed never serves (relabeled resolutions) must
                # not rescan the full index on every poll forever
                if anchored or self._backfill_tries >= 20:
                    self._backfill_pending = False
                if n_bf:
                    log.info("replica backfilled %d pre-snapshot "
                             "window(s) from history chunks", n_bf)
            except Exception:  # noqa: BLE001 - history is best-effort here
                # a TRANSIENT index/chunk read failure keeps the
                # backfill pending (retried next poll, same bounded
                # tries) — one connection reset at bootstrap must not
                # silently narrow the replica's history for good
                self._backfill_tries += 1
                if self._backfill_tries >= 20:
                    self._backfill_pending = False
                log.warning("history backfill attempt failed (retrying"
                            " up to %d times)",
                            20 - self._backfill_tries, exc_info=True)
        self._gauges()
        return n

    def _backfill(self) -> tuple[int, bool]:
        """Install pre-snapshot, still-inside-TTL windows from the
        history chunk store into the replica view (additive: no seq
        advance, no hooks, latest window untouched).  Returns (windows
        installed — counted in ``heatmap_hist_backfill_total`` —,
        anchored: whether the view had any grid to backfill against)."""
        if self.hist_source is None:
            return 0, True
        from heatmap_tpu.query.history import decode_chunk

        now = self.clock()
        anchored = False
        by_gw: dict = {}
        for meta in self.hist_source.index():
            grid = meta.get("grid")
            if not grid:
                continue
            for ws_s, wm in (meta.get("windows") or {}).items():
                try:
                    ws = int(ws_s)
                except (TypeError, ValueError):
                    continue
                stale = wm.get("stale")
                if stale is not None and stale <= now:
                    continue  # would evict on first read anyway
                by_gw.setdefault((grid, ws), []).append(meta)
        installed = 0
        for (grid, ws), metas in sorted(by_gw.items()):
            latest = self.view.latest_ws_of(grid)
            if latest is None:
                continue
            anchored = True
            if ws >= latest or self.view.has_window(grid, ws):
                continue
            cells: dict = {}
            stale = None
            for meta in metas:
                buf = self.hist_source.chunk_bytes(meta.get("name"))
                if buf is None:
                    continue
                try:
                    _m, windows = decode_chunk(buf)
                except ValueError:
                    continue
                part = windows.get(ws)
                if part is not None:
                    for d in part["docs"]:
                        cells[d.get("cellId")] = d
                wm = (meta.get("windows") or {}).get(str(ws)) or {}
                if wm.get("stale") is not None:
                    stale = wm["stale"]
            if cells and self.view.backfill_window(
                    grid, ws, list(cells.values()), stale_ts=stale):
                installed += 1
                if self.c_backfill is not None:
                    self.c_backfill.inc()
        return installed, anchored or not by_gw

    def _gauges(self) -> None:
        if self._g_lag is not None:
            self._g_lag.set(self.seq_lag())
        if self._g_lag_s is not None:
            lag = self.lag_s()
            self._g_lag_s.set(lag if lag != float("inf") else -1.0)
        if self._g_synced is not None:
            self._g_synced.set(1 if self.synced else 0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                n = self.step()
                self._backoff = 0.0
                # a full page means we're mid-catch-up: keep draining
                wait = 0.0 if n >= 512 else self.poll_s
            except Exception as e:
                if self.c_errors is not None:
                    self.c_errors.inc()
                self._backoff = min(5.0, (self._backoff or 0.1) * 2)
                wait = self._backoff
                log.warning("replication catch-up failed (retry in "
                            "%.1fs): %s", wait, e)
                self._gauges()
            if wait:
                self._stop.wait(wait)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="repl-follower")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
