"""query — the materialized tile-view tier between the sink and the API.

The streaming fold writes tiles through the sink; until this package the
read path re-rendered the full city-scale FeatureCollection from the
Store on every poll (~0.5 s/core for 6.4k tiles), shielded only by a
1 s TTL cache.  CheetahGIS (arXiv:2511.09262) and GeoFlink
(arXiv:2004.03352) both separate the streaming fold from an
incrementally-maintained spatial query layer; this is ours:

- ``matview``  — ``TileMatView``: an in-memory per-grid view of
  (windowStart, cell) → tile doc, applied on the AsyncWriter thread
  AFTER each sink write has durably applied (the view never exposes
  rows that aren't in the store), with a monotonic ``view_seq``, a
  bounded per-grid changelog powering ``/api/tiles/delta`` and the SSE
  stream, and lazy staleAt window eviction matching the store's TTL
  semantics.  ``StoreViewRefresher`` rebuilds the same view by Store
  scan + version polling for serve-only processes (no runtime
  in-process).
- ``pyramid``  — incremental multi-resolution rollup: base-cell deltas
  propagate to coarser H3 parent cells (count sums, count-weighted
  speed means and centroids) so ``?res=`` zoom-out queries are
  O(changed cells), never a window rebuild.
- ``repl``     — delta-log view replication: the writer publishes the
  view's mutation stream (file-backed segment log + snapshots, epoch
  nonce per boot) and ``ReplicaViewFollower`` drives a replica-mode
  ``TileMatView`` in any number of serve workers with zero
  steady-state store reads; ``StoreViewRefresher`` is demoted to a
  counted, healthz-warning fallback on replicas.
- ``geom``     — bbox/polygon → H3 cell-set compilation for standing
  queries: coarse fully-interior parents + a boundary sliver at snap
  res, so hot-path membership is one or two set lookups.
- ``continuous`` — the standing-query engine (GeoFlink-style
  continuous spatial queries): range/topk subscriptions, geofence
  enter/exit and threshold alerts, evaluated O(changed) off the
  view's mutation stream via an inverted cell index — the replica
  fleet's horizontally-scaling query tier at zero writer cost.
"""

from heatmap_tpu.query.matview import (  # noqa: F401
    StoreViewRefresher,
    TileMatView,
)
from heatmap_tpu.query.pyramid import Pyramid, cell_to_parent  # noqa: F401
