"""Incremental multi-resolution rollup over one grid's tile view.

The UI zooms out; the configured pyramid only goes as fine as the
streamed resolutions.  Re-aggregating a whole window per request would
be the same O(city) rebuild the matview exists to kill, so the rollup
is maintained INCREMENTALLY: every base-cell upsert the view applies is
turned into a delta (new minus old contribution) and propagated to the
cell's H3 parent at each maintained coarser resolution — O(levels) per
changed cell, O(changed) per batch, never a window scan.

What rolls up, and what provably can't:
- ``count`` sums exactly.
- ``avgSpeedKmh`` and the centroid are count-weighted means, so their
  weighted SUMS add exactly and the mean recombines at render time.
- ``p95SpeedKmh``/``stddevSpeedKmh`` do NOT combine from per-cell
  aggregates (quantiles and variances need the raw moments the sink
  rows don't carry per parent), so rollup tiles omit them — documented
  in the endpoint contract rather than silently wrong.

Parent math: an H3 index's parent is the index itself with the
resolution field lowered and the now-unused digits set to the invalid
marker (7) — pure bit surgery, no geometry, exact for pentagons too.
"""

from __future__ import annotations

import datetime as dt

RES_SHIFT = 52
RES_MASK = 0xF << RES_SHIFT


def cell_to_parent(cell: int, parent_res: int) -> int:
    """H3 parent of ``cell`` at ``parent_res`` (must not exceed the
    cell's own resolution)."""
    res = (cell >> RES_SHIFT) & 0xF
    if parent_res > res:
        raise ValueError(f"parent res {parent_res} finer than cell res {res}")
    out = (cell & ~RES_MASK) | (parent_res << RES_SHIFT)
    for r in range(parent_res + 1, res + 1):
        out |= 0x7 << (3 * (15 - r))
    return out


class Pyramid:
    """Per-grid rollup state: {res: {window_start_epoch: {parent_cell_int:
    [count, speed_wsum, lat_wsum, lon_wsum]}}}.

    Not thread-safe by itself — the owning TileMatView serializes every
    call under its own lock."""

    __slots__ = ("resolutions", "_agg")

    def __init__(self, base_res: int, levels: int):
        lo = max(0, base_res - max(0, levels))
        self.resolutions = tuple(range(lo, base_res))
        self._agg: dict[int, dict[int, dict[int, list]]] = {
            r: {} for r in self.resolutions}

    def apply(self, ws: int, cell: int, old: dict | None, new: dict) -> None:
        """Propagate one base-cell upsert (``old`` is the previously
        visible doc for the same (window, cell), or None)."""
        dc = int(new.get("count", 0)) - (int(old.get("count", 0)) if old else 0)
        dspeed = self._wsum(new, "avgSpeedKmh") - self._wsum(old, "avgSpeedKmh")
        dlat = self._cwsum(new, 1) - self._cwsum(old, 1)
        dlon = self._cwsum(new, 0) - self._cwsum(old, 0)
        if not dc and not dspeed and not dlat and not dlon:
            return
        for res in self.resolutions:
            parent = cell_to_parent(cell, res)
            wins = self._agg[res].setdefault(ws, {})
            a = wins.get(parent)
            if a is None:
                a = wins[parent] = [0, 0.0, 0.0, 0.0]
            a[0] += dc
            a[1] += dspeed
            a[2] += dlat
            a[3] += dlon
            if a[0] <= 0:
                del wins[parent]

    @staticmethod
    def _wsum(doc: dict | None, key: str) -> float:
        if doc is None:
            return 0.0
        return float(doc.get(key, 0.0)) * int(doc.get("count", 0))

    @staticmethod
    def _cwsum(doc: dict | None, axis: int) -> float:
        if doc is None:
            return 0.0
        try:
            coord = doc["centroid"]["coordinates"][axis]
        except (KeyError, TypeError, IndexError):
            return 0.0
        return float(coord) * int(doc.get("count", 0))

    def drop_window(self, ws: int) -> None:
        for wins in self._agg.values():
            wins.pop(ws, None)

    def docs(self, res: int, ws: int, window_end: dt.datetime | None,
             window_start: dt.datetime | None) -> list[dict]:
        """Synthesized rollup tile docs for one (res, window), shaped so
        the serving renderer's ``_tile_props`` consumes them unchanged.
        p95/stddev are intentionally absent (non-combinable)."""
        from heatmap_tpu.hexgrid import h3_to_string

        wins = self._agg.get(res)
        if wins is None:
            raise KeyError(res)
        out = []
        for parent, (c, sw, slat, slon) in wins.get(ws, {}).items():
            out.append({
                "cellId": h3_to_string(parent),
                "count": int(c),
                "avgSpeedKmh": sw / c,
                "windowStart": window_start,
                "windowEnd": window_end,
                "centroid": {"type": "Point",
                             "coordinates": [slon / c, slat / c]},
            })
        return out
