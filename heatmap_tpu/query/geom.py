"""Geometry → H3 cell-set compilation for continuous spatial queries.

A standing query (bbox range subscription, polygon geofence) is
registered ONCE and then evaluated against every view mutation forever,
so the geometry work happens exactly once here: the region is compiled
to an H3 cell set at the grid's snap resolution, and membership of a
changed cell is thereafter one or two set lookups — never a
point-in-polygon test on the hot path.

The compiled set is two-tier, riding the same parent bit surgery the
pyramid rollup uses (query.pyramid.cell_to_parent):

- ``parents`` — coarse cells (``coarse_res``) whose entire boundary
  lies inside the region: every snap-res cell under such a parent is a
  member, so city-scale interiors compress to a handful of entries.
- ``cells``   — the boundary sliver at snap res: cells touched by the
  region whose coarse parent is NOT fully interior.

``CellSet.contains`` is therefore ``cell in cells or parent(cell) in
parents`` — O(1), and the engine's inverted index (cell → query ids)
keys on the same coarse parent, so a view mutation touches only the
queries whose compiled set can possibly contain the changed cell.

Membership semantics: a cell belongs to the region iff it contains a
sample point of a lattice laid over the region at ~0.8 hex-edge
spacing (corners/vertices always sampled).  That makes a zero-area
bbox compile to exactly the one cell containing the point (the natural
point-geofence), keeps tiny fences at a few cells, and leaves no holes
in large regions (the lattice step is well under the minimal hex
width).  Edge cells with slim overlap may fall either way — the
compiled set IS the query's definition, which is what the differential
replay invariant pins; geometric perfection at the sliver is not part
of the contract.

Antimeridian: a bbox whose ``min_lon > max_lon`` is taken as crossing
the antimeridian and compiled as the union of the two straddling
boxes.  (The serving-tier ``bbox=`` parser for one-shot topk rejects
that shape; standing queries accept it here.)
"""

from __future__ import annotations

import math

from heatmap_tpu.query.pyramid import cell_to_parent

# Mean H3 hexagon edge length per resolution, meters (the published H3
# table; only used to size the sampling lattice, so mean is fine — the
# 0.8 factor keeps the step under the minimal hex width everywhere).
EDGE_M = (1107712.591, 418676.0055, 158244.6558, 59810.85794,
          22606.3794, 8544.408276, 3229.482772, 1220.629759,
          461.354684, 174.375668, 65.907807, 24.910561,
          9.415526, 3.559893, 1.348575, 0.509713)

_M_PER_DEG_LAT = 111320.0


class CellSet:
    """One compiled region: coarse interior parents + snap-res sliver.

    Immutable after construction; ``contains`` is the only hot-path
    call.  ``index_keys`` are the coarse-res cells the engine's
    inverted index files this query under (every member cell's parent
    is one of them, so index lookup never misses)."""

    __slots__ = ("res", "coarse_res", "parents", "cells")

    def __init__(self, res: int, coarse_res: int, parents, cells):
        self.res = int(res)
        self.coarse_res = int(coarse_res)
        self.parents = frozenset(parents)
        self.cells = frozenset(cells)

    def contains(self, cell: int) -> bool:
        return (cell in self.cells
                or cell_to_parent(cell, self.coarse_res) in self.parents)

    def index_keys(self) -> frozenset:
        return self.parents | frozenset(
            cell_to_parent(c, self.coarse_res) for c in self.cells)

    def size(self) -> int:
        """Compiled entries held (parents compress whole interiors, so
        this is the memory/metric figure, not the member-cell count)."""
        return len(self.parents) + len(self.cells)


def _wrap_lon(lon: float) -> float:
    while lon > 180.0:
        lon -= 360.0
    while lon < -180.0:
        lon += 360.0
    return lon


def point_in_ring(lon: float, lat: float, ring) -> bool:
    """Ray-casting point-in-polygon on plain lon/lat (the polygon is
    registered in the same coordinate plane the UI draws in; small
    regions only — no great-circle edges)."""
    inside = False
    n = len(ring)
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        if (y1 > lat) != (y2 > lat):
            xin = x1 + (lat - y1) / (y2 - y1) * (x2 - x1)
            if lon < xin:
                inside = not inside
    return inside


def _lattice(lo_lon: float, lo_lat: float, hi_lon: float, hi_lat: float,
             res: int, max_samples: int):
    """Sample points covering one non-wrapping bbox: a lattice at
    ~0.8 hex-edge spacing, corners included.  Degenerate (zero-area)
    boxes collapse to their corner point(s)."""
    step_m = 0.8 * EDGE_M[res]
    dlat = step_m / _M_PER_DEG_LAT
    # lon degrees shrink with latitude; size the step at the widest
    # (most equatorward) latitude of the box so spacing never opens up
    coslat = max(0.05, math.cos(math.radians(
        min(abs(lo_lat), abs(hi_lat)))))
    dlon = step_m / (_M_PER_DEG_LAT * coslat)
    n_lat = max(1, int(math.ceil((hi_lat - lo_lat) / dlat)) + 1)
    n_lon = max(1, int(math.ceil((hi_lon - lo_lon) / dlon)) + 1)
    if n_lat * n_lon > max_samples:
        raise ValueError(
            f"region too large to compile at res {res}: "
            f"{n_lat * n_lon} samples exceeds the {max_samples} budget "
            f"(register against a coarser grid or shrink the region)")
    for i in range(n_lat):
        lat = hi_lat if n_lat == 1 else lo_lat + (hi_lat - lo_lat) \
            * i / (n_lat - 1)
        for j in range(n_lon):
            lon = hi_lon if n_lon == 1 else lo_lon + (hi_lon - lo_lon) \
                * j / (n_lon - 1)
            yield lat, lon


def _snap_many(points, res: int) -> set:
    from heatmap_tpu.hexgrid import host

    T = host.tables()
    out: set = set()
    for lat, lon in points:
        lat = max(-90.0, min(90.0, lat))
        out.add(host.latlng_to_cell_int(
            math.radians(lat), math.radians(_wrap_lon(lon)), res, T))
    return out


def _promote(cells: set, res: int, coarse_res: int,
             inside_fn) -> tuple[set, set]:
    """Split sampled snap cells into fully-interior coarse parents and
    the boundary sliver: a parent is promoted when its centroid and
    every boundary vertex pass ``inside_fn`` — then all its children
    are members and the snap entries compress away."""
    from heatmap_tpu.hexgrid import host

    if coarse_res >= res:
        return set(), set(cells)
    by_parent: dict[int, set] = {}
    for c in cells:
        by_parent.setdefault(cell_to_parent(c, coarse_res), set()).add(c)
    parents: set = set()
    sliver: set = set()
    # a fully-interior parent has every child containing a lattice
    # sample (the lattice is denser than the child cells), so a parent
    # with under half its 7^Δ children sampled cannot be interior —
    # skipping the boundary-geometry test there is what keeps a
    # 100k-tiny-fence registration storm (tools/bench_cq.py) cheap
    min_members = (7 ** (res - coarse_res)) // 2
    for p, members in by_parent.items():
        if len(members) < min_members:
            sliver |= members
            continue
        try:
            lat, lng = host.cell_to_latlng(p)
            verts = host.cell_to_boundary(p)
        except Exception:
            sliver |= members
            continue
        if inside_fn(lng, lat) and all(inside_fn(vlng, vlat)
                                       for vlat, vlng in verts):
            parents.add(p)
        else:
            sliver |= members
    return parents, sliver


def _budgeted(cs: CellSet, max_cells: int) -> CellSet:
    """Enforce HEATMAP_CQ_MAX_CELLS on the COMPILED set (parents +
    sliver) — the budget the knob documents; parent promotion means a
    city interior is cheap to hold even when its raw sampling was not
    (the raw cost is bounded separately by ``max_samples``)."""
    if cs.size() > max_cells:
        raise ValueError(
            f"region compiles to {cs.size()} entries at res {cs.res}, "
            f"over the {max_cells} budget (HEATMAP_CQ_MAX_CELLS); "
            f"register against a coarser grid or shrink the region")
    return cs


def compile_bbox(bbox, res: int, coarse_res: int | None = None,
                 max_cells: int = 4096,
                 max_samples: int = 262144) -> CellSet:
    """``(min_lon, min_lat, max_lon, max_lat)`` → CellSet at ``res``.
    ``min_lon > max_lon`` crosses the antimeridian (two-box union);
    ``min_lat > max_lat`` is an error; equal bounds are a legal
    degenerate box (a point compiles to its one containing cell)."""
    lo_lon, lo_lat, hi_lon, hi_lat = (float(v) for v in bbox)
    if not all(map(math.isfinite, (lo_lon, lo_lat, hi_lon, hi_lat))):
        raise ValueError("bbox values must be finite numbers")
    if lo_lat > hi_lat:
        raise ValueError("bbox min_lat exceeds max_lat")
    if not (-90.0 <= lo_lat <= 90.0 and -90.0 <= hi_lat <= 90.0):
        raise ValueError("bbox latitudes must be in [-90, 90]")
    if not (0 <= res <= 15):
        raise ValueError(f"resolution must be in [0, 15], got {res}")
    if coarse_res is None:
        coarse_res = max(0, res - 2)
    boxes = ([(lo_lon, lo_lat, hi_lon, hi_lat)] if lo_lon <= hi_lon
             # antimeridian crossing: the box runs east from lo_lon
             # through 180/-180 to hi_lon
             else [(lo_lon, lo_lat, 180.0, hi_lat),
                   (-180.0, lo_lat, hi_lon, hi_lat)])
    cells: set = set()
    for b in boxes:
        cells |= _snap_many(_lattice(*b, res, max_samples), res)

    def inside(lon: float, lat: float) -> bool:
        lon = _wrap_lon(lon)
        return any(b[0] <= lon <= b[2] and b[1] <= lat <= b[3]
                   for b in boxes)

    parents, sliver = _promote(cells, res, coarse_res, inside)
    return _budgeted(CellSet(res, coarse_res, parents, sliver),
                     max_cells)


def compile_polygon(ring, res: int, coarse_res: int | None = None,
                    max_cells: int = 4096,
                    max_samples: int = 262144) -> CellSet:
    """Closed (or auto-closed) ``[[lon, lat], ...]`` ring → CellSet.
    Vertices always sample in, so a sliver polygon still compiles to
    the cells it actually touches.  Antimeridian-spanning polygons are
    not supported (register two, or use a wrapping bbox)."""
    pts = [(float(lon), float(lat)) for lon, lat in ring]
    if pts and pts[0] == pts[-1]:
        pts = pts[:-1]
    if len(pts) < 3:
        raise ValueError("polygon needs at least 3 distinct vertices")
    for lon, lat in pts:
        if not (math.isfinite(lon) and math.isfinite(lat)
                and -90.0 <= lat <= 90.0 and -180.0 <= lon <= 180.0):
            raise ValueError(f"polygon vertex out of range: "
                             f"({lon}, {lat})")
    if not (0 <= res <= 15):
        raise ValueError(f"resolution must be in [0, 15], got {res}")
    if coarse_res is None:
        coarse_res = max(0, res - 2)
    lo_lon = min(p[0] for p in pts)
    hi_lon = max(p[0] for p in pts)
    lo_lat = min(p[1] for p in pts)
    hi_lat = max(p[1] for p in pts)

    def inside(lon: float, lat: float) -> bool:
        return point_in_ring(lon, lat, pts)

    samples = [(lat, lon) for lat, lon in
               _lattice(lo_lon, lo_lat, hi_lon, hi_lat, res, max_samples)
               if inside(lon, lat)]
    samples.extend((lat, lon) for lon, lat in pts)
    cells = _snap_many(samples, res)
    parents, sliver = _promote(cells, res, coarse_res, inside)
    return _budgeted(CellSet(res, coarse_res, parents, sliver),
                     max_cells)
