"""Space-time history tier: durable compacted log + time-travel queries.

Until this module, serving was latest-only: eviction destroyed every
window that aged out, and the PR 8 repl segment log — an ordered,
epoch/dense-seq, byte-exact-replayable record of every tile mutation —
was deleted at rotation.  This tier stops deleting it and turns the
feed into the system's durable log of record (WarpFlow's immutable
parent-cell x time-bucket columnar chunks, PAPERS.md; GeoFlink's
window semantics motivate serving RANGES, not just instants):

Store layout (``HEATMAP_HIST_DIR``)::

    log/seg-<epoch>-<startseq>.jsonl    rotated repl segments, moved
                                        here (os.replace) instead of
                                        deleted — the raw log of record
    log/snap-<epoch>-<seq>.json         the feed snapshot ADOPTED at
                                        publisher boot and at every
                                        rotation — the replay bases
                                        view-at-seq reconstruction
                                        starts from
    chunks/chunk-<grid>-<parent>-<bucket>.hst
                                        immutable compacted chunks: one
                                        per (grid, H3 parent cell at
                                        HEATMAP_HIST_PARENT_RES, time
                                        bucket of HEATMAP_HIST_BUCKET_S)
    hist-state.json                     compactor watermarks, atomically
                                        rewritten AFTER a flush — the
                                        crash-safety anchor

Chunk format: line 1 is a JSON meta header (grid, parent, bucket,
chunk shape, per-window ``{digest, docs, seq, stale, verified}``),
then one length-prefixed block per window: the PR 14 ``serve/wire.py``
columnar frame (byte-exact doc round-trip) plus two side columns the
serving frame deliberately omits — per-doc centroids (range rollups
need the count-weighted mean position) and per-doc 64-bit content
hashes (``obs.audit.doc_hash``), which make the window digest
incrementally recomputable across a compactor restart.

Crash-safety / zero-loss retention invariant: a raw log segment is
pruned ONLY when (1) every record in it is at or below the persisted
ingest watermark — which is advanced AFTER the chunks covering the
flush are durably written — and (2) no digest mismatch is outstanding,
and (3) the segment has aged past ``HEATMAP_HIST_RETENTION_S``.  A
crash between chunk write and state/prune re-ingests the segments on
restart; re-applying the same records over the chunk-seeded
accumulator is content-idempotent, so nothing is lost and nothing
double-counts.  Digest verification is the PR 12 contract: the writer
publishes its post-apply per-(grid, window) XOR digest inside feed
records (``"dg"``), and the compactor recomputes its own digest from
the accumulated cells per ingested record — compaction is verified
against the live view's books, not trusted.

Read side (:class:`HistoryReader`, served by ``serve/api.py``):
``/api/tiles/range?grid&t0&t1[&res][&fmt=bin]`` (per-window series +
pyramid-math rollup), ``/api/tiles/at?seq=`` (view-at-seq replay from
adopted snapshot + log segments, byte-identical to the live view at
that seq — differential-pinned in tests/test_history.py), and
``/api/tiles/diff?t0&t1`` (day-over-day per-cell deltas).  Replicas
also cold-start BACKFILL pre-snapshot windows from chunks
(query.repl.ReplicaViewFollower), so a writer restart that shrank the
snapshot no longer silently narrows the fleet's history.

Compactor entry point::

    python -m heatmap_tpu.query.history --hist DIR [--feed DIR] [--once]
"""

from __future__ import annotations

import glob
import json
import logging
import os
import struct
import threading
import time

from heatmap_tpu.obs.audit import doc_hash
from heatmap_tpu.obs.xproc import atomic_write_json
from heatmap_tpu.query import repl as replmod
from heatmap_tpu.query.pyramid import cell_to_parent

log = logging.getLogger(__name__)

STATE = "hist-state.json"
LOG_DIR = "log"
CHUNK_DIR = "chunks"

_BLOCK_WIRE = 0   # window block payload is a serve/wire.py frame
_BLOCK_JSON = 1   # fallback: repl-codec JSON docs (unrepresentable doc)

RES_SHIFT = 52


def _cell_parent_key(cid: str, parent_res: int) -> int:
    """Chunk partition key for one cellId: its H3 parent at
    ``parent_res`` (clamped to the cell's own resolution so coarse
    grids never raise), or 0 for non-H3 cell ids — junk must land in a
    bucket, not break compaction."""
    try:
        cell = int(cid, 16)
        res = (cell >> RES_SHIFT) & 0xF
        return cell_to_parent(cell, min(parent_res, res))
    except (TypeError, ValueError):
        return 0


def _seg_name_parts(path: str) -> tuple[str, int] | None:
    """(epoch, start_seq) of a ``seg-<epoch>-<start>.jsonl`` name."""
    base = os.path.basename(path)
    if not base.startswith("seg-") or not base.endswith(".jsonl"):
        return None
    body = base[4:-6]
    epoch, _, start = body.rpartition("-")
    try:
        return (epoch, int(start)) if epoch else None
    except ValueError:
        return None


def _snap_name_parts(path: str) -> tuple[str, int] | None:
    """(epoch, seq) of a ``snap-<epoch>-<seq>.json`` name."""
    base = os.path.basename(path)
    if not base.startswith("snap-") or not base.endswith(".json"):
        return None
    body = base[5:-5]
    epoch, _, seq = body.rpartition("-")
    try:
        return (epoch, int(seq)) if epoch else None
    except ValueError:
        return None


def _read_segment(path: str) -> list:
    """Decoded records of one sealed segment, in file order.  A torn
    tail line (only possible on an adopted dead-epoch LIVE segment)
    stops the scan — everything before it is intact."""
    try:
        with open(path, encoding="utf-8") as fh:
            raw = fh.read()
    except OSError:
        return []
    out = []
    for line in raw.splitlines():
        if not line:
            continue
        try:
            rec = replmod.loads(line)
        except ValueError:
            break
        if isinstance(rec, dict):
            out.append(rec)
    return out


# ----------------------------------------------------------------- log
class HistoryLog:
    """The durable-log half the feed publisher hands rotated segments
    to (query.repl.DeltaLogPublisher ``hist=``): ``retire`` moves a
    segment into ``log/`` atomically instead of deleting it, and
    ``adopt_snapshot`` copies the rotation/boot snapshot next to it as
    a replay base.  Never raises into the publisher — a full history
    disk degrades to the pre-history delete, loudly."""

    def __init__(self, hist_dir: str):
        self.dir = hist_dir
        self.log_dir = os.path.join(hist_dir, LOG_DIR)
        os.makedirs(self.log_dir, exist_ok=True)

    def retire(self, seg_path: str) -> bool:
        dst = os.path.join(self.log_dir, os.path.basename(seg_path))
        try:
            os.replace(seg_path, dst)
            return True
        except OSError as e:
            log.warning("history retire of %s failed (%s); deleting",
                        seg_path, e)
            try:
                os.remove(seg_path)
            except OSError:
                pass
            return False

    def adopt_snapshot(self, epoch: str, seq: int, payload: dict) -> None:
        """Copy one feed snapshot ({"epoch", "seq", "state"}) into the
        log as ``snap-<epoch>-<seq>.json`` — the base view-at-seq
        replay resets from.  One file per (epoch, seq); rewriting the
        same seq is idempotent."""
        try:
            atomic_write_json(
                os.path.join(self.log_dir,
                             f"snap-{epoch}-{int(seq):012d}.json"),
                payload)
        except OSError as e:
            log.warning("history snapshot adopt failed: %s", e)


# --------------------------------------------------------------- chunks
def encode_chunk(grid: str, parent: int, bucket: int, bucket_s: int,
                 parent_res: int, windows: dict, native=None) -> bytes:
    """One immutable chunk: JSON meta line + per-window blocks.

    ``windows``: {ws: {"docs": [full tile docs, window order],
    "digest": int, "seq": int, "stale": float|None,
    "verified": bool}}.  Docs ride the serve/wire.py columnar frame
    (byte-exact round-trip of every serving-visible field) plus the
    centroid and content-hash side columns."""
    from heatmap_tpu.serve import wire

    meta_w: dict = {}
    body = bytearray()
    for ws in sorted(windows):
        w = windows[ws]
        docs = w["docs"]
        meta_w[str(ws)] = {
            "digest": format(int(w.get("digest", 0)), "016x"),
            "docs": len(docs),
            "seq": int(w.get("seq", 0)),
            "stale": w.get("stale"),
            "verified": bool(w.get("verified", False)),
            "closed": bool(w.get("closed", False)),
            "epoch": w.get("epoch"),
            "rebased": bool(w.get("rebased", False)),
        }
        ws_dt = docs[0]["windowStart"] if docs else None
        block = bytearray()
        try:
            frame = wire.encode("full", int(w.get("seq", 0)), grid,
                                ws_dt, docs, native=native)
            block.append(_BLOCK_WIRE)
        except ValueError:
            # a doc the compact layout cannot represent exactly: the
            # JSON fallback keeps the chunk lossless rather than wrong
            frame = replmod.dumps(docs).encode("utf-8")
            block.append(_BLOCK_JSON)
        block += struct.pack("<I", len(frame))
        block += frame
        # centroid side column: presence bitmap + f64 lon/lat pairs
        bitmap = bytearray((len(docs) + 7) // 8)
        cents = []
        for i, d in enumerate(docs):
            try:
                lon, lat = d["centroid"]["coordinates"]
                lon, lat = float(lon), float(lat)
            except (KeyError, TypeError, ValueError):
                continue
            bitmap[i // 8] |= 1 << (i % 8)
            cents.append((lon, lat))
        block += bytes(bitmap)
        for lon, lat in cents:
            block += struct.pack("<dd", lon, lat)
        # content-hash side column (obs.audit.doc_hash, doc order):
        # what lets a restarted compactor keep the window digest
        # incrementally exact over chunk-seeded cells
        hashes = w.get("hashes")
        for i, d in enumerate(docs):
            h = (hashes.get(d.get("cellId")) if isinstance(hashes, dict)
                 else None)
            block += struct.pack("<Q", int(h if h is not None
                                           else doc_hash(d)))
        body += struct.pack("<I", len(block))
        body += block
    meta = {"v": 1, "grid": grid, "parent": format(parent, "016x"),
            "parent_res": int(parent_res), "bucket": int(bucket),
            "bucket_s": int(bucket_s), "windows": meta_w}
    return json.dumps(meta, separators=(",", ":")).encode("utf-8") \
        + b"\n" + bytes(body)


def decode_chunk(buf: bytes) -> tuple[dict, dict]:
    """(meta, {ws: {"docs": [...], "hashes": {cid: int}}}) — docs carry
    every serving-visible field EXACTLY (wire decode) plus the merged
    centroid; raises ValueError on a malformed chunk."""
    from heatmap_tpu.serve import wire

    nl = buf.find(b"\n")
    if nl < 0:
        raise ValueError("chunk has no meta line")
    meta = json.loads(buf[:nl].decode("utf-8"))
    if not isinstance(meta, dict) or meta.get("v") != 1:
        raise ValueError("unsupported chunk version")
    pos = nl + 1
    windows: dict = {}
    order = sorted(int(ws) for ws in (meta.get("windows") or {}))
    for ws in order:
        if pos + 4 > len(buf):
            raise ValueError("chunk truncated in block header")
        (blen,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        block = buf[pos:pos + blen]
        if len(block) != blen:
            raise ValueError("chunk truncated in window block")
        pos += blen
        kind = block[0]
        (flen,) = struct.unpack_from("<I", block, 1)
        frame = block[5:5 + flen]
        bpos = 5 + flen
        if kind == _BLOCK_WIRE:
            docs = wire.decode(frame)["docs"]
        elif kind == _BLOCK_JSON:
            docs = replmod.loads(frame.decode("utf-8"))
        else:
            raise ValueError(f"unknown chunk block kind {kind}")
        n = len(docs)
        bitmap = block[bpos:bpos + (n + 7) // 8]
        bpos += (n + 7) // 8
        for i, d in enumerate(docs):
            if bitmap[i // 8] & (1 << (i % 8)):
                lon, lat = struct.unpack_from("<dd", block, bpos)
                bpos += 16
                d["centroid"] = {"type": "Point",
                                 "coordinates": [lon, lat]}
        hashes = {}
        for d in docs:
            (h,) = struct.unpack_from("<Q", block, bpos)
            bpos += 8
            hashes[d.get("cellId")] = h
        windows[ws] = {"docs": docs, "hashes": hashes}
    return meta, windows


def _chunk_name(grid: str, parent: int, bucket: int) -> str:
    return f"chunk-{grid}-{parent:016x}-{int(bucket)}.hst"


_CHUNK_NAME_OK = None  # compiled lazily


def chunk_name_ok(name: str) -> bool:
    """Validate a client-supplied chunk name (the /api/hist/chunk
    re-export must never open an attacker-chosen path)."""
    global _CHUNK_NAME_OK
    if _CHUNK_NAME_OK is None:
        import re

        _CHUNK_NAME_OK = re.compile(
            r"^chunk-[A-Za-z0-9_.:\-]{1,64}-[0-9a-f]{16}-\d{1,12}"
            r"\.hst$")
    return bool(_CHUNK_NAME_OK.match(name))


# -------------------------------------------------------------- sources
class FileHistorySource:
    """Same-host chunk access: scan + read the chunk directory.  Chunk
    metas are memoized by (name, size, mtime) — chunks are immutable
    between atomic rewrites, so the memo is exact."""

    def __init__(self, hist_dir: str):
        self.dir = hist_dir
        self.chunk_dir = os.path.join(hist_dir, CHUNK_DIR)
        self._meta_memo: dict = {}

    def index(self) -> list:
        out = []
        for p in sorted(glob.glob(os.path.join(
                glob.escape(self.chunk_dir), "chunk-*.hst"))):
            name = os.path.basename(p)
            if not chunk_name_ok(name):
                continue
            try:
                st = os.stat(p)
                key = (st.st_size, st.st_mtime_ns)
                memo = self._meta_memo.get(name)
                if memo is not None and memo[0] == key:
                    out.append(memo[1])
                    continue
                with open(p, "rb") as fh:
                    meta = json.loads(
                        fh.readline().decode("utf-8"))
            except (OSError, ValueError):
                continue
            if not isinstance(meta, dict):
                continue
            meta = dict(meta)
            meta["name"] = name
            meta["bytes"] = st.st_size
            meta["mtime_ns"] = st.st_mtime_ns
            if len(self._meta_memo) >= 4096:
                self._meta_memo.pop(next(iter(self._meta_memo)))
            self._meta_memo[name] = (key, meta)
            out.append(meta)
        return out

    def chunk_bytes(self, name: str) -> bytes | None:
        if not chunk_name_ok(name):
            return None
        try:
            with open(os.path.join(self.chunk_dir, name), "rb") as fh:
                return fh.read()
        except OSError:
            return None


class HttpHistorySource:
    """Remote chunk access over the writer's /api/hist/* re-export
    (serve/api.py) — what a remote replica backfills from."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _get(self, path: str) -> bytes:
        import urllib.request

        req = urllib.request.Request(self.base + path)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return r.read()

    def index(self) -> list:
        """Raises OSError/ValueError on transport or framing trouble —
        callers must be able to tell a failed read from a genuinely
        empty store (a transient error must not cancel a replica's
        one-shot backfill)."""
        d = json.loads(self._get("/api/hist/index").decode("utf-8"))
        chunks = d.get("chunks") if isinstance(d, dict) else None
        return chunks if isinstance(chunks, list) else []

    def chunk_bytes(self, name: str) -> bytes | None:
        import urllib.error
        from urllib.parse import quote

        if not chunk_name_ok(name):
            return None
        try:
            return self._get(f"/api/hist/chunk?name={quote(name)}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None  # legitimately pruned underneath us
            raise


def history_source(spec: str):
    """``HEATMAP_HIST_DIR``/feed value -> source: an http(s):// URL
    gets the TCP transport, anything else is a same-host directory."""
    if spec.startswith("http://") or spec.startswith("https://"):
        return HttpHistorySource(spec)
    return FileHistorySource(spec)


# ------------------------------------------------------------ compactor
class _Window:
    """One accumulated (grid, windowStart): full docs by cell, content
    hashes, the newest seq that touched it, the writer's published
    digest for it (when auditing), and the dirty/loaded bookkeeping."""

    __slots__ = ("cells", "hashes", "stale", "seq", "want_dg",
                 "verified", "dirty", "loaded", "closed", "epoch",
                 "rebased")

    def __init__(self):
        self.cells: dict = {}     # cid -> full doc (insertion order)
        self.hashes: dict = {}    # cid -> doc_hash
        self.stale: float | None = None
        self.seq = 0              # newest seq applied, WITHIN .epoch
        self.want_dg: int | None = None
        self.verified = False
        self.dirty = False
        self.loaded = True
        # the view EVICTED this window: its content here is final.  A
        # later apply into the same (grid, ws) re-creates the window
        # fresh on the writer, so the accumulator must start fresh too
        # or its digest would diverge from the view's books.
        self.closed = False
        # seqs are only comparable within one writer epoch; a window
        # touched from a NEW epoch rebases (seq restarts at 0 and the
        # new records upsert over the old epoch's final content).  A
        # rebased window's digest is a cross-epoch union the new
        # writer's books never described, so verification is suspended
        # until its content is exactly re-established (resync, or
        # evict + recreate).
        self.epoch: str | None = None
        self.rebased = False

    def enter_epoch(self, epoch: str) -> None:
        if self.epoch == epoch:
            return
        if self.epoch is not None:
            self.rebased = True
            self.verified = False
        self.epoch = epoch
        self.seq = 0

    def digest(self) -> int:
        out = 0
        for h in self.hashes.values():
            out ^= h
        return out


class HistoryCompactor:
    """Compacts retired repl segments into the immutable chunk store.

    Drive it with :meth:`step` (tests, the CLI ``--once`` mode) or
    :meth:`start` (a daemon thread at ``interval_s``).  One compactor
    per history directory."""

    def __init__(self, hist_dir: str, feed_dir: str | None = None,
                 bucket_s: int = 3600, parent_res: int = 3,
                 retention_s: float = 7 * 86400.0,
                 registry=None, clock=time.time, interval_s: float = 2.0,
                 native=None):
        self.dir = hist_dir
        self.feed_dir = feed_dir
        self.bucket_s = max(60, int(bucket_s))
        self.parent_res = max(0, min(15, int(parent_res)))
        self.retention_s = float(retention_s)
        self.clock = clock
        self.interval_s = max(0.05, float(interval_s))
        self.native = native
        self.log_dir = os.path.join(hist_dir, LOG_DIR)
        self.chunk_dir = os.path.join(hist_dir, CHUNK_DIR)
        os.makedirs(self.log_dir, exist_ok=True)
        os.makedirs(self.chunk_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # grid -> ws -> _Window
        self._accum: dict[str, dict[int, _Window]] = {}
        # end-capless segments (a closed feed's final segment) would
        # otherwise re-read every tick: memoize (mtime_ns, size,
        # max seq seen) and skip while unchanged and covered
        self._seg_memo: dict = {}
        self._state = self._load_state()
        self.records_ingested = 0
        self.chunk_writes = 0
        self.verified = 0
        # a persisted mismatch keeps the prune freeze across restarts —
        # an operator clears it by deleting hist-state.json after the
        # incident, not by bouncing the process
        self.mismatches = int(self._state.get("mismatches", 0))
        self.segments_pruned = 0
        self.chunks_pruned = 0
        self.last_mismatch: dict | None = None
        self._lag_s = 0.0
        self._chunks = 0
        self._chunk_bytes = 0
        self._span_s = 0.0
        self._refresh_chunk_stats()
        if registry is not None:
            self._c_records = registry.counter(
                "heatmap_hist_records_total",
                "repl feed records ingested by the history compactor "
                "(apply/evict/resync, across epochs)")
            self._c_chunk_writes = registry.counter(
                "heatmap_hist_chunk_writes_total",
                "immutable space-time chunk files written (atomic "
                "rewrites of a (grid, parent cell, time bucket) chunk "
                "count once each)")
            self._c_verified = registry.counter(
                "heatmap_hist_digest_verified_total",
                "compacted windows whose recomputed content digest "
                "matched the writer's published per-window digest "
                "(HEATMAP_AUDIT=1 feeds)")
            self._c_mismatch = registry.counter(
                "heatmap_hist_digest_mismatch_total",
                "compacted-vs-published window digest mismatches — a "
                "corrupted segment or diverged compaction; any nonzero "
                "degrades /healthz and FREEZES raw-segment pruning")
            self._c_seg_pruned = registry.counter(
                "heatmap_hist_pruned_segments_total",
                "raw log segments pruned after their chunks were "
                "durably written, digest-verified, and aged past "
                "HEATMAP_HIST_RETENTION_S")
            registry.gauge(
                "heatmap_hist_chunks",
                "space-time chunk files currently on disk",
                fn=lambda: self._chunks)
            registry.gauge(
                "heatmap_hist_chunk_bytes",
                "total bytes of space-time chunk files on disk",
                fn=lambda: self._chunk_bytes)
            registry.gauge(
                "heatmap_hist_covered_span_seconds",
                "wall-clock span covered by the chunk store (newest "
                "bucket end minus oldest bucket start; 0 when empty)",
                fn=lambda: self._span_s)
            registry.gauge(
                "heatmap_hist_compaction_lag_seconds",
                "age of the oldest retired segment still holding "
                "records above the persisted ingest watermark (0 when "
                "fully compacted) — the /healthz compaction-lag check",
                fn=lambda: self._lag_s)
        else:
            self._c_records = self._c_chunk_writes = None
            self._c_verified = self._c_mismatch = None
            self._c_seg_pruned = None

    # ------------------------------------------------------------ state
    def _state_path(self) -> str:
        return os.path.join(self.dir, STATE)

    def _load_state(self) -> dict:
        try:
            with open(self._state_path(), encoding="utf-8") as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            return {"v": 1, "epochs": {}}
        if not isinstance(d, dict) or not isinstance(d.get("epochs"),
                                                     dict):
            return {"v": 1, "epochs": {}}
        return d

    def _save_state(self) -> None:
        # mismatches persist so serve workers (which run no compactor)
        # can degrade /healthz off the state file alone
        self._state["mismatches"] = self.mismatches
        atomic_write_json(self._state_path(), self._state)

    # ------------------------------------------------------- accumulate
    def _window(self, grid: str, ws: int) -> _Window:
        wins = self._accum.setdefault(grid, {})
        w = wins.get(ws)
        if w is None:
            w = wins[ws] = _Window()
            self._seed_from_chunks(grid, ws, w)
        elif not w.loaded:
            self._seed_from_chunks(grid, ws, w)
        return w

    def _seed_from_chunks(self, grid: str, ws: int, w: _Window) -> None:
        """Reload one window's cells from its on-disk chunks (compactor
        restart: the accumulator is chunks + un-pruned segments, by
        construction)."""
        bucket = ws - ws % self.bucket_s
        pat = os.path.join(glob.escape(self.chunk_dir),
                           f"chunk-{glob.escape(grid)}-*-{bucket}.hst")
        for p in sorted(glob.glob(pat)):
            try:
                with open(p, "rb") as fh:
                    meta, windows = decode_chunk(fh.read())
            except (OSError, ValueError):
                continue
            part = windows.get(ws)
            if part is None:
                continue
            for d in part["docs"]:
                cid = d.get("cellId")
                w.cells[cid] = d
                w.hashes[cid] = part["hashes"].get(cid, 0)
            wm = (meta.get("windows") or {}).get(str(ws)) or {}
            w.seq = max(w.seq, int(wm.get("seq", 0)))
            if wm.get("stale") is not None:
                w.stale = wm["stale"]
            w.verified = w.verified or bool(wm.get("verified"))
            w.closed = w.closed or bool(wm.get("closed"))
            w.rebased = w.rebased or bool(wm.get("rebased"))
            if wm.get("epoch") and w.epoch is None:
                w.epoch = wm["epoch"]
        w.loaded = True

    def _ingest(self, rec: dict, dirty: set, epoch: str) -> None:
        kind = rec.get("kind")
        seq = int(rec.get("seq", 0))
        touched: set = set()
        if kind == "apply":
            for doc in rec.get("docs") or []:
                self._ingest_doc(doc, seq, touched, epoch)
        elif kind == "resync":
            grid = rec.get("grid") or ""
            ws = rec.get("ws")
            if grid and ws is not None:
                # the window's state is REPLACED at this seq; older
                # accumulated windows of the grid keep their last
                # content — they were true at their time, which is the
                # whole point of a history tier
                w = self._window(grid, int(ws))
                w.enter_epoch(epoch)
                if seq > w.seq:
                    w.cells.clear()
                    w.hashes.clear()
                    w.closed = False
                    w.rebased = False  # content exactly known again
                    touched.add((grid, int(ws)))
                    for doc in rec.get("docs") or []:
                        self._ingest_doc(doc, seq, touched, epoch,
                                         grid=grid)
                    w.seq = max(w.seq, seq)
                    w.dirty = True
        elif kind == "evict":
            # eviction is the live view forgetting; history keeps the
            # final content but CLOSES the window (persisted in the
            # chunk meta): a later apply into the same ws is a fresh
            # window on the writer and must be one here too
            grid = rec.get("grid") or ""
            for ws in rec.get("ws") or []:
                if not grid:
                    break
                # through _window(): an evict REPLAYED after a restart
                # must seed the window from its chunks first, or the
                # closed flag is lost and a later re-create would
                # merge the stale chunk cells into fresh content
                w = self._window(grid, int(ws))
                w.enter_epoch(epoch)
                if seq > w.seq:
                    w.seq = seq
                    w.closed = True
                    w.dirty = True
                    dirty.add((grid, int(ws)))
        self._verify(rec, seq, touched)
        dirty.update(touched)
        self.records_ingested += 1
        if self._c_records is not None:
            self._c_records.inc()

    def _ingest_doc(self, doc: dict, seq: int, touched: set,
                    epoch: str, grid: str | None = None) -> None:
        import datetime as dt

        g = grid or doc.get("grid")
        ws_dt = doc.get("windowStart")
        cid = doc.get("cellId")
        if not g or cid is None or not isinstance(ws_dt, dt.datetime):
            return
        ws = int(ws_dt.timestamp())
        w = self._window(g, ws)
        w.enter_epoch(epoch)
        if seq <= w.seq and (g, ws) not in touched:
            # replay idempotence (per window, like the replica's
            # per-view rule): a re-ingested record at or below the
            # chunk-seeded seq is already folded into the window —
            # re-applying its older doc would regress content and its
            # digest check would compare final state to an
            # intermediate one.  Same-record siblings (equal seq) pass
            # via the touched set.
            return
        if w.closed:
            w.cells.clear()
            w.hashes.clear()
            w.closed = False
            w.verified = False
            w.rebased = False  # fresh window: content exactly known
        w.cells[cid] = doc
        w.hashes[cid] = doc_hash(doc)
        w.seq = max(w.seq, seq)
        w.dirty = True
        stale = doc.get("staleAt")
        if isinstance(stale, dt.datetime):
            w.stale = stale.timestamp()
        touched.add((g, ws))

    def _verify(self, rec: dict, seq: int, touched: set) -> None:
        """Per-record digest verification against the writer's books
        (``"dg"``, published under HEATMAP_AUDIT=1): recompute the
        accumulated window's digest and compare.  Only windows this
        record actually touched verify — a dg entry for a window whose
        history predates this store must not read as divergence."""
        dg = rec.get("dg")
        if not isinstance(dg, dict):
            return
        for grid, per_ws in dg.items():
            if not isinstance(per_ws, dict):
                continue
            for ws_s, expect in per_ws.items():
                try:
                    ws, want = int(ws_s), int(expect, 16)
                except (TypeError, ValueError):
                    continue
                if (grid, ws) not in touched:
                    continue
                w = (self._accum.get(grid) or {}).get(ws)
                if w is None:
                    continue
                if w.rebased:
                    # cross-epoch union: the writer's books never
                    # described this content — verification resumes
                    # once the window's content is exactly known again
                    continue
                w.want_dg = want
                if w.digest() == want:
                    w.verified = True
                    self.verified += 1
                    if self._c_verified is not None:
                        self._c_verified.inc()
                else:
                    w.verified = False
                    self.mismatches += 1
                    self.last_mismatch = {
                        "grid": grid, "ws": ws, "seq": seq,
                        "have": format(w.digest(), "016x"),
                        "want": format(want, "016x")}
                    if self._c_mismatch is not None:
                        self._c_mismatch.inc()
                    log.error(
                        "HIST digest mismatch: grid=%s window=%d "
                        "seq=%d (have %016x, want %016x)", grid, ws,
                        seq, w.digest(), want)

    # ------------------------------------------------------------ flush
    def _flush(self, dirty: set) -> None:
        """Rewrite every chunk a dirty window belongs to.  A rewrite
        loads the existing chunk, overlays the dirty windows' slices,
        and replaces it atomically — readers only ever see complete
        chunks."""
        by_chunk: dict = {}
        for grid, ws in dirty:
            w = (self._accum.get(grid) or {}).get(ws)
            if w is None:
                continue
            bucket = ws - ws % self.bucket_s
            parents: set = set()
            for cid in w.cells:
                parents.add(_cell_parent_key(cid, self.parent_res))
            # ALSO rewrite chunks that hold a now-stale slice of this
            # window under a parent its current cells no longer touch
            # (a resync / evict+recreate dropped every cell of that
            # parent) — without this the stale slice would serve (and
            # re-seed a restarted compactor) forever
            pat = os.path.join(glob.escape(self.chunk_dir),
                               f"chunk-{glob.escape(grid)}-*-"
                               f"{bucket}.hst")
            for p in glob.glob(pat):
                try:
                    with open(p, "rb") as fh:
                        meta = json.loads(
                            fh.readline().decode("utf-8"))
                    if str(ws) in (meta.get("windows") or {}):
                        parents.add(int(meta.get("parent", "0"), 16))
                except (OSError, ValueError):
                    continue
            for parent in parents:
                by_chunk.setdefault((grid, parent, bucket),
                                    set()).add(ws)
        for (grid, parent, bucket), ws_set in sorted(by_chunk.items()):
            path = os.path.join(self.chunk_dir,
                                _chunk_name(grid, parent, bucket))
            windows: dict = {}
            try:
                with open(path, "rb") as fh:
                    meta, existing = decode_chunk(fh.read())
                for ws, part in existing.items():
                    wm = (meta.get("windows") or {}).get(str(ws)) or {}
                    windows[ws] = {
                        "docs": part["docs"],
                        "hashes": part["hashes"],
                        "digest": int(wm.get("digest", "0"), 16),
                        "seq": int(wm.get("seq", 0)),
                        "stale": wm.get("stale"),
                        "verified": bool(wm.get("verified")),
                        "closed": bool(wm.get("closed")),
                        "epoch": wm.get("epoch"),
                        "rebased": bool(wm.get("rebased")),
                    }
            except FileNotFoundError:
                pass
            except (OSError, ValueError):
                log.warning("unreadable chunk %s; rewriting from the "
                            "accumulator alone", path)
            for ws in ws_set:
                w = self._accum[grid][ws]
                docs = [d for cid, d in w.cells.items()
                        if _cell_parent_key(cid, self.parent_res)
                        == parent]
                if not docs:
                    # this parent's slice of the window is gone
                    # (resync/recreate): drop it from the chunk
                    windows.pop(ws, None)
                    continue
                hashes = {d.get("cellId"):
                          w.hashes.get(d.get("cellId"), 0)
                          for d in docs}
                windows[ws] = {
                    "docs": docs, "hashes": hashes,
                    "digest": w.digest(), "seq": w.seq,
                    "stale": w.stale, "verified": w.verified,
                    "closed": w.closed, "epoch": w.epoch,
                    "rebased": w.rebased,
                }
            if not windows:
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            data = encode_chunk(grid, parent, bucket, self.bucket_s,
                                self.parent_res, windows,
                                native=self.native)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            self.chunk_writes += 1
            if self._c_chunk_writes is not None:
                self._c_chunk_writes.inc()
        for grid, ws in dirty:
            w = (self._accum.get(grid) or {}).get(ws)
            if w is not None:
                w.dirty = False

    # ------------------------------------------------------------- step
    def _log_segments(self) -> tuple[list, dict]:
        """([(epoch, start, path, mtime)], {epoch: end cap}) of sealed
        segments, ordered epoch-boot-first (min mtime per epoch), then
        by start seq.  The cap is the excluded live segment's start −
        1: it bounds the newest sealed segment's records, so a
        caught-up compactor skips it by watermark instead of
        re-reading it every tick.

        Includes the FEED directory's sealed rotated segments: the
        newest ``HEATMAP_REPL_SEGMENTS - 1`` rotated segments stay in
        the feed for follower tailing and only retire at a later
        rotation — without reading them in place the compactor would
        sit one retention window behind (and see a seq gap after a
        clean shutdown retired the live tail around them).  The feed's
        LIVE segment (max start per epoch) is excluded unless the feed
        is cleanly closed — it is still being appended to.  A segment
        read both here and after retirement dedups via the watermark
        (identical bytes, os.replace keeps the name)."""
        segs = []
        caps: dict = {}
        for p in glob.glob(os.path.join(glob.escape(self.log_dir),
                                        "seg-*.jsonl")):
            parts = _seg_name_parts(p)
            if parts is None:
                continue
            try:
                mtime = os.stat(p).st_mtime
            except OSError:
                continue
            segs.append((parts[0], parts[1], p, mtime))
        if self.feed_dir:
            meta = replmod.read_meta(self.feed_dir)
            closed = bool(meta.get("closed"))
            feed_epoch = meta.get("epoch")
            feed_segs: dict = {}
            for p in glob.glob(os.path.join(
                    glob.escape(self.feed_dir), "seg-*.jsonl")):
                parts = _seg_name_parts(p)
                if parts is None:
                    continue
                try:
                    mtime = os.stat(p).st_mtime
                except OSError:
                    continue
                feed_segs.setdefault(parts[0], []).append(
                    (parts[1], p, mtime))
            for epoch, eseg in feed_segs.items():
                eseg.sort()
                live_ok = closed and epoch == feed_epoch
                for i, (start, p, mtime) in enumerate(eseg):
                    if i + 1 == len(eseg) and not live_ok:
                        caps[epoch] = start - 1
                        continue  # the live (appended-to) segment
                    segs.append((epoch, start, p, mtime))
        first_seen: dict = {}
        for epoch, _s, _p, mtime in segs:
            first_seen[epoch] = min(first_seen.get(epoch, mtime), mtime)
        segs.sort(key=lambda t: (first_seen[t[0]], t[0], t[1]))
        return segs, caps

    def _seed_epoch(self, epoch: str, dirty: set) -> int:
        """First sight of an epoch: seed the accumulator from its
        adopted BOOT snapshot (the oldest snap) so windows that
        predate the first rotated segment are complete, and return the
        snapshot seq as the initial watermark."""
        snaps = []
        for p in glob.glob(os.path.join(
                glob.escape(self.log_dir),
                f"snap-{glob.escape(epoch)}-*.json")):
            parts = _snap_name_parts(p)
            if parts is not None:
                snaps.append((parts[1], p))
        if not snaps:
            return 0
        seq0, path = min(snaps)
        try:
            with open(path, encoding="utf-8") as fh:
                snap = replmod.loads(fh.read())
        except (OSError, ValueError):
            return 0
        state = (snap or {}).get("state") or {}
        touched: set = set()
        for grid, gs in (state.get("grids") or {}).items():
            for ws_key, cells in (gs.get("windows") or {}).items():
                for cid, doc in cells.items():
                    self._ingest_doc(doc, seq0, touched, epoch,
                                     grid=grid)
        dirty.update(touched)
        return int(seq0)

    def step(self) -> int:
        """One compaction round: ingest new records from sealed
        segments, flush dirty windows to chunks, persist the
        watermarks, then prune.  Returns records ingested."""
        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> int:
        segs, caps = self._log_segments()
        epochs = self._state["epochs"]
        ingested = 0
        dirty: set = set()
        pending_oldest: float | None = None
        # per-epoch segment end bounds: records of seg i span
        # [start_i, start_{i+1} - 1]; the newest segment's end is
        # unknown and always read
        by_epoch: dict = {}
        for epoch, start, path, mtime in segs:
            by_epoch.setdefault(epoch, []).append((start, path, mtime))
        seeded = False
        for epoch, eseg in by_epoch.items():
            eseg.sort()
            if epoch not in epochs:
                epochs[epoch] = self._seed_epoch(epoch, dirty)
                seeded = True
            wm = int(epochs[epoch])
            for i, (start, path, mtime) in enumerate(eseg):
                end = (eseg[i + 1][0] - 1) if i + 1 < len(eseg) \
                    else caps.get(epoch)
                if end is not None and end <= wm:
                    continue
                try:
                    st = os.stat(path)
                    stat_key = (st.st_mtime_ns, st.st_size)
                except OSError:
                    stat_key = None
                memo = self._seg_memo.get(path)
                if memo is not None and stat_key is not None \
                        and memo[0] == stat_key and memo[1] <= wm:
                    continue
                top = 0
                for rec in _read_segment(path):
                    seq = int(rec.get("seq", 0))
                    top = max(top, seq)
                    if seq <= wm:
                        continue
                    self._ingest(rec, dirty, epoch)
                    wm = max(wm, seq)
                    ingested += 1
                if stat_key is not None and top > 0:
                    # only a read that actually saw records memoizes —
                    # an empty or failed read must retry next tick
                    if len(self._seg_memo) >= 1024:
                        self._seg_memo.pop(next(iter(self._seg_memo)))
                    self._seg_memo[path] = (stat_key, top)
            epochs[epoch] = wm
        if dirty:
            self._flush(dirty)
        if ingested or dirty or seeded:
            # AFTER the flush: the persisted watermark only ever claims
            # records whose chunks are durably on disk — the ordering
            # the zero-loss retention invariant rests on
            self._save_state()
        self._prune(by_epoch)
        # compaction lag: oldest sealed segment still above the
        # persisted watermark (after this round: normally none)
        now = self.clock()
        for epoch, eseg in by_epoch.items():
            wm = int(self._state["epochs"].get(epoch, 0))
            for i, (start, path, mtime) in enumerate(eseg):
                end = (eseg[i + 1][0] - 1) if i + 1 < len(eseg) \
                    else caps.get(epoch)
                if end is None or end > wm:
                    # conservatively: unread tail counts only when it
                    # still exists (the prune may have removed it)
                    if os.path.exists(path) and (end is not None):
                        pending_oldest = (mtime if pending_oldest is None
                                          else min(pending_oldest, mtime))
        self._lag_s = (max(0.0, now - pending_oldest)
                       if pending_oldest is not None else 0.0)
        self._refresh_chunk_stats()
        return ingested

    # ------------------------------------------------------------ prune
    def _prune(self, by_epoch: dict) -> None:
        """Retention prune.  Raw segments go ONLY when fully ingested
        (below the persisted watermark), aged past retention, and no
        digest mismatch is outstanding — the zero-loss ordering
        invariant.  Chunks and accumulator windows age out past
        retention; replay snapshots keep the newest base at or below
        every retained segment."""
        now = self.clock()
        horizon = now - self.retention_s
        # the live epoch's newest segment can still GROW (the retired
        # live tail of a crashed writer re-appears at the next boot
        # sweep); a dead epoch's newest segment cannot, so once the
        # watermark covers what we read of it, it is fully ingested
        live_epoch = None
        if self.feed_dir:
            meta = replmod.read_meta(self.feed_dir)
            if not meta.get("closed"):
                live_epoch = meta.get("epoch")
        if self.mismatches == 0:
            for epoch, eseg in by_epoch.items():
                wm = int(self._state["epochs"].get(epoch, 0))
                eseg = sorted(eseg)
                for i, (start, path, mtime) in enumerate(eseg):
                    end = (eseg[i + 1][0] - 1) if i + 1 < len(eseg) \
                        else None
                    if end is None and epoch != live_epoch \
                            and start <= wm:
                        end = wm
                    if end is None or end > wm or mtime > horizon:
                        continue
                    if os.path.dirname(path) != self.log_dir:
                        # feed-resident segments are the publisher's to
                        # prune (follower tail retention) — never ours
                        continue
                    try:
                        os.remove(path)
                        self.segments_pruned += 1
                        if self._c_seg_pruned is not None:
                            self._c_seg_pruned.inc()
                    except OSError:
                        pass
        # chunks whose whole bucket aged out
        for p in glob.glob(os.path.join(glob.escape(self.chunk_dir),
                                        "chunk-*.hst")):
            name = os.path.basename(p)
            try:
                bucket = int(name[:-4].rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if bucket + self.bucket_s < horizon:
                try:
                    os.remove(p)
                    self.chunks_pruned += 1
                except OSError:
                    pass
        for grid in list(self._accum):
            wins = self._accum[grid]
            for ws in [ws for ws in wins if ws + self.bucket_s
                       < horizon]:
                del wins[ws]
            if not wins:
                del self._accum[grid]
        # replay snapshots: drop aged ones, but ALWAYS keep, per epoch,
        # the newest snap at or below the oldest retained segment start
        # (the replay base) and the newest snap overall
        remaining: dict = {}
        for p in glob.glob(os.path.join(glob.escape(self.log_dir),
                                        "seg-*.jsonl")):
            parts = _seg_name_parts(p)
            if parts is not None:
                e, s = parts
                remaining[e] = min(remaining.get(e, s), s)
        for p in glob.glob(os.path.join(glob.escape(self.log_dir),
                                        "snap-*.json")):
            parts = _snap_name_parts(p)
            if parts is None:
                continue
            epoch, seq = parts
            try:
                mtime = os.stat(p).st_mtime
            except OSError:
                continue
            if mtime > horizon:
                continue
            oldest_seg = remaining.get(epoch)
            if oldest_seg is not None:
                # the newest snap <= the oldest retained segment is
                # the replay base — keep it regardless of age
                bases = [s for s in self._epoch_snap_seqs(epoch)
                         if s <= oldest_seg]
                if bases and seq == max(bases):
                    continue
            else:
                keep = self._epoch_snap_seqs(epoch)
                if keep and seq == max(keep):
                    # epoch fully compacted: the newest snap is still
                    # the only view-at-seq base for its tail
                    continue
            try:
                os.remove(p)
            except OSError:
                pass

    def _epoch_snap_seqs(self, epoch: str) -> list:
        out = []
        for p in glob.glob(os.path.join(
                glob.escape(self.log_dir),
                f"snap-{glob.escape(epoch)}-*.json")):
            parts = _snap_name_parts(p)
            if parts is not None:
                out.append(parts[1])
        return out

    def _refresh_chunk_stats(self) -> None:
        n = b = 0
        lo = hi = None
        for p in glob.glob(os.path.join(glob.escape(self.chunk_dir),
                                        "chunk-*.hst")):
            try:
                b += os.stat(p).st_size
            except OSError:
                continue
            n += 1
            try:
                bucket = int(os.path.basename(p)[:-4].rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            lo = bucket if lo is None else min(lo, bucket)
            hi = bucket if hi is None else max(hi, bucket)
        self._chunks = n
        self._chunk_bytes = b
        self._span_s = (hi + self.bucket_s - lo) if lo is not None \
            else 0.0

    def member_block(self) -> dict:
        """The compact history block a fleet member snapshot publishes
        (obs.xproc) — what ``obs_top --fleet`` renders per member."""
        return {"chunks": self._chunks,
                "chunk_bytes": self._chunk_bytes,
                "covered_span_s": round(self._span_s, 3),
                "lag_s": round(self._lag_s, 3),
                "records": self.records_ingested,
                "chunk_writes": self.chunk_writes,
                "verified": self.verified,
                "mismatches": self.mismatches,
                "segments_pruned": self.segments_pruned}

    # ----------------------------------------------------------- thread
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hist-compactor")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                log.exception("history compaction step failed")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        try:
            self.step()  # final drain: nothing rotated is left behind
        except Exception:
            log.exception("history compactor final step failed")


# --------------------------------------------------------------- status
def compaction_status(hist_dir: str, now: float | None = None) -> dict:
    """File-derived compaction status — what serve workers (which run
    no compactor) feed /healthz and the fleet member snapshot:
    chunks/bytes/covered span, pending (not-yet-ingested) sealed
    segments, and the compaction lag in seconds."""
    now = time.time() if now is None else now
    out = {"chunks": 0, "chunk_bytes": 0, "covered_span_s": 0.0,
           "pending_segments": 0, "lag_s": 0.0, "backfills": None}
    chunk_dir = os.path.join(hist_dir, CHUNK_DIR)
    lo = hi = None
    bucket_s = None
    for p in glob.glob(os.path.join(glob.escape(chunk_dir),
                                    "chunk-*.hst")):
        try:
            st = os.stat(p)
        except OSError:
            continue
        out["chunks"] += 1
        out["chunk_bytes"] += st.st_size
        if bucket_s is None:
            try:
                with open(p, "rb") as fh:
                    meta = json.loads(fh.readline().decode("utf-8"))
                bucket_s = int(meta.get("bucket_s", 0)) or None
            except (OSError, ValueError):
                pass
        try:
            bucket = int(os.path.basename(p)[:-4].rsplit("-", 1)[1])
        except (IndexError, ValueError):
            continue
        lo = bucket if lo is None else min(lo, bucket)
        hi = bucket if hi is None else max(hi, bucket)
    if lo is not None:
        out["covered_span_s"] = float(hi - lo + (bucket_s or 0))
    try:
        with open(os.path.join(hist_dir, STATE),
                  encoding="utf-8") as fh:
            state = json.load(fh)
        epochs = (state.get("epochs") or {}) \
            if isinstance(state, dict) else {}
        out["mismatches"] = int(state.get("mismatches", 0)) \
            if isinstance(state, dict) else 0
    except (OSError, ValueError):
        epochs = {}
        out["mismatches"] = 0
    log_dir = os.path.join(hist_dir, LOG_DIR)
    by_epoch: dict = {}
    for p in glob.glob(os.path.join(glob.escape(log_dir),
                                    "seg-*.jsonl")):
        parts = _seg_name_parts(p)
        if parts is None:
            continue
        try:
            mtime = os.stat(p).st_mtime
        except OSError:
            continue
        by_epoch.setdefault(parts[0], []).append((parts[1], p, mtime))
    oldest: float | None = None
    for epoch, eseg in by_epoch.items():
        wm = int(epochs.get(epoch, 0))
        eseg.sort()
        for i, (start, path, mtime) in enumerate(eseg):
            end = (eseg[i + 1][0] - 1) if i + 1 < len(eseg) else None
            if end is not None and end <= wm:
                continue
            if end is None and wm >= start:
                # the epoch's newest sealed segment has no end bound;
                # once the watermark has ENTERED it, the compactor is
                # at most one segment behind — counting it pending
                # forever would read as multi-day lag after every
                # rotation (and for every dead epoch's tail)
                continue
            out["pending_segments"] += 1
            oldest = mtime if oldest is None else min(oldest, mtime)
    if oldest is not None:
        out["lag_s"] = max(0.0, now - oldest)
    return out


# --------------------------------------------------------------- reader
# per-request scan accounting: the serve tier calls scan_reset() before
# a history query and attaches last_scan() to the request span after —
# thread-local so concurrent workers never mix counts.  The registry
# counters (heatmap_hist_scan_*) always accrue, reset or not.
_scan_tls = threading.local()

#: the fields one request's scan accounting carries
SCAN_FIELDS = ("chunks_opened", "blocks_scanned", "blocks_used",
               "bytes_decoded", "rows_surfaced")


def scan_reset() -> None:
    """Zero this thread's per-request scan accounting."""
    _scan_tls.scan = dict.fromkeys(SCAN_FIELDS, 0)


def last_scan() -> dict | None:
    """This thread's accounting since the last :func:`scan_reset`,
    with the scan-efficiency ratio (blocks the query actually needed /
    blocks materialized to find them): today's whole-chunk decodes
    pin it well below 1; ROADMAP item 4's window index must drive it
    toward 1.  None when never reset on this thread."""
    s = getattr(_scan_tls, "scan", None)
    if s is None:
        return None
    out = dict(s)
    out["scan_ratio"] = round(
        out["blocks_used"] / max(1, out["blocks_scanned"]), 4)
    return out


def _scan_add(field: str, n: int) -> None:
    s = getattr(_scan_tls, "scan", None)
    if s is not None:
        s[field] += n


class HistoryReader:
    """Range / at-seq / diff queries over a history source (+ an
    optional live view whose windows overlay the chunks — latest and
    not-yet-compacted windows serve without waiting for the
    compactor).  Decoded chunks are memoized by (name, bytes) bounded
    at ``cache_chunks``.

    Every query is scan-accounted: chunks opened, window blocks
    scanned vs actually used, bytes decoded, rows surfaced — the
    process counters feed ``heatmap_hist_scan_*`` and the thread-local
    per-request tally feeds the serve request span."""

    def __init__(self, source, view=None, cache_chunks: int = 64,
                 registry=None):
        self.source = source
        self.view = view
        self._cache: dict = {}
        self._cache_max = max(4, int(cache_chunks))
        self._c_chunks = self._c_blocks = None
        self._c_bytes = self._c_rows = None
        if registry is not None:
            self._c_chunks = registry.counter(
                "heatmap_hist_scan_chunks_total",
                "history chunks consulted by range/at/diff queries "
                "(cache hits included — the chunk was still the scan "
                "unit)")
            self._c_blocks = registry.counter(
                "heatmap_hist_scan_blocks_total",
                "window blocks materialized by history queries; with "
                "whole-chunk decodes every block in a consulted chunk "
                "counts, wanted or not — the denominator of the "
                "scan-efficiency ratio the window index must improve")
            self._c_bytes = registry.counter(
                "heatmap_hist_scan_bytes_total",
                "chunk bytes decoded by history queries (cache misses "
                "only — what the query actually paid in decode I/O)")
            self._c_rows = registry.counter(
                "heatmap_hist_scan_rows_total",
                "cell documents surfaced to history query responses")

    def _chunk_windows(self, meta: dict) -> dict:
        name = meta.get("name")
        # mtime in the key: an atomic rewrite can keep the byte size
        # (varint count bumps, f64 changes) — size alone served stale
        key = (name, meta.get("bytes"), meta.get("mtime_ns"))
        if self._c_chunks is not None:
            self._c_chunks.inc()
        _scan_add("chunks_opened", 1)
        hit = self._cache.get(name)
        if hit is not None and hit[0] == key:
            self._count_blocks(len(hit[1]))
            return hit[1]
        buf = self.source.chunk_bytes(name)
        if buf is None:
            return {}
        try:
            _meta, windows = decode_chunk(buf)
        except ValueError:
            return {}
        if self._c_bytes is not None:
            self._c_bytes.inc(len(buf))
        _scan_add("bytes_decoded", len(buf))
        # whole-chunk decode: every window block was materialized to
        # answer the query, however few it wanted.  Counted on cache
        # hits too (the decoded form is block-complete either way) so
        # the efficiency ratio doesn't flatter a warm cache.
        self._count_blocks(len(windows))
        if len(self._cache) >= self._cache_max:
            self._cache.pop(next(iter(self._cache)))
        self._cache[name] = (key, windows)
        return windows

    def _count_blocks(self, n: int) -> None:
        if n <= 0:
            return
        if self._c_blocks is not None:
            self._c_blocks.inc(n)
        _scan_add("blocks_scanned", n)

    def _count_rows(self, n: int) -> None:
        if n <= 0:
            return
        if self._c_rows is not None:
            self._c_rows.inc(n)
        _scan_add("rows_surfaced", n)

    def windows_in_range(self, grid: str, t0: float,
                         t1: float) -> dict:
        """{ws: {"docs": [...]}} for windows with t0 <= ws < t1, cells
        merged across parent chunks, live-view windows overlaid (the
        view is fresher than any chunk)."""
        out: dict = {}
        for meta in self.source.index():
            if meta.get("grid") != grid:
                continue
            wanted = [int(ws) for ws in (meta.get("windows") or {})
                      if t0 <= int(ws) < t1]
            if not wanted:
                continue
            windows = self._chunk_windows(meta)
            used = 0
            for ws in wanted:
                part = windows.get(ws)
                if part is None:
                    continue
                used += 1
                cells = out.setdefault(ws, {})
                for d in part["docs"]:
                    cells[d.get("cellId")] = d
            _scan_add("blocks_used", used)
        if self.view is not None:
            try:
                live = self.view.window_docs(grid)
            except Exception:  # noqa: BLE001 - history must not 500 on a view bug
                live = {}
            for ws, (_ws_dt, _we_dt, docs) in live.items():
                if t0 <= ws < t1:
                    out[ws] = {d.get("cellId"): d for d in docs}
        self._count_rows(sum(len(c) for c in out.values()))
        return {ws: {"docs": [cells[c] for c in sorted(cells)]}
                for ws, cells in out.items()}

    def window_at(self, grid: str, t: float) -> tuple[int, list] | None:
        """(ws, docs) of the newest window with ws <= t (the window
        state a diff anchors at), or None."""
        best: int | None = None
        for meta in self.source.index():
            if meta.get("grid") != grid:
                continue
            for ws_s in (meta.get("windows") or {}):
                ws = int(ws_s)
                if ws <= t and (best is None or ws > best):
                    best = ws
        if self.view is not None:
            try:
                for ws in self.view.window_docs(grid):
                    if ws <= t and (best is None or ws > best):
                        best = ws
            except Exception:  # noqa: BLE001
                pass
        if best is None:
            return None
        got = self.windows_in_range(grid, best, best + 1)
        part = got.get(best)
        return (best, part["docs"]) if part else (best, [])


def rollup_window(docs: list, res: int, base_res: int, ws_dt,
                  we_dt) -> list:
    """One window's docs rolled up to coarser H3 resolution ``res`` via
    the pyramid math (query.pyramid — counts sum, speed and centroid
    recombine as count-weighted means; p95/stddev are non-combinable
    and omitted, same contract as the live ``?res=`` rollup)."""
    from heatmap_tpu.query.pyramid import Pyramid

    pyr = Pyramid(base_res, base_res - res)
    ws = int(ws_dt.timestamp()) if ws_dt is not None else 0
    for d in docs:
        try:
            pyr.apply(ws, int(d["cellId"], 16), None, d)
        except (KeyError, TypeError, ValueError):
            continue
    try:
        return pyr.docs(res, ws, we_dt, ws_dt)
    except KeyError:
        return []


def aggregate_range(per_window: dict, t0_dt, t1_dt) -> list:
    """Cross-window aggregate of a range response: per cell, counts
    sum and speeds/centroids recombine count-weighted — the rollup row
    a day-over-day heatmap draws."""
    agg: dict = {}
    for ws in sorted(per_window):
        for d in per_window[ws]["docs"]:
            cid = d.get("cellId")
            c = int(d.get("count", 0))
            a = agg.get(cid)
            if a is None:
                a = agg[cid] = [0, 0.0, 0.0, 0.0, False]
            a[0] += c
            a[1] += float(d.get("avgSpeedKmh", 0.0)) * c
            try:
                lon, lat = d["centroid"]["coordinates"]
                a[2] += float(lon) * c
                a[3] += float(lat) * c
                a[4] = True
            except (KeyError, TypeError, ValueError):
                pass
    out = []
    for cid in sorted(agg):
        c, sw, slon, slat, has_cent = agg[cid]
        if c <= 0:
            continue
        doc = {"cellId": cid, "count": int(c), "avgSpeedKmh": sw / c,
               "windowStart": t0_dt, "windowEnd": t1_dt}
        if has_cent:
            doc["centroid"] = {"type": "Point",
                               "coordinates": [slon / c, slat / c]}
        out.append(doc)
    return out


# --------------------------------------------------------------- replay
def replay_records(hist_dir: str, epoch: str, since: int, until: int,
                   feed_dir: str | None = None) -> list:
    """Records of ``epoch`` with since < seq <= until, merged from the
    sealed log and (for the not-yet-rotated tail) the live feed.  The
    feed is globbed FIRST so a segment racing retirement lands in at
    least one of the two scans; duplicates dedup by seq (identical
    bytes either way)."""
    recs: dict = {}
    if feed_dir:
        for rec in replmod.read_records(feed_dir, epoch, since,
                                        max_n=1 << 30):
            seq = int(rec.get("seq", 0))
            if since < seq <= until:
                recs[seq] = rec
    log_dir = os.path.join(hist_dir, LOG_DIR)
    segs = []
    for p in glob.glob(os.path.join(glob.escape(log_dir),
                                    f"seg-{glob.escape(epoch)}-*"
                                    f".jsonl")):
        parts = _seg_name_parts(p)
        if parts is not None:
            segs.append((parts[1], p))
    for start, p in sorted(segs):
        if start > until:
            continue
        for rec in _read_segment(p):
            seq = int(rec.get("seq", 0))
            if since < seq <= until and seq not in recs:
                recs[seq] = rec
    return [recs[s] for s in sorted(recs)]


def view_at_seq(hist_dir: str, seq: int, feed_dir: str | None = None,
                epoch: str | None = None):
    """Reconstruct the materialized view at ``seq``: reset a
    replica-mode TileMatView from the newest adopted snapshot at or
    below ``seq``, then replay the log records up to it.  Raises
    ValueError when the seq predates the retained history or overruns
    the feed head (a dense-seq gap would silently diverge — refuse
    instead)."""
    from heatmap_tpu.query.matview import TileMatView

    if epoch is None and feed_dir:
        epoch = replmod.read_meta(feed_dir).get("epoch")
    log_dir = os.path.join(hist_dir, LOG_DIR)
    if epoch is None:
        # newest epoch by snap mtime — the forensics default
        cand = []
        for p in glob.glob(os.path.join(glob.escape(log_dir),
                                        "snap-*.json")):
            parts = _snap_name_parts(p)
            if parts is not None:
                try:
                    cand.append((os.stat(p).st_mtime, parts[0]))
                except OSError:
                    pass
        if not cand:
            raise ValueError("no history snapshots retained")
        epoch = max(cand)[1]
    snaps = []
    for p in glob.glob(os.path.join(glob.escape(log_dir),
                                    f"snap-{glob.escape(epoch)}-*"
                                    f".json")):
        parts = _snap_name_parts(p)
        if parts is not None:
            snaps.append((parts[1], p))
    bases = [(s, p) for s, p in snaps if s <= seq]
    if not bases:
        raise ValueError(
            f"seq {seq} predates the retained history of epoch "
            f"{epoch!r}")
    base_seq, base_path = max(bases)
    try:
        with open(base_path, encoding="utf-8") as fh:
            snap = replmod.loads(fh.read())
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable replay base: {e}") from e
    view = TileMatView(replica=True)
    view.replica_reset((snap or {}).get("state") or {})
    applied = base_seq
    for rec in replay_records(hist_dir, epoch, base_seq, seq,
                              feed_dir=feed_dir):
        if int(rec.get("seq", 0)) != applied + 1:
            raise ValueError(
                f"history gap at seq {applied + 1} (epoch {epoch!r}); "
                f"the range was pruned or never retired")
        view.replica_apply(rec)
        applied = int(rec.get("seq", 0))
    if applied != seq:
        raise ValueError(
            f"seq {seq} is beyond the retained history head "
            f"({applied})")
    return view


# ------------------------------------------------------------------ CLI
def main(argv=None) -> int:
    """Standalone compactor: compact a feed's retired history once (or
    on an interval) without a runtime attached."""
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hist", required=True,
                    help="history directory (HEATMAP_HIST_DIR)")
    ap.add_argument("--feed", default=None,
                    help="feed directory (for lag vs the live head)")
    ap.add_argument("--bucket-s", type=int, default=3600)
    ap.add_argument("--parent-res", type=int, default=3)
    ap.add_argument("--retention-s", type=float, default=7 * 86400.0)
    ap.add_argument("--interval", type=float, default=0.0,
                    help="compaction cadence in seconds; 0 = one round")
    ap.add_argument("--once", action="store_true",
                    help="one compaction round (same as --interval 0)")
    args = ap.parse_args(argv)
    if args.once:
        args.interval = 0.0
    comp = HistoryCompactor(args.hist, feed_dir=args.feed,
                            bucket_s=args.bucket_s,
                            parent_res=args.parent_res,
                            retention_s=args.retention_s)
    while True:
        n = comp.step()
        print(json.dumps({"records": n, "chunks": comp._chunks,
                          "chunk_bytes": comp._chunk_bytes,
                          "mismatches": comp.mismatches}))
        if args.interval <= 0:
            return 1 if comp.mismatches else 0
        time.sleep(args.interval)


if __name__ == "__main__":
    import sys

    sys.exit(main())
