"""Shared socket I/O helpers for the wire-protocol clients and mocks.

One definition of the exact-read loop (EINTR-safe via Python's default
retry semantics; raises ConnectionError on EOF) serves the Mongo client,
the Kafka client, and both protocol mocks.
"""

from __future__ import annotations

import socket


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF.

    recv_into a single preallocated buffer: the chunks+join pattern
    allocated and copied every receive twice, which at multi-MiB fetch
    responses was a measurable slice of the ingest wall (round-5
    profile)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("connection closed by peer")
        got += r
    return bytes(buf)


def recv_exact_or_none(sock: socket.socket, n: int) -> bytes | None:
    """Server-side variant: None on clean EOF (client went away)."""
    try:
        return recv_exact(sock, n)
    except ConnectionError:
        return None
