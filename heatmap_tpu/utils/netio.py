"""Shared socket I/O helpers for the wire-protocol clients and mocks.

One definition of the exact-read loop (EINTR-safe via Python's default
retry semantics; raises ConnectionError on EOF) serves the Mongo client,
the Kafka client, and both protocol mocks.
"""

from __future__ import annotations

import socket


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    chunks = []
    while n:
        b = sock.recv(n)
        if not b:
            raise ConnectionError("connection closed by peer")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_exact_or_none(sock: socket.socket, n: int) -> bytes | None:
    """Server-side variant: None on clean EOF (client went away)."""
    try:
        return recv_exact(sock, n)
    except ConnectionError:
        return None
