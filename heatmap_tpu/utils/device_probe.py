"""Startup accelerator probe with CPU fallback for CLI entrypoints.

With a remote-attached accelerator (TPU behind a relay), a dead link
does not raise — the first device operation (even the module-level
constants in ``heatmap_tpu.engine``) blocks forever.  ``bench.py``
solved this for the benchmark harness; this is the same discipline for
the long-running entrypoints (``python -m heatmap_tpu.stream``, the
demo): probe device init + one tiny jit in a fresh subprocess (a hung
in-process init can never be retried — the backend lock stays held),
and on failure pin this process to the CPU backend, loudly, so the
pipeline starts degraded instead of hanging silently.

Skipped when the operator already chose a backend (``HEATMAP_PLATFORM``),
when probing is disabled (``HEATMAP_DEVICE_PROBE=0``), or in multi-host
mode (``HEATMAP_COORDINATOR`` — a fallback decided per-host would
desync the mesh; the supervisor's failover handles that case from
outside the process group).

Call ``ensure_reachable_backend()`` BEFORE importing anything that
touches jax arrays.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time

log = logging.getLogger("device_probe")

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "jax.block_until_ready(jax.jit(lambda v: v + 1)(jnp.zeros(8)));"
    "d = jax.devices()[0];"
    "print(f'PROBE_OK {d.platform} {d.device_kind}')"
)


def ensure_reachable_backend(timeout_s: float | None = None,
                             attempts: int | None = None) -> str:
    """Probe the default backend; pin CPU if it never answers.

    Returns ``"ok"`` (accelerator answered), ``"fallback"`` (pinned to
    CPU), or ``"skipped"`` (probe not applicable)."""
    if (os.environ.get("HEATMAP_PLATFORM")
            or os.environ.get("HEATMAP_DEVICE_PROBE") == "0"
            or os.environ.get("HEATMAP_COORDINATOR")):
        return "skipped"
    if timeout_s is None:
        timeout_s = float(os.environ.get("HEATMAP_PROBE_TIMEOUT_S", "90"))
    if attempts is None:
        attempts = int(os.environ.get("HEATMAP_PROBE_ATTEMPTS", "1"))
    for k in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               capture_output=True, text=True,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            log.warning("device probe %d/%d: no response in %.0fs",
                        k + 1, attempts, timeout_s)
        else:
            out = r.stdout or ""
            if "PROBE_OK" in out:
                if " cpu " in out or out.rstrip().endswith(" cpu"):
                    return "ok"  # default backend IS cpu; nothing to pin
                log.info("device probe: %s", out.strip())
                return "ok"
            tail = ((r.stderr or "").strip().splitlines() or ["<no output>"])
            log.warning("device probe %d/%d: backend error: %s",
                        k + 1, attempts, tail[-1])
        if k + 1 < attempts:
            time.sleep(float(os.environ.get("HEATMAP_PROBE_BACKOFF_S", "5")))
    log.warning(
        "accelerator unreachable; pinning this process to the CPU backend "
        "(set HEATMAP_PLATFORM or HEATMAP_DEVICE_PROBE=0 to override)")
    import jax

    jax.config.update("jax_platforms", "cpu")
    # children (multihost workers, supervised restarts) inherit the choice
    os.environ["HEATMAP_PLATFORM"] = "cpu"
    return "fallback"
