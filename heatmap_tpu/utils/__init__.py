"""utils — small shared host-side helpers."""
