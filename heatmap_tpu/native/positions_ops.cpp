// positions_ops.cpp — columnar positions -> BSON pipeline-update ops.
//
// The positions_latest sink writes one *aggregation-pipeline* update per
// vehicle (the race-free form of the reference's conditional upsert,
// heatmap_stream.py:198-237; see sink/mongo.py::_monotonic_update_pipeline):
//
//   { q: {_id: "prov|veh"},
//     u: [ {$replaceRoot: {newRoot:
//            {$cond: [ {$or: [ {$lte: [{$ifNull: ["$ts", null]}, null]},
//                              {$lt:  ["$ts", <ts>]} ]},
//                      {_id, provider, vehicleId, ts, loc{Point}},
//                      "$$ROOT" ]} }} ],
//     upsert: true }
//
// Each op is ~40 BSON elements; at fleet scale (one op per vehicle per
// batch) encoding them in Python dominates the sink thread.  This builds
// the ops straight from columnar arrays + joined string buffers; output
// framing matches tile_ops.cpp (concatenated op docs + per-op end offsets
// for 1000-op chunking, shipped as OP_MSG document sequences).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Buf {
    uint8_t* p;
    int64_t cap;
    int64_t len = 0;
    bool overflow = false;

    void need(int64_t n) {
        if (len + n > cap) overflow = true;
    }
    void raw(const void* src, int64_t n) {
        need(n);
        if (!overflow) std::memcpy(p + len, src, n);
        len += n;
    }
    void u8(uint8_t v) { raw(&v, 1); }
    void i32(int32_t v) { raw(&v, 4); }
    void i64(int64_t v) { raw(&v, 8); }
    void f64(double v) { raw(&v, 8); }
    void cstr(const char* s) { raw(s, (int64_t)std::strlen(s) + 1); }
    int64_t mark() { int64_t at = len; i32(0); return at; }
    void patch(int64_t at) {
        if (overflow) return;
        int32_t total = (int32_t)(len - at);
        std::memcpy(p + at, &total, 4);
    }
};

void el_str(Buf& b, const char* name, const char* s, int64_t n) {
    b.u8(0x02); b.cstr(name);
    b.i32((int32_t)(n + 1)); b.raw(s, n); b.u8(0);
}
void el_f64(Buf& b, const char* name, double v) { b.u8(0x01); b.cstr(name); b.f64(v); }
void el_dt(Buf& b, const char* name, int64_t ms) { b.u8(0x09); b.cstr(name); b.i64(ms); }
void el_null(Buf& b, const char* name) { b.u8(0x0a); b.cstr(name); }
void el_bool(Buf& b, const char* name, bool v) { b.u8(0x08); b.cstr(name); b.u8(v ? 1 : 0); }
int64_t doc_open(Buf& b, const char* name) { b.u8(0x03); b.cstr(name); return b.mark(); }
int64_t arr_open(Buf& b, const char* name) { b.u8(0x04); b.cstr(name); return b.mark(); }
void closing(Buf& b, int64_t at) { b.u8(0); b.patch(at); }

}  // namespace

extern "C" {

// Inputs are columnar over n changed vehicles: lat/lon degrees (f32),
// ts_ms epoch milliseconds (i64), and the provider / vehicle strings as
// joined UTF-8 buffers with (n+1) end-exclusive offsets.  Output/return
// contract matches enc_tile_ops: concatenated op docs, per-op END
// offsets, -needed on insufficient cap.
int64_t enc_position_ops(
    const float* lat, const float* lon, const int64_t* ts_ms, int64_t n,
    const uint8_t* prov_bytes, const int64_t* prov_off,
    const uint8_t* veh_bytes, const int64_t* veh_off,
    uint8_t* out, int64_t cap,
    int64_t* end_offsets, int64_t* bytes_out) {
    Buf b{out, cap};
    std::vector<char> idbuf;
    for (int64_t r = 0; r < n; r++) {
        const char* prov = (const char*)prov_bytes + prov_off[r];
        int64_t pn = prov_off[r + 1] - prov_off[r];
        const char* veh = (const char*)veh_bytes + veh_off[r];
        int64_t vn = veh_off[r + 1] - veh_off[r];
        idbuf.resize((size_t)(pn + vn + 2));
        std::memcpy(idbuf.data(), prov, pn);
        idbuf[pn] = '|';
        std::memcpy(idbuf.data() + pn + 1, veh, vn);
        int64_t idn = pn + 1 + vn;

        int64_t op = b.mark();
        {
            int64_t q = doc_open(b, "q");
            el_str(b, "_id", idbuf.data(), idn);
            closing(b, q);

            int64_t u = arr_open(b, "u");           // pipeline = array
            {
                int64_t st = doc_open(b, "0");      // one stage
                int64_t rr = doc_open(b, "$replaceRoot");
                int64_t nr = doc_open(b, "newRoot");
                int64_t cond = arr_open(b, "$cond");
                {
                    // [0] condition: {$or: [...]}
                    int64_t c0 = doc_open(b, "0");
                    int64_t orr = arr_open(b, "$or");
                    {
                        int64_t o0 = doc_open(b, "0");
                        int64_t lte = arr_open(b, "$lte");
                        {
                            int64_t ifn_doc = doc_open(b, "0");
                            int64_t ifn = arr_open(b, "$ifNull");
                            el_str(b, "0", "$ts", 3);
                            el_null(b, "1");
                            closing(b, ifn);
                            closing(b, ifn_doc);
                            el_null(b, "1");
                        }
                        closing(b, lte);
                        closing(b, o0);

                        int64_t o1 = doc_open(b, "1");
                        int64_t lt = arr_open(b, "$lt");
                        el_str(b, "0", "$ts", 3);
                        el_dt(b, "1", ts_ms[r]);
                        closing(b, lt);
                        closing(b, o1);
                    }
                    closing(b, orr);
                    closing(b, c0);

                    // [1] then-branch: the replacement document
                    int64_t d = doc_open(b, "1");
                    el_str(b, "_id", idbuf.data(), idn);
                    el_str(b, "provider", prov, pn);
                    el_str(b, "vehicleId", veh, vn);
                    el_dt(b, "ts", ts_ms[r]);
                    {
                        int64_t loc = doc_open(b, "loc");
                        el_str(b, "type", "Point", 5);
                        int64_t coords = arr_open(b, "coordinates");
                        el_f64(b, "0", (double)lon[r]);
                        el_f64(b, "1", (double)lat[r]);
                        closing(b, coords);
                        closing(b, loc);
                    }
                    closing(b, d);

                    // [2] else-branch: keep the stored document
                    el_str(b, "2", "$$ROOT", 6);
                }
                closing(b, cond);
                closing(b, nr);
                closing(b, rr);
                closing(b, st);
            }
            closing(b, u);

            el_bool(b, "upsert", true);
        }
        b.u8(0);
        b.patch(op);
        end_offsets[r] = b.len;
    }
    *bytes_out = b.len;
    if (b.overflow) return -b.len;
    return n;
}

}  // extern "C"
