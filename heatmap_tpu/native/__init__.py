"""native — C++ host components, loaded via ctypes.

The hot ingest decode (JSON-lines → columnar arrays) runs in C++ at memory
speed (decoder.cpp); the Python ``parse_events`` path stays as the portable
fallback and the correctness oracle (they are differential-tested against
each other).  The library builds lazily with g++ on first use and is cached
next to the source keyed by its hash; if no compiler is available,
``NativeDecoder.available()`` is False and callers fall back to Python.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading

import numpy as np

log = logging.getLogger(__name__)

_HERE = os.path.dirname(__file__)
_SRCS = [os.path.join(_HERE, "decoder.cpp"),
         os.path.join(_HERE, "tile_ops.cpp"),
         os.path.join(_HERE, "kafka_codec.cpp"),
         os.path.join(_HERE, "positions_ops.cpp"),
         os.path.join(_HERE, "h3_snap.cpp")]
_LOCK = threading.Lock()
_LIB = None
_LIB_ERR: str | None = None


def _build_lib() -> str:
    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as fh:
            h.update(fh.read())
    digest = h.hexdigest()[:16]
    cache_dir = os.environ.get(
        "HEATMAP_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "heatmap-tpu-native"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"_native-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
    import platform

    if platform.machine().lower() in ("x86_64", "amd64"):
        cmd.append("-msse4.2")  # hardware CRC32C (kafka_codec.cpp)
    cmd += [*_SRCS, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


def _load():
    global _LIB, _LIB_ERR
    with _LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build_lib())
        except (OSError, subprocess.CalledProcessError, FileNotFoundError) as e:
            _LIB_ERR = str(e)
            log.warning("native decoder unavailable (%s); using Python parse",
                        _LIB_ERR.splitlines()[0] if _LIB_ERR else e)
            return None
        lib.dec_new.restype = ctypes.c_void_p
        lib.dec_free.argtypes = [ctypes.c_void_p]
        lib.dec_intern_count.restype = ctypes.c_int64
        lib.dec_intern_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        # void* (not c_char_p): names may contain NUL bytes, so they are
        # read back by explicit length via string_at
        lib.dec_intern_get.restype = ctypes.c_void_p
        lib.dec_intern_get.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int64]
        lib.dec_intern_len.restype = ctypes.c_int64
        lib.dec_intern_len.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int64]
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.dec_decode.restype = ctypes.c_int64
        lib.dec_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            f32p, f32p, f32p, i32p, i32p, i32p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.enc_tile_ops.restype = ctypes.c_int64
        lib.enc_tile_ops.argtypes = [
            u32p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
            u8p, ctypes.c_int64,
            i64p, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.enc_wire_cols.restype = ctypes.c_int64
        lib.enc_wire_cols.argtypes = [
            u8p, ctypes.c_int64,
            i64p, i64p,
            ctypes.c_int32, i64p,
            ctypes.c_int32, i64p, ctypes.c_int64,
            ctypes.c_int32, i64p, ctypes.c_int64,
            i64p, ctypes.c_int64,
            i64p, ctypes.c_int64,
            u8p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.kc_crc32c.restype = ctypes.c_uint32
        lib.kc_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_uint32]
        lib.cf_strtab_offsets.restype = ctypes.c_int
        lib.cf_strtab_offsets.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, i32p, i32p,
        ]
        lib.kc_decode_values.restype = ctypes.c_int64
        lib.kc_decode_values.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            u8p, ctypes.c_int64,
            i64p, i64p, ctypes.c_int64,
            i64p,
        ]
        lib.dec_decode_binary.restype = ctypes.c_int64
        lib.dec_decode_binary.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            f32p, f32p, f32p, i32p, i32p, i32p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.enc_position_ops.restype = ctypes.c_int64
        lib.enc_position_ops.argtypes = [
            f32p, f32p, i64p, ctypes.c_int64,
            u8p, i64p, u8p, i64p,
            u8p, ctypes.c_int64,
            i64p, ctypes.POINTER(ctypes.c_int64),
        ]
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        _snap_args = [
            f32p, f32p, ctypes.c_int64, ctypes.c_int,
            f64p, f64p, f64p,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            i32p, i32p, i32p, i32p, i32p, i32p, i32p,
            ctypes.c_int,
            u32p, u32p,
        ]
        lib.h3_snap_f32.argtypes = _snap_args
        # scalar-only entry: the reference path the SIMD block path is
        # differential-tested against (tests/test_native_snap.py)
        lib.h3_snap_f32_scalar.argtypes = _snap_args
        _LIB = lib
        return _LIB


def strtab_offsets_native(blob: bytes, n: int):
    """(offsets, lengths) int32 arrays for a colfmt strtab blob, parsed
    in C++ (decoder.cpp cf_strtab_offsets).  None when no toolchain
    (caller falls back to the Python parse); ValueError when an entry
    runs past the blob (same rejection the Python parse performs)."""
    lib = _load()
    if lib is None:
        return None
    # bound BEFORE allocating: n is an unvalidated u32 from the record
    # header, and every entry needs at least its 2 length bytes — a
    # corrupt record claiming n=0xFFFFFFFF must be a cheap reject, not
    # a pair of giant allocations (r5 review finding)
    if n < 0 or 2 * n > len(blob):
        raise ValueError("strtab count exceeds blob")
    offs = np.empty(n, np.int32)
    lens = np.empty(n, np.int32)
    if lib.cf_strtab_offsets(blob, len(blob), n, offs, lens) != 0:
        raise ValueError("malformed strtab blob")
    return offs, lens


def crc32c_native(data: bytes, crc: int = 0) -> "int | None":
    """Hardware/sliced CRC32C (kafka_codec.cpp); None without a toolchain."""
    lib = _load()
    if lib is None:
        return None
    return int(lib.kc_crc32c(data, len(data), crc))


class KafkaValues:
    """Result of kafka_decode_values: record values joined under the
    requested framing — newline-terminated lines ("newline", JSON values;
    a blob containing newline-bearing values returns None instead and
    callers take the Python record path) or u32-length-prefixed frames
    ("lp", binary event values, stream/binfmt.py) — plus the bookkeeping
    the consumer's partial-take logic needs."""

    __slots__ = ("blob", "val_off", "val_pos", "next_offset",
                 "skipped_batches", "n_null")

    def __init__(self, blob, val_off, val_pos, next_offset, skipped,
                 n_null):
        self.blob = blob
        self.val_off = val_off
        self.val_pos = val_pos
        self.next_offset = next_offset
        self.skipped_batches = skipped
        self.n_null = n_null

    def __len__(self):
        return len(self.val_off)


def kafka_decode_values(blob: bytes, start_offset: int,
                        verify_crc: bool = True,
                        framing: str = "newline") -> "KafkaValues | None":
    """Decode a Fetch records blob straight to a joined values buffer
    (kafka_codec.cpp): framing="newline" for JSON values, "lp" for
    u32-length-prefixed binary event values (stream/binfmt.py).  None when
    no toolchain exists, the blob's varints are malformed, or (newline
    framing only) a value contains raw newlines — callers fall back to the
    Python record path (kafka.records.decode_batches_tolerant)."""
    lib = _load()
    if lib is None:
        return None
    lp = framing == "lp"
    n = len(blob)
    cap_vals = n // 6 + 8
    out = np.empty(n + cap_vals * (4 if lp else 1) + 16, np.uint8)
    val_off = np.empty(cap_vals, np.int64)
    val_pos = np.empty(cap_vals, np.int64)
    state = np.zeros(5, np.int64)
    nv = lib.kc_decode_values(blob, n, start_offset, int(verify_crc),
                              int(lp), out, len(out), val_off, val_pos,
                              cap_vals, state)
    if nv < 0 or state[3] > 0:  # malformed varints / newline-bearing values
        return None
    nv = int(nv)
    return KafkaValues(
        out[:int(state[0])].tobytes(), val_off[:nv].copy(),
        val_pos[:nv].copy(), int(state[1]), int(state[2]), int(state[4]),
    )


def maybe_decoder(logger=None) -> "NativeDecoder | None":
    """A NativeDecoder when the toolchain allows, else None (callers fall
    back to json.loads).  One place for the probe so sources don't drift."""
    try:
        if NativeDecoder.available():
            return NativeDecoder()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        if logger is not None:
            logger.info("native decoder unavailable (%s)", e)
    return None


def decode_lines(dec: "NativeDecoder", values) -> "object":
    """Decode an iterable of raw JSON document byte-strings to columns.

    Values are joined with newlines for the line-oriented scanner.  A value
    containing raw newline bytes (pretty-printed JSON) takes the slow path:
    json.loads validates it with the exact semantics of the no-toolchain
    fallback — valid documents are re-serialized compact and batched,
    invalid ones are dropped and counted (blind newline-flattening would
    instead ACCEPT documents with a raw 0x0A inside a string, mutating
    their data, where json.loads rejects them)."""
    import json

    cleaned = []
    pre_dropped = 0
    for v in values:
        if b"\n" in v or b"\r" in v:
            try:
                cleaned.append(json.dumps(json.loads(v)).encode())
            except (json.JSONDecodeError, UnicodeDecodeError):
                pre_dropped += 1
        else:
            cleaned.append(v)
    if not cleaned:
        from heatmap_tpu.stream.events import columns_from_arrays

        cols = columns_from_arrays([], [], [], [])
        cols.n_dropped = pre_dropped
        return cols
    cols, _ = dec.decode(b"\n".join(cleaned) + b"\n", final=True)
    cols.n_dropped += pre_dropped
    return cols


class NativeDecoder:
    """Streaming JSON-lines event decoder with persistent string interning.

    ``decode(data)`` accepts a bytes block of newline-separated event JSON
    and returns (EventColumns, consumed_bytes); partial trailing lines are
    left unconsumed so callers can stream chunked reads.  Pass
    ``final=True`` on the last chunk so a complete terminal record without
    a trailing newline is flushed rather than held back.
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native decoder unavailable: {_LIB_ERR}")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.dec_new())
        self._providers: list[str] = []
        self._vehicles: list[str] = []

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def close(self):
        if self._h:
            self._lib.dec_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass

    def _refresh_interns(self):
        for which, cache in ((0, self._providers), (1, self._vehicles)):
            n = self._lib.dec_intern_count(self._h, which)
            for i in range(len(cache), n):
                ln = self._lib.dec_intern_len(self._h, which, i)
                raw = ctypes.string_at(
                    self._lib.dec_intern_get(self._h, which, i), ln)
                # surrogatepass: the C side emits WTF-8 for lone \uD800-style
                # escapes, matching what Python's json preserves in its strs
                try:
                    cache.append(raw.decode("utf-8", "surrogatepass"))
                except UnicodeDecodeError:
                    cache.append(raw.decode("utf-8", "replace"))

    def decode(self, data: bytes, max_events: int | None = None,
               final: bool = False):
        from heatmap_tpu.stream.events import columns_from_arrays

        orig_len = len(data)
        if final and data and not data.endswith(b"\n"):
            # flush mode: at EOF a complete last record may lack the
            # newline the streaming contract waits for
            data = data + b"\n"
        cap = max_events if max_events is not None else max(1, data.count(b"\n") + 1)
        lat = np.empty(cap, np.float32)
        lon = np.empty(cap, np.float32)
        speed = np.empty(cap, np.float32)
        ts = np.empty(cap, np.int32)
        pid = np.empty(cap, np.int32)
        vid = np.empty(cap, np.int32)
        dropped = ctypes.c_int64(0)
        consumed = ctypes.c_int64(0)
        n = self._lib.dec_decode(
            self._h, data, len(data), cap,
            lat, lon, speed, ts, pid, vid,
            ctypes.byref(dropped), ctypes.byref(consumed),
        )
        self._refresh_interns()
        cols = columns_from_arrays(
            lat[:n], lon[:n], speed[:n], ts[:n],
            provider_id=pid[:n], vehicle_id=vid[:n],
            providers=self._providers, vehicles=self._vehicles,
        )
        cols.n_dropped = int(dropped.value)
        return cols, min(int(consumed.value), orig_len)

    def decode_binary(self, data: bytes, max_events: int | None = None):
        """Like ``decode`` but for a u32-length-prefixed stream of binary
        event records (stream/binfmt.py layout); shares the same intern
        tables, so mixed JSON/binary sessions keep stable ids."""
        from heatmap_tpu.stream.events import columns_from_arrays

        cap = (max_events if max_events is not None
               else len(data) // 36 + 1)  # min frame = 4 + 32-byte header
        lat = np.empty(cap, np.float32)
        lon = np.empty(cap, np.float32)
        speed = np.empty(cap, np.float32)
        ts = np.empty(cap, np.int32)
        pid = np.empty(cap, np.int32)
        vid = np.empty(cap, np.int32)
        dropped = ctypes.c_int64(0)
        consumed = ctypes.c_int64(0)
        n = self._lib.dec_decode_binary(
            self._h, data, len(data), cap,
            lat, lon, speed, ts, pid, vid,
            ctypes.byref(dropped), ctypes.byref(consumed),
        )
        self._refresh_interns()
        cols = columns_from_arrays(
            lat[:n], lon[:n], speed[:n], ts[:n],
            provider_id=pid[:n], vehicle_id=vid[:n],
            providers=self._providers, vehicles=self._vehicles,
        )
        cols.n_dropped = int(dropped.value)
        return cols, int(consumed.value)


def _encode_with_resize(call, cap, what):
    """Run a native encoder (``call(out, cap) -> n_docs | -needed_bytes``)
    once; on overflow reallocate to the exact reported size and retry."""
    out = np.empty(cap, np.uint8)
    got = call(out, cap)
    if got < 0:
        cap = int(-got) + 1024
        out = np.empty(cap, np.uint8)
        got = call(out, cap)
        if got < 0:
            raise RuntimeError(
                f"native {what} encode overflow after resize")
    return out, int(got)


class NativeTileOps:
    """Packed-emit rows -> wire-ready BSON update ops (tile_ops.cpp).

    ``encode(body, ...)`` takes the packed emit matrix's BODY rows
    ((E, 13) uint32, i.e. ``packed[1:]``) and returns
    ``(ops_bytes, end_offsets, n_docs)`` where ``ops_bytes`` is the
    concatenated update-op documents for an OP_MSG "updates" document
    sequence and ``end_offsets[i]`` is the byte end of op i (for 1000-op
    chunking).  Rows with valid==0 or count<=0 are skipped, mirroring
    stream.runtime's doc builder.
    """

    # conservative per-doc bound: fixed fields ~430B + _id/cellId strings
    _DOC_BOUND = 640

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native tile encoder unavailable: {_LIB_ERR}")
        self._lib = lib

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def encode(self, body: np.ndarray, city: str, grid: str,
               window_s: int, ttl_minutes: int,
               window_minutes_tag: int = 0, with_p95: bool = True):
        body = np.ascontiguousarray(body, np.uint32)
        if body.ndim != 2 or body.shape[1] != 13:
            raise ValueError(f"body must be (E, 13) uint32, got {body.shape}")
        n_rows = body.shape[0]
        offsets = np.empty(max(n_rows, 1), np.int64)
        nbytes = ctypes.c_int64(0)

        def call(out, cap):
            return self._lib.enc_tile_ops(
                body, n_rows, city.encode(), grid.encode(),
                window_s * 1000, ttl_minutes * 60_000,
                window_minutes_tag, int(bool(with_p95)),
                out, cap, offsets, ctypes.byref(nbytes),
            )

        out, n = _encode_with_resize(
            call, n_rows * self._DOC_BOUND + 1024, "tile")
        return out[:int(nbytes.value)].tobytes(), offsets[:n].copy(), n


class NativeWireOps:
    """Binary wire-frame column writer (tile_ops.cpp enc_wire_cols) —
    the serve tier's compact tile/delta frame body.  The caller
    (serve/wire.py) assembles the header and makes the per-column
    fixed-point-vs-f64 decision; this writes the varint/zigzag/raw
    columns at memory speed, byte-identical to the pure-Python writer
    (differential-tested in tests/test_wire.py)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native wire encoder unavailable: "
                               f"{_LIB_ERR}")
        self._lib = lib

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def encode_body(self, flags, deltas, counts, s_enc, speeds,
                    p_enc, p95, d_enc, stddev, wmin,
                    overrides) -> bytes:
        n = len(flags)
        nbytes = ctypes.c_int64(0)

        def call(out, cap):
            return self._lib.enc_wire_cols(
                flags, n, deltas, counts,
                s_enc, speeds,
                p_enc, p95, len(p95),
                d_enc, stddev, len(stddev),
                wmin, len(wmin),
                overrides, len(overrides),
                out, cap, ctypes.byref(nbytes))

        # worst case per doc: flag 1B + delta/count varints ≤ 20B +
        # f64 speed 8B (+ subset columns sized separately)
        cap = (n * 32 + 8 * (len(p95) + len(stddev) + len(overrides))
               + 10 * len(wmin) + 64)
        out, rc = _encode_with_resize(call, cap, "wire")
        if rc < 0:  # pragma: no cover - resize retried once already
            raise RuntimeError("native wire encode overflow")
        return out[:int(nbytes.value)].tobytes()


def maybe_wire_ops(logger=None) -> "NativeWireOps | None":
    """A NativeWireOps when the toolchain allows, else None (callers
    fall back to the pure-Python column writer)."""
    try:
        if NativeWireOps.available():
            return NativeWireOps()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        if logger is not None:
            logger.info("native wire encoder unavailable (%s)", e)
    return None


def maybe_tile_ops(logger=None) -> "NativeTileOps | None":
    """A NativeTileOps when the toolchain allows, else None (callers fall
    back to the Python doc builder)."""
    try:
        if NativeTileOps.available():
            return NativeTileOps()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        if logger is not None:
            logger.info("native tile encoder unavailable (%s)", e)
    return None


class NativePositionOps:
    """Columnar changed-vehicle rows -> wire-ready monotonic pipeline-update
    ops (positions_ops.cpp).  ``encode(rows)`` takes a
    sink.base.PositionRows and returns (ops_bytes, end_offsets, n)."""

    # fixed pipeline skeleton ~330B + strings (id appears twice)
    _DOC_BOUND = 420

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native position encoder unavailable: "
                               f"{_LIB_ERR}")
        self._lib = lib

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def encode(self, rows):
        n = len(rows.ts_ms)
        prov = [p.encode("utf-8") for p in rows.providers]
        veh = [v.encode("utf-8") for v in rows.vehicles]
        prov_off = np.zeros(n + 1, np.int64)
        veh_off = np.zeros(n + 1, np.int64)
        np.cumsum([len(p) for p in prov], out=prov_off[1:])
        np.cumsum([len(v) for v in veh], out=veh_off[1:])
        prov_buf = np.frombuffer(b"".join(prov) or b"\0", np.uint8)
        veh_buf = np.frombuffer(b"".join(veh) or b"\0", np.uint8)
        str_bytes = int(prov_off[-1] + veh_off[-1])
        offsets = np.empty(max(n, 1), np.int64)
        nbytes = ctypes.c_int64(0)
        lat = np.ascontiguousarray(rows.lat, np.float32)
        lon = np.ascontiguousarray(rows.lon, np.float32)
        ts_ms = np.ascontiguousarray(rows.ts_ms, np.int64)

        def call(out, cap):
            return self._lib.enc_position_ops(
                lat, lon, ts_ms, n, prov_buf, prov_off, veh_buf, veh_off,
                out, cap, offsets, ctypes.byref(nbytes),
            )

        out, _ = _encode_with_resize(
            call, n * self._DOC_BOUND + 3 * str_bytes + 1024, "position")
        return out[:int(nbytes.value)].tobytes(), offsets[:n].copy(), n


def maybe_position_ops(logger=None) -> "NativePositionOps | None":
    try:
        if NativePositionOps.available():
            return NativePositionOps()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        if logger is not None:
            logger.info("native position encoder unavailable (%s)", e)
    return None


class NativeH3Snap:
    """Scalar C++ H3 forward snap over f32 arrays (h3_snap.cpp) — the
    CPU-backend fast path for hexgrid (HEATMAP_H3_IMPL=native); computes
    in f64 internally, matching the host oracle's rounding rather than
    the f32 XLA device path (points within ~0.4 m of a cell edge at
    res 9 may differ from the f32 snap — both are valid snaps)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native h3 snap unavailable: {_LIB_ERR}")
        self._lib = lib
        from heatmap_tpu.hexgrid.device import (
            _DeviceTables,
            _projection_bases,
        )
        from heatmap_tpu.hexgrid.constants import (
            FACE_CENTER_XYZ,
            M_AP7_ROT_RADS,
            M_SQRT7,
        )
        from heatmap_tpu.hexgrid.mathlib import (
            _DOWN_AP7,
            _DOWN_AP7R,
            K_AXES_DIGIT,
        )
        import math

        u1, u2 = _projection_bases()
        T = _DeviceTables()
        self._face_xyz = np.ascontiguousarray(FACE_CENTER_XYZ, np.float64)
        self._u1 = np.ascontiguousarray(u1, np.float64)
        self._u2 = np.ascontiguousarray(u2, np.float64)
        self._rot_cos = float(math.cos(M_AP7_ROT_RADS))
        self._rot_sin = float(math.sin(M_AP7_ROT_RADS))
        self._sqrt7 = float(M_SQRT7)
        self._down_ap7 = np.ascontiguousarray(
            np.asarray(_DOWN_AP7, np.int32).reshape(-1))
        self._down_ap7r = np.ascontiguousarray(
            np.asarray(_DOWN_AP7R, np.int32).reshape(-1))
        self._bc = np.ascontiguousarray(T.face_ijk_bc)
        self._rot = np.ascontiguousarray(T.face_ijk_rot)
        self._pent = np.ascontiguousarray(T.bc_pent)
        self._cw_off = np.ascontiguousarray(T.pent_cw_offset)
        self._ccw_pow = np.ascontiguousarray(T.ccw_pow)
        self._k_digit = int(K_AXES_DIGIT)

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def snap(self, lat_rad, lng_rad, res: int, scalar: bool = False):
        """(N,) f32 radians -> (hi, lo) uint32 arrays.  res <= 10 (the
        packed-digit-chain form; higher res goes through the XLA path).
        ``scalar=True`` forces the scalar reference path (bypassing the
        AVX-512 block path) — for differential tests only."""
        if not 0 <= res <= 10:
            raise ValueError(f"native snap supports res 0..10, got {res}")
        lat = np.ascontiguousarray(lat_rad, np.float32).reshape(-1)
        lng = np.ascontiguousarray(lng_rad, np.float32).reshape(-1)
        if lng.shape[0] != lat.shape[0]:
            # the C++ loop is sized from lat; a silent mismatch would
            # read past the lng buffer
            raise ValueError(f"lat/lng length mismatch: "
                             f"{lat.shape[0]} vs {lng.shape[0]}")
        n = lat.shape[0]
        hi = np.empty(n, np.uint32)
        lo = np.empty(n, np.uint32)
        fn = (self._lib.h3_snap_f32_scalar if scalar
              else self._lib.h3_snap_f32)
        fn(lat, lng, n, res, self._face_xyz, self._u1, self._u2,
           self._rot_cos, self._rot_sin, float(self._sqrt7 ** res),
           self._down_ap7, self._down_ap7r, self._bc, self._rot,
           self._pent, self._cw_off, self._ccw_pow, self._k_digit,
           hi, lo)
        shape = np.shape(lat_rad)
        return hi.reshape(shape), lo.reshape(shape)


def maybe_h3_snap(logger=None) -> "NativeH3Snap | None":
    try:
        if NativeH3Snap.available():
            return NativeH3Snap()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        if logger is not None:
            logger.info("native h3 snap unavailable (%s)", e)
    return None
