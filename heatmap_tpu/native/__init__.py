"""native — C++ host components, loaded via ctypes.

The hot ingest decode (JSON-lines → columnar arrays) runs in C++ at memory
speed (decoder.cpp); the Python ``parse_events`` path stays as the portable
fallback and the correctness oracle (they are differential-tested against
each other).  The library builds lazily with g++ on first use and is cached
next to the source keyed by its hash; if no compiler is available,
``NativeDecoder.available()`` is False and callers fall back to Python.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading

import numpy as np

log = logging.getLogger(__name__)

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "decoder.cpp")
_LOCK = threading.Lock()
_LIB = None
_LIB_ERR: str | None = None


def _build_lib() -> str:
    with open(_SRC, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    cache_dir = os.environ.get(
        "HEATMAP_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "heatmap-tpu-native"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"_decoder-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


def _load():
    global _LIB, _LIB_ERR
    with _LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build_lib())
        except (OSError, subprocess.CalledProcessError, FileNotFoundError) as e:
            _LIB_ERR = str(e)
            log.warning("native decoder unavailable (%s); using Python parse",
                        _LIB_ERR.splitlines()[0] if _LIB_ERR else e)
            return None
        lib.dec_new.restype = ctypes.c_void_p
        lib.dec_free.argtypes = [ctypes.c_void_p]
        lib.dec_intern_count.restype = ctypes.c_int64
        lib.dec_intern_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        # void* (not c_char_p): names may contain NUL bytes, so they are
        # read back by explicit length via string_at
        lib.dec_intern_get.restype = ctypes.c_void_p
        lib.dec_intern_get.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int64]
        lib.dec_intern_len.restype = ctypes.c_int64
        lib.dec_intern_len.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int64]
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.dec_decode.restype = ctypes.c_int64
        lib.dec_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            f32p, f32p, f32p, i32p, i32p, i32p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        _LIB = lib
        return _LIB


def maybe_decoder(logger=None) -> "NativeDecoder | None":
    """A NativeDecoder when the toolchain allows, else None (callers fall
    back to json.loads).  One place for the probe so sources don't drift."""
    try:
        if NativeDecoder.available():
            return NativeDecoder()
    except Exception as e:  # pragma: no cover - toolchain-dependent
        if logger is not None:
            logger.info("native decoder unavailable (%s)", e)
    return None


def decode_lines(dec: "NativeDecoder", values) -> "object":
    """Decode an iterable of raw JSON document byte-strings to columns.

    Values are joined with newlines for the line-oriented scanner.  A value
    containing raw newline bytes (pretty-printed JSON) takes the slow path:
    json.loads validates it with the exact semantics of the no-toolchain
    fallback — valid documents are re-serialized compact and batched,
    invalid ones are dropped and counted (blind newline-flattening would
    instead ACCEPT documents with a raw 0x0A inside a string, mutating
    their data, where json.loads rejects them)."""
    import json

    cleaned = []
    pre_dropped = 0
    for v in values:
        if b"\n" in v or b"\r" in v:
            try:
                cleaned.append(json.dumps(json.loads(v)).encode())
            except (json.JSONDecodeError, UnicodeDecodeError):
                pre_dropped += 1
        else:
            cleaned.append(v)
    if not cleaned:
        from heatmap_tpu.stream.events import columns_from_arrays

        cols = columns_from_arrays([], [], [], [])
        cols.n_dropped = pre_dropped
        return cols
    cols, _ = dec.decode(b"\n".join(cleaned) + b"\n", final=True)
    cols.n_dropped += pre_dropped
    return cols


class NativeDecoder:
    """Streaming JSON-lines event decoder with persistent string interning.

    ``decode(data)`` accepts a bytes block of newline-separated event JSON
    and returns (EventColumns, consumed_bytes); partial trailing lines are
    left unconsumed so callers can stream chunked reads.  Pass
    ``final=True`` on the last chunk so a complete terminal record without
    a trailing newline is flushed rather than held back.
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native decoder unavailable: {_LIB_ERR}")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.dec_new())
        self._providers: list[str] = []
        self._vehicles: list[str] = []

    @staticmethod
    def available() -> bool:
        return _load() is not None

    def close(self):
        if self._h:
            self._lib.dec_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass

    def _refresh_interns(self):
        for which, cache in ((0, self._providers), (1, self._vehicles)):
            n = self._lib.dec_intern_count(self._h, which)
            for i in range(len(cache), n):
                ln = self._lib.dec_intern_len(self._h, which, i)
                raw = ctypes.string_at(
                    self._lib.dec_intern_get(self._h, which, i), ln)
                # surrogatepass: the C side emits WTF-8 for lone \uD800-style
                # escapes, matching what Python's json preserves in its strs
                try:
                    cache.append(raw.decode("utf-8", "surrogatepass"))
                except UnicodeDecodeError:
                    cache.append(raw.decode("utf-8", "replace"))

    def decode(self, data: bytes, max_events: int | None = None,
               final: bool = False):
        from heatmap_tpu.stream.events import columns_from_arrays

        orig_len = len(data)
        if final and data and not data.endswith(b"\n"):
            # flush mode: at EOF a complete last record may lack the
            # newline the streaming contract waits for
            data = data + b"\n"
        cap = max_events if max_events is not None else max(1, data.count(b"\n") + 1)
        lat = np.empty(cap, np.float32)
        lon = np.empty(cap, np.float32)
        speed = np.empty(cap, np.float32)
        ts = np.empty(cap, np.int32)
        pid = np.empty(cap, np.int32)
        vid = np.empty(cap, np.int32)
        dropped = ctypes.c_int64(0)
        consumed = ctypes.c_int64(0)
        n = self._lib.dec_decode(
            self._h, data, len(data), cap,
            lat, lon, speed, ts, pid, vid,
            ctypes.byref(dropped), ctypes.byref(consumed),
        )
        self._refresh_interns()
        cols = columns_from_arrays(
            lat[:n], lon[:n], speed[:n], ts[:n],
            provider_id=pid[:n], vehicle_id=vid[:n],
            providers=self._providers, vehicles=self._vehicles,
        )
        cols.n_dropped = int(dropped.value)
        return cols, min(int(consumed.value), orig_len)
