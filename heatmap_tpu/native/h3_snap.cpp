// Host-side H3 forward snap: (lat, lng) radians -> 64-bit cell index.
//
// The CPU-backend counterpart of hexgrid/device.py's vectorized XLA snap
// (itself the replacement for the reference's per-row geo_to_h3 UDF,
// reference: heatmap_stream.py:65-75).  On CPU the XLA snap dominates the
// fold (~80% of batch wall at res 8); this scalar C++ port of the same
// trig-free gnomonic + packed-digit-chain algorithm runs ~an order of
// magnitude faster per core and computes in double throughout, matching
// the f64 host oracle (hexgrid/host.py) rather than the f32 device path.
//
// No code is copied from the C h3 library; this is a port of this
// package's own device.py math (see hexgrid/__init__.py provenance
// note).  All lookup tables are PASSED IN from Python — the generated
// tables in hexgrid/_tables.py stay the single source of truth.

#include <cstdint>
#include <cmath>

namespace {

inline int64_t fdiv(int64_t a, int64_t b) {
  // floor division (jnp.floor_divide semantics for negative a)
  int64_t q = a / b, r = a % b;
  return (r != 0 && ((r < 0) != (b < 0))) ? q - 1 : q;
}

inline void ijk_normalize(int64_t& i, int64_t& j, int64_t& k) {
  // mirror mathlib.ijk_normalize: fold negative axes, subtract min
  int64_t neg = i < 0 ? i : 0;
  j -= neg; k -= neg; i -= neg;
  neg = j < 0 ? j : 0;
  i -= neg; k -= neg; j -= neg;
  neg = k < 0 ? k : 0;
  i -= neg; j -= neg; k -= neg;
  int64_t m = i < j ? i : j;
  if (k < m) m = k;
  i -= m; j -= m; k -= m;
}

inline int64_t div7_round(int64_t x) {  // round-half-away of x/7 (exact)
  return fdiv(2 * x + 7, 14);
}

inline void up_ap7(int64_t& i, int64_t& j, int64_t& k) {
  int64_t ii = i - k, jj = j - k;
  i = div7_round(3 * ii - jj);
  j = div7_round(ii + 2 * jj);
  k = 0;
  ijk_normalize(i, j, k);
}

inline void up_ap7r(int64_t& i, int64_t& j, int64_t& k) {
  int64_t ii = i - k, jj = j - k;
  i = div7_round(2 * ii + jj);
  j = div7_round(3 * jj - ii);
  k = 0;
  ijk_normalize(i, j, k);
}

inline void lin3(const int32_t* m /*9 ints: iv, jv, kv*/, int64_t i,
                 int64_t j, int64_t k, int64_t& oi, int64_t& oj,
                 int64_t& ok) {
  oi = i * m[0] + j * m[3] + k * m[6];
  oj = i * m[1] + j * m[4] + k * m[7];
  ok = i * m[2] + j * m[5] + k * m[8];
  ijk_normalize(oi, oj, ok);
}

constexpr double kSin60 = 0.8660254037844386467637231707529362;

inline void hex2d_to_ijk(double x, double y, int64_t& i, int64_t& j,
                         int64_t& k) {
  // exact port of mathlib.hex2d_to_ijk / device._hex2d_to_ijk
  double a1 = std::fabs(x), a2 = std::fabs(y);
  double x2 = a2 / kSin60;
  double x1 = a1 + x2 * 0.5;
  int64_t m1 = (int64_t)std::floor(x1);
  int64_t m2 = (int64_t)std::floor(x2);
  double r1 = x1 - (double)m1, r2 = x2 - (double)m2;
  const double third = 1.0 / 3.0;
  if (r1 < 0.5) {
    if (r1 < third) {
      i = m1;
      j = (r2 < (1.0 + r1) * 0.5) ? m2 : m2 + 1;
    } else {
      j = (r2 < (1.0 - r1)) ? m2 : m2 + 1;
      i = (((1.0 - r1) <= r2) && (r2 < 2.0 * r1)) ? m1 + 1 : m1;
    }
  } else {
    if (r1 < 2.0 * third) {
      j = (r2 < (1.0 - r1)) ? m2 : m2 + 1;
      i = (((2.0 * r1 - 1.0) < r2) && (r2 < (1.0 - r1))) ? m1 : m1 + 1;
    } else {
      i = m1 + 1;
      j = (r2 < r1 * 0.5) ? m2 : m2 + 1;
    }
  }
  if (x < 0.0) {
    bool j_even = (j % 2) == 0;
    int64_t axisi = j_even ? fdiv(j, 2) : fdiv(j + 1, 2);
    int64_t diff = i - axisi;
    i = j_even ? i - 2 * diff : i - (2 * diff + 1);
  }
  if (y < 0.0) {
    i = i - fdiv(2 * j + 1, 2);
    j = -j;
  }
  k = 0;
  ijk_normalize(i, j, k);
}

inline int lead_digit_packed(uint32_t p) {
  if (p == 0) return 0;
  int b = 31 - __builtin_clz(p);
  return (int)((p >> (3 * (b / 3))) & 7u);
}

inline uint32_t rot_fields(uint32_t p, const int32_t* ccw_pow, int rot,
                           int res) {
  uint32_t out = 0;
  for (int f = 0; f < res; ++f) {
    uint32_t d = (p >> (3 * f)) & 7u;
    out |= (uint32_t)ccw_pow[rot * 7 + (int)d] << (3 * f);
  }
  return out;
}

}  // namespace

extern "C" {

// lat/lng: float32 radians (n points); outputs hi/lo: uint32 halves of the
// 64-bit H3-compatible index.  Tables are the flat arrays of
// hexgrid.device._DeviceTables / _projection_bases, passed from Python.
void h3_snap_f32(
    const float* lat, const float* lng, int64_t n, int res,
    const double* face_xyz,     // (20,3)
    const double* u1,           // (20,3) — includes 1/RES0_U scale
    const double* u2,           // (20,3)
    double rot_cos, double rot_sin,  // Class III ap7 rotation
    double scale,               // sqrt(7)^res
    const int32_t* down_ap7,    // 9
    const int32_t* down_ap7r,   // 9
    const int32_t* face_ijk_bc,   // 540
    const int32_t* face_ijk_rot,  // 540
    const int32_t* bc_pent,       // 122
    const int32_t* pent_cw_off,   // 2440 = 122*20
    const int32_t* ccw_pow,       // 42 = 6*7
    int k_axes_digit,
    uint32_t* hi, uint32_t* lo) {
  const bool res_class_iii = (res & 1) != 0;
  for (int64_t idx = 0; idx < n; ++idx) {
    // --- geo -> face + gnomonic hex2d (device._geo_to_hex2d_vec) -------
    double la = (double)lat[idx], lo_ = (double)lng[idx];
    // Non-finite coords (NaN-filled invalid rows inside the live prefix)
    // would reach UB double->int64 casts in the digit chain and could
    // pack digit 7, driving rot_fields past the 42-entry ccw_pow table.
    // Their outputs are masked downstream, so pin them to (0,0) here.
    if (!std::isfinite(la) || !std::isfinite(lo_)) { la = 0.0; lo_ = 0.0; }
    double cl = std::cos(la);
    double v0 = cl * std::cos(lo_), v1 = cl * std::sin(lo_),
           v2 = std::sin(la);
    int face = 0;
    double best = -2.0;
    for (int f = 0; f < 20; ++f) {
      double d = v0 * face_xyz[3 * f] + v1 * face_xyz[3 * f + 1] +
                 v2 * face_xyz[3 * f + 2];
      if (d > best) { best = d; face = f; }
    }
    double p0 = v0 / best - face_xyz[3 * face];
    double p1 = v1 / best - face_xyz[3 * face + 1];
    double p2 = v2 / best - face_xyz[3 * face + 2];
    double x = p0 * u1[3 * face] + p1 * u1[3 * face + 1] +
               p2 * u1[3 * face + 2];
    double y = p0 * u2[3 * face] + p1 * u2[3 * face + 1] +
               p2 * u2[3 * face + 2];
    if (res_class_iii) {
      double xr = x * rot_cos + y * rot_sin;
      y = y * rot_cos - x * rot_sin;
      x = xr;
    }
    x *= scale;
    y *= scale;

    // --- hex rounding + aperture-7 digit chain (device._forward_digits)
    int64_t i, j, k;
    hex2d_to_ijk(x, y, i, j, k);
    uint32_t p = 0;
    for (int r = res; r >= 1; --r) {
      int64_t li = i, lj = j, lk = k, ci, cj, ck;
      if (r & 1) {  // Class III
        up_ap7(i, j, k);
        lin3(down_ap7, i, j, k, ci, cj, ck);
      } else {
        up_ap7r(i, j, k);
        lin3(down_ap7r, i, j, k, ci, cj, ck);
      }
      int64_t di = li - ci, dj = lj - cj, dk = lk - ck;
      ijk_normalize(di, dj, dk);
      uint32_t digit = (uint32_t)(4 * di + 2 * dj + dk);
      p |= digit << (3 * (res - r));
    }
    // res-0 coords are mathematically within [0,2]; clamp for safety
    if (i < 0) i = 0; if (i > 2) i = 2;
    if (j < 0) j = 0; if (j > 2) j = 2;
    if (k < 0) k = 0; if (k > 2) k = 2;

    // --- base cell + home-orientation rotations (_apply_rotations_packed)
    int flat = (int)(((face * 3 + i) * 3 + j) * 3 + k);
    int bc = face_ijk_bc[flat];
    int rot = face_ijk_rot[flat];
    if (res > 0) {
      bool pent = bc_pent[bc] != 0;
      if (pent) {
        bool cw_off = pent_cw_off[bc * 20 + face] != 0;
        if (lead_digit_packed(p) == k_axes_digit) {
          // deleted-subsequence offset: leading K rotated out (CW == CCW^5)
          p = rot_fields(p, ccw_pow, cw_off ? 5 : 1, res);
        }
        for (int t = 0; t < rot; ++t) {
          uint32_t p1 = rot_fields(p, ccw_pow, 1, res);
          if (lead_digit_packed(p1) == k_axes_digit)
            p1 = rot_fields(p1, ccw_pow, 1, res);
          p = p1;
        }
      } else {
        p = rot_fields(p, ccw_pow, rot, res);
      }
    }

    // --- pack (device._pack_packed; mode=1 cell) -----------------------
    uint64_t h = ((uint64_t)1 << 59) | ((uint64_t)res << 52) |
                 ((uint64_t)bc << 45);
    h |= (uint64_t)p << (3 * (15 - res));
    for (int r = res + 1; r <= 15; ++r) h |= (uint64_t)7 << (3 * (15 - r));
    hi[idx] = (uint32_t)(h >> 32);
    lo[idx] = (uint32_t)(h & 0xFFFFFFFFull);
  }
}

}  // extern "C"
