// Host-side H3 forward snap: (lat, lng) radians -> 64-bit cell index.
//
// The CPU-backend counterpart of hexgrid/device.py's vectorized XLA snap
// (itself the replacement for the reference's per-row geo_to_h3 UDF,
// reference: heatmap_stream.py:65-75).  On CPU the XLA snap dominates the
// fold (~80% of batch wall at res 8); this C++ port of the same
// trig-free gnomonic + packed-digit-chain algorithm runs ~an order of
// magnitude faster per core and computes in double throughout, matching
// the f64 host oracle (hexgrid/host.py) rather than the f32 device path.
//
// Two paths share one algorithm:
//   * `snap_one` — the scalar reference (and the tail/pentagon path);
//   * an AVX-512 block path (8 points/vector) used when the CPU has
//     avx512f+avx512dq: the face argmax, gnomonic projection, hex
//     rounding, and the aperture-7 digit chain all run as f64 vectors.
//     Every arithmetic step replicates the scalar evaluation order with
//     explicit mul/add (no FMA contraction), and the digit chain's
//     integer work is done in f64 — exact, because all intermediates
//     stay far below 2^53 and div7_round's operand (2x+7, odd) is never
//     a multiple of 14, so floor((2x+7)/14.0) == floor-div exactly.
//
//     TRIG + MARGIN FALLBACK: the two scalar libm sincos calls per
//     point used to dominate the block path (~half of ~139 ns/pt on
//     the round-5 host — see tools/bench_snap_native.py), so the block
//     path computes sin/cos with a vectorized fdlibm-style polynomial
//     (~1 ulp, NOT bit-identical to libm) and proves per lane that the
//     last-ulp trig difference cannot change the DISCRETE outputs:
//       * face argmax margin: best dot minus second-best dot;
//       * hex rounding margin: distance from the scaled hex-plane
//         point to its rounded cell's nearest edge (0.5 - max lattice
//         projection; unit cell spacing).
//     A lane whose margin is below tolerance (conservatively ~1000x
//     the worst-case f64 error amplification through the projection at
//     res <= 10) is REDONE with scalar `snap_one` (libm sincos), so
//     the library's outputs remain bit-identical to the scalar
//     reference — and to the f64 host oracle — everywhere, by
//     construction rather than by luck: lanes where poly-vs-libm could
//     matter never take the poly result.  Fallback fraction is ~1e-7
//     of uniform points (boundary-epsilon neighborhoods), amortized to
//     nothing.  Base-cell lookup and the (rare) home-orientation/
//     pentagon rotations run scalar per lane as before.  The block
//     path is differential-tested against `snap_one` over random +
//     near-boundary sweeps (tests/test_native_snap.py), and the whole
//     lib against the f64 host oracle.
//
// No code is copied from the C h3 library; this is a port of this
// package's own device.py math (see hexgrid/__init__.py provenance
// note).  All lookup tables are PASSED IN from Python — the generated
// tables in hexgrid/_tables.py stay the single source of truth.

#include <cstdint>
#include <cmath>

#if defined(__x86_64__) && defined(__GNUC__)
#define H3_SNAP_AVX512 1
#include <immintrin.h>
#endif

// One call computing both sin and cos, bit-identical to the separate
// libm calls.  glibc exports sincos (a GNU extension); elsewhere fall
// back to std::sin/std::cos so the combined native .so still links
// (an undefined symbol here would silently disable EVERY native
// component — they share one library).
#if defined(__GLIBC__)
extern "C" void sincos(double, double*, double*);
static inline void h3_sincos(double x, double* s, double* c) {
  sincos(x, s, c);
}
#else
static inline void h3_sincos(double x, double* s, double* c) {
  *s = std::sin(x);
  *c = std::cos(x);
}
#endif

namespace {

inline int64_t fdiv(int64_t a, int64_t b) {
  // floor division (jnp.floor_divide semantics for negative a)
  int64_t q = a / b, r = a % b;
  return (r != 0 && ((r < 0) != (b < 0))) ? q - 1 : q;
}

inline void ijk_normalize(int64_t& i, int64_t& j, int64_t& k) {
  // mirror mathlib.ijk_normalize: fold negative axes, subtract min
  int64_t neg = i < 0 ? i : 0;
  j -= neg; k -= neg; i -= neg;
  neg = j < 0 ? j : 0;
  i -= neg; k -= neg; j -= neg;
  neg = k < 0 ? k : 0;
  i -= neg; j -= neg; k -= neg;
  int64_t m = i < j ? i : j;
  if (k < m) m = k;
  i -= m; j -= m; k -= m;
}

inline int64_t div7_round(int64_t x) {  // round-half-away of x/7 (exact)
  return fdiv(2 * x + 7, 14);
}

inline void up_ap7(int64_t& i, int64_t& j, int64_t& k) {
  int64_t ii = i - k, jj = j - k;
  i = div7_round(3 * ii - jj);
  j = div7_round(ii + 2 * jj);
  k = 0;
  ijk_normalize(i, j, k);
}

inline void up_ap7r(int64_t& i, int64_t& j, int64_t& k) {
  int64_t ii = i - k, jj = j - k;
  i = div7_round(2 * ii + jj);
  j = div7_round(3 * jj - ii);
  k = 0;
  ijk_normalize(i, j, k);
}

inline void lin3(const int32_t* m /*9 ints: iv, jv, kv*/, int64_t i,
                 int64_t j, int64_t k, int64_t& oi, int64_t& oj,
                 int64_t& ok) {
  oi = i * m[0] + j * m[3] + k * m[6];
  oj = i * m[1] + j * m[4] + k * m[7];
  ok = i * m[2] + j * m[5] + k * m[8];
  ijk_normalize(oi, oj, ok);
}

constexpr double kSin60 = 0.8660254037844386467637231707529362;

inline void hex2d_to_ijk(double x, double y, int64_t& i, int64_t& j,
                         int64_t& k) {
  // exact port of mathlib.hex2d_to_ijk / device._hex2d_to_ijk
  double a1 = std::fabs(x), a2 = std::fabs(y);
  double x2 = a2 / kSin60;
  double x1 = a1 + x2 * 0.5;
  int64_t m1 = (int64_t)std::floor(x1);
  int64_t m2 = (int64_t)std::floor(x2);
  double r1 = x1 - (double)m1, r2 = x2 - (double)m2;
  const double third = 1.0 / 3.0;
  if (r1 < 0.5) {
    if (r1 < third) {
      i = m1;
      j = (r2 < (1.0 + r1) * 0.5) ? m2 : m2 + 1;
    } else {
      j = (r2 < (1.0 - r1)) ? m2 : m2 + 1;
      i = (((1.0 - r1) <= r2) && (r2 < 2.0 * r1)) ? m1 + 1 : m1;
    }
  } else {
    if (r1 < 2.0 * third) {
      j = (r2 < (1.0 - r1)) ? m2 : m2 + 1;
      i = (((2.0 * r1 - 1.0) < r2) && (r2 < (1.0 - r1))) ? m1 : m1 + 1;
    } else {
      i = m1 + 1;
      j = (r2 < r1 * 0.5) ? m2 : m2 + 1;
    }
  }
  if (x < 0.0) {
    bool j_even = (j % 2) == 0;
    int64_t axisi = j_even ? fdiv(j, 2) : fdiv(j + 1, 2);
    int64_t diff = i - axisi;
    i = j_even ? i - 2 * diff : i - (2 * diff + 1);
  }
  if (y < 0.0) {
    i = i - fdiv(2 * j + 1, 2);
    j = -j;
  }
  k = 0;
  ijk_normalize(i, j, k);
}

inline int lead_digit_packed(uint32_t p) {
  if (p == 0) return 0;
  int b = 31 - __builtin_clz(p);
  return (int)((p >> (3 * (b / 3))) & 7u);
}

inline uint32_t rot_fields(uint32_t p, const int32_t* ccw_pow, int rot,
                           int res) {
  uint32_t out = 0;
  for (int f = 0; f < res; ++f) {
    uint32_t d = (p >> (3 * f)) & 7u;
    out |= (uint32_t)ccw_pow[rot * 7 + (int)d] << (3 * f);
  }
  return out;
}

// All the precomputed tables, bundled so the scalar/vector paths share
// one plumbing surface.
struct Tables {
  const double* face_xyz;
  const double* u1;
  const double* u2;
  double rot_cos, rot_sin, scale;
  const int32_t* down_ap7;
  const int32_t* down_ap7r;
  const int32_t* face_ijk_bc;
  const int32_t* face_ijk_rot;
  const int32_t* bc_pent;
  const int32_t* pent_cw_off;
  const int32_t* ccw_pow;
  int k_axes_digit;
};

// Base-cell lookup + home-orientation/pentagon digit rotations — the
// per-lane epilogue shared verbatim by both paths (rotations are
// table-driven and branchy; they run scalar even in the vector path).
inline void finish_cell(const Tables& T, int res, int face, int64_t i,
                        int64_t j, int64_t k, uint32_t p, uint32_t* hi,
                        uint32_t* lo) {
  // res-0 coords are mathematically within [0,2]; clamp for safety
  if (i < 0) i = 0; if (i > 2) i = 2;
  if (j < 0) j = 0; if (j > 2) j = 2;
  if (k < 0) k = 0; if (k > 2) k = 2;

  int flat = (int)(((face * 3 + i) * 3 + j) * 3 + k);
  int bc = T.face_ijk_bc[flat];
  int rot = T.face_ijk_rot[flat];
  if (res > 0) {
    bool pent = T.bc_pent[bc] != 0;
    if (pent) {
      bool cw_off = T.pent_cw_off[bc * 20 + face] != 0;
      if (lead_digit_packed(p) == T.k_axes_digit) {
        // deleted-subsequence offset: leading K rotated out (CW == CCW^5)
        p = rot_fields(p, T.ccw_pow, cw_off ? 5 : 1, res);
      }
      for (int t = 0; t < rot; ++t) {
        uint32_t p1 = rot_fields(p, T.ccw_pow, 1, res);
        if (lead_digit_packed(p1) == T.k_axes_digit)
          p1 = rot_fields(p1, T.ccw_pow, 1, res);
        p = p1;
      }
    } else {
      p = rot_fields(p, T.ccw_pow, rot, res);
    }
  }

  // --- pack (device._pack_packed; mode=1 cell) -----------------------
  uint64_t h = ((uint64_t)1 << 59) | ((uint64_t)res << 52) |
               ((uint64_t)bc << 45);
  h |= (uint64_t)p << (3 * (15 - res));
  for (int r = res + 1; r <= 15; ++r) h |= (uint64_t)7 << (3 * (15 - r));
  *hi = (uint32_t)(h >> 32);
  *lo = (uint32_t)(h & 0xFFFFFFFFull);
}

// One point, scalar — the reference semantics both paths must match.
inline void snap_one(const Tables& T, int res, bool res_class_iii,
                     float latf, float lngf, uint32_t* hi, uint32_t* lo) {
  // --- geo -> face + gnomonic hex2d (device._geo_to_hex2d_vec) -------
  double la = (double)latf, lo_ = (double)lngf;
  // Non-finite coords (NaN-filled invalid rows inside the live prefix)
  // would reach UB double->int64 casts in the digit chain and could
  // pack digit 7, driving rot_fields past the 42-entry ccw_pow table.
  // Their outputs are masked downstream, so pin them to (0,0) here.
  if (!std::isfinite(la) || !std::isfinite(lo_)) { la = 0.0; lo_ = 0.0; }
  double sla, cla, slo, clo;
  h3_sincos(la, &sla, &cla);
  h3_sincos(lo_, &slo, &clo);
  double v0 = cla * clo, v1 = cla * slo, v2 = sla;
  int face = 0;
  double best = -2.0;
  for (int f = 0; f < 20; ++f) {
    double d = v0 * T.face_xyz[3 * f] + v1 * T.face_xyz[3 * f + 1] +
               v2 * T.face_xyz[3 * f + 2];
    if (d > best) { best = d; face = f; }
  }
  double p0 = v0 / best - T.face_xyz[3 * face];
  double p1 = v1 / best - T.face_xyz[3 * face + 1];
  double p2 = v2 / best - T.face_xyz[3 * face + 2];
  double x = p0 * T.u1[3 * face] + p1 * T.u1[3 * face + 1] +
             p2 * T.u1[3 * face + 2];
  double y = p0 * T.u2[3 * face] + p1 * T.u2[3 * face + 1] +
             p2 * T.u2[3 * face + 2];
  if (res_class_iii) {
    double xr = x * T.rot_cos + y * T.rot_sin;
    y = y * T.rot_cos - x * T.rot_sin;
    x = xr;
  }
  x *= T.scale;
  y *= T.scale;

  // --- hex rounding + aperture-7 digit chain (device._forward_digits)
  int64_t i, j, k;
  hex2d_to_ijk(x, y, i, j, k);
  uint32_t p = 0;
  for (int r = res; r >= 1; --r) {
    int64_t li = i, lj = j, lk = k, ci, cj, ck;
    if (r & 1) {  // Class III
      up_ap7(i, j, k);
      lin3(T.down_ap7, i, j, k, ci, cj, ck);
    } else {
      up_ap7r(i, j, k);
      lin3(T.down_ap7r, i, j, k, ci, cj, ck);
    }
    int64_t di = li - ci, dj = lj - cj, dk = lk - ck;
    ijk_normalize(di, dj, dk);
    uint32_t digit = (uint32_t)(4 * di + 2 * dj + dk);
    p |= digit << (3 * (res - r));
  }
  finish_cell(T, res, face, i, j, k, p, hi, lo);
}

#ifdef H3_SNAP_AVX512

// ---- AVX-512 block path: 8 points per __m512d ------------------------
//
// f64 vectors replicate the scalar arithmetic step by step (explicit
// mul/add, no FMA).  "Integer" quantities (i, j, k, digit chain) live
// in f64 lanes: every value stays orders of magnitude below 2^53, all
// products/sums/floors are exact, and div7_round's floor-division
// rounds exactly (see file header), so the lane arithmetic is
// bit-for-bit the scalar integer arithmetic.

#define H3_TGT __attribute__((target("avx512f,avx512dq")))

// ---- vector f64 sincos (fdlibm-style minimax, ~1 ulp) ----------------
//
// Good to ~1 ulp for |x| <= SINCOS_MAX_ABS (GPS radians are <= pi, so
// the 2-constant Cody-Waite pi/2 reduction is far more than enough:
// with |q| <= 11 the reduction error is ~q*6e-28, invisible at f64).
// Lanes outside that range (or non-finite) are reported in `bad` and
// must be redone scalar — snap_one's libm handles any finite input.
// The minimax coefficients are the public fdlibm __kernel_sin /
// __kernel_cos constants (pure mathematical constants, reproduced in
// every libm derivative); the combine differs (mask blends, no
// precision-preserving correction terms — the margin fallback absorbs
// the last-ulp difference vs libm).
constexpr double kSinC1 = -1.66666666666666324348e-01;
constexpr double kSinC2 = 8.33333333332248946124e-03;
constexpr double kSinC3 = -1.98412698298579493134e-04;
constexpr double kSinC4 = 2.75573137070700676789e-06;
constexpr double kSinC5 = -2.50507602534068634195e-08;
constexpr double kSinC6 = 1.58969099521155010221e-10;
constexpr double kCosC1 = 4.16666666666666019037e-02;
constexpr double kCosC2 = -1.38888888888741095749e-03;
constexpr double kCosC3 = 2.48015872894767294178e-05;
constexpr double kCosC4 = -2.75573143513906633035e-07;
constexpr double kCosC5 = 2.08757232129817482790e-09;
constexpr double kCosC6 = -1.13596475577881948265e-11;
constexpr double kPio2Hi = 1.57079632673412561417e+00;   // 33 bits of pi/2
constexpr double kPio2Lo = 6.07710050650619224932e-11;   // next 53 bits
constexpr double kTwoOverPi = 6.36619772367581382433e-01;
constexpr double kSincosMaxAbs = 16.0;

H3_TGT static inline void vsincos(__m512d x, __m512d* s_out,
                                  __m512d* c_out, __mmask8* bad) {
  const __m512d one = _mm512_set1_pd(1.0), half = _mm512_set1_pd(0.5);
  // lanes the poly path must not answer: |x| too large or non-finite
  __m512d ax = _mm512_abs_pd(x);
  __mmask8 in_range =
      _mm512_cmp_pd_mask(ax, _mm512_set1_pd(kSincosMaxAbs), _CMP_LE_OQ);
  *bad = (__mmask8)~in_range;  // unordered (NaN) fails LE -> bad too
  // quadrant: q = round(x * 2/pi); r = (x - q*hi) - q*lo
  __m512d q = _mm512_roundscale_pd(
      _mm512_mul_pd(x, _mm512_set1_pd(kTwoOverPi)),
      _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC);
  __m512d r = _mm512_sub_pd(x, _mm512_mul_pd(q, _mm512_set1_pd(kPio2Hi)));
  r = _mm512_sub_pd(r, _mm512_mul_pd(q, _mm512_set1_pd(kPio2Lo)));
  __m512i qi = _mm512_cvtpd_epi64(q);  // avx512dq

  __m512d z = _mm512_mul_pd(r, r);
  // sin(r) = r + r*z*(S1 + z*(S2 + ... z*S6))
  __m512d sp = _mm512_set1_pd(kSinC6);
  sp = _mm512_add_pd(_mm512_mul_pd(sp, z), _mm512_set1_pd(kSinC5));
  sp = _mm512_add_pd(_mm512_mul_pd(sp, z), _mm512_set1_pd(kSinC4));
  sp = _mm512_add_pd(_mm512_mul_pd(sp, z), _mm512_set1_pd(kSinC3));
  sp = _mm512_add_pd(_mm512_mul_pd(sp, z), _mm512_set1_pd(kSinC2));
  sp = _mm512_add_pd(_mm512_mul_pd(sp, z), _mm512_set1_pd(kSinC1));
  __m512d sr = _mm512_add_pd(r, _mm512_mul_pd(_mm512_mul_pd(r, z), sp));
  // cos(r) = 1 - z/2 + z*z*(C1 + z*(C2 + ... z*C6))
  __m512d cp = _mm512_set1_pd(kCosC6);
  cp = _mm512_add_pd(_mm512_mul_pd(cp, z), _mm512_set1_pd(kCosC5));
  cp = _mm512_add_pd(_mm512_mul_pd(cp, z), _mm512_set1_pd(kCosC4));
  cp = _mm512_add_pd(_mm512_mul_pd(cp, z), _mm512_set1_pd(kCosC3));
  cp = _mm512_add_pd(_mm512_mul_pd(cp, z), _mm512_set1_pd(kCosC2));
  cp = _mm512_add_pd(_mm512_mul_pd(cp, z), _mm512_set1_pd(kCosC1));
  __m512d cr = _mm512_add_pd(
      _mm512_sub_pd(one, _mm512_mul_pd(z, half)),
      _mm512_mul_pd(_mm512_mul_pd(z, z), cp));

  // quadrant combine: n = q & 3
  //   sin(x) = [ sr,  cr, -sr, -cr][n]    cos(x) = [ cr, -sr, -cr,  sr][n]
  __m512i n = _mm512_and_epi64(qi, _mm512_set1_epi64(3));
  __mmask8 swap = _mm512_test_epi64_mask(n, _mm512_set1_epi64(1));
  __mmask8 n_ge2 = _mm512_cmp_epi64_mask(n, _mm512_set1_epi64(2),
                                         _MM_CMPINT_NLT);
  __mmask8 n12 = _mm512_test_epi64_mask(
      _mm512_add_epi64(n, _mm512_set1_epi64(1)), _mm512_set1_epi64(2));
  __m512d s = _mm512_mask_mov_pd(sr, swap, cr);
  __m512d c = _mm512_mask_mov_pd(cr, swap, sr);
  const __m512d zero = _mm512_setzero_pd();
  s = _mm512_mask_sub_pd(s, n_ge2, zero, s);  // negate where n in {2,3}
  c = _mm512_mask_sub_pd(c, n12, zero, c);    // negate where n in {1,2}
  *s_out = s;
  *c_out = c;
}

// Margin tolerances: the poly-vs-libm trig difference propagates to the
// scaled hex coords as at most ~|coord| * few-ulps ~ 1e-10 grid units
// at res 10 (scale 7^5), and to the face dots as ~1e-15.  Tolerances
// sit ~1000x above those bounds; lanes inside the band redo scalar.
constexpr double kHexMarginTol = 1e-7;    // grid units (cell spacing 1)
constexpr double kFaceMarginTol = 1e-12;  // unit-sphere dot difference

H3_TGT static inline __m512d vmin(__m512d a, __m512d b) {
  return _mm512_min_pd(a, b);
}

H3_TGT static inline void vnormalize(__m512d& i, __m512d& j, __m512d& k) {
  const __m512d z = _mm512_setzero_pd();
  __m512d neg = vmin(i, z);
  j = _mm512_sub_pd(j, neg); k = _mm512_sub_pd(k, neg);
  i = _mm512_sub_pd(i, neg);
  neg = vmin(j, z);
  i = _mm512_sub_pd(i, neg); k = _mm512_sub_pd(k, neg);
  j = _mm512_sub_pd(j, neg);
  neg = vmin(k, z);
  i = _mm512_sub_pd(i, neg); j = _mm512_sub_pd(j, neg);
  k = _mm512_sub_pd(k, neg);
  __m512d m = vmin(vmin(i, j), k);
  i = _mm512_sub_pd(i, m); j = _mm512_sub_pd(j, m);
  k = _mm512_sub_pd(k, m);
}

H3_TGT static inline __m512d vfloor(__m512d a) {
  return _mm512_roundscale_pd(a, _MM_FROUND_TO_NEG_INF | _MM_FROUND_NO_EXC);
}

// floor((2x+7)/14): x integer-valued f64; 2x+7 is odd so the quotient is
// never an integer and the f64 division's sub-ulp rounding cannot cross
// a floor boundary — exact round-half-away of x/7, as in the scalar.
H3_TGT static inline __m512d vdiv7_round(__m512d x) {
  const __m512d two = _mm512_set1_pd(2.0), seven = _mm512_set1_pd(7.0),
                fourteen = _mm512_set1_pd(14.0);
  __m512d t = _mm512_add_pd(_mm512_mul_pd(two, x), seven);
  return vfloor(_mm512_div_pd(t, fourteen));
}

H3_TGT static inline void vup_ap7(bool class_iii, __m512d& i, __m512d& j,
                                  __m512d& k) {
  __m512d ii = _mm512_sub_pd(i, k), jj = _mm512_sub_pd(j, k);
  const __m512d two = _mm512_set1_pd(2.0), three = _mm512_set1_pd(3.0);
  if (class_iii) {  // up_ap7: i = (3ii - jj)/7r, j = (ii + 2jj)/7r
    i = vdiv7_round(_mm512_sub_pd(_mm512_mul_pd(three, ii), jj));
    j = vdiv7_round(_mm512_add_pd(ii, _mm512_mul_pd(two, jj)));
  } else {          // up_ap7r: i = (2ii + jj)/7r, j = (3jj - ii)/7r
    i = vdiv7_round(_mm512_add_pd(_mm512_mul_pd(two, ii), jj));
    j = vdiv7_round(_mm512_sub_pd(_mm512_mul_pd(three, jj), ii));
  }
  k = _mm512_setzero_pd();
  vnormalize(i, j, k);
}

H3_TGT static inline void vlin3(const int32_t* m, __m512d i, __m512d j,
                                __m512d k, __m512d& oi, __m512d& oj,
                                __m512d& ok) {
  // oi = i*m0 + j*m3 + k*m6 with the scalar's (a+b)+c association
  __m512d m0 = _mm512_set1_pd((double)m[0]),
          m1 = _mm512_set1_pd((double)m[1]),
          m2 = _mm512_set1_pd((double)m[2]),
          m3 = _mm512_set1_pd((double)m[3]),
          m4 = _mm512_set1_pd((double)m[4]),
          m5 = _mm512_set1_pd((double)m[5]),
          m6 = _mm512_set1_pd((double)m[6]),
          m7 = _mm512_set1_pd((double)m[7]),
          m8 = _mm512_set1_pd((double)m[8]);
  oi = _mm512_add_pd(_mm512_add_pd(_mm512_mul_pd(i, m0),
                                   _mm512_mul_pd(j, m3)),
                     _mm512_mul_pd(k, m6));
  oj = _mm512_add_pd(_mm512_add_pd(_mm512_mul_pd(i, m1),
                                   _mm512_mul_pd(j, m4)),
                     _mm512_mul_pd(k, m7));
  ok = _mm512_add_pd(_mm512_add_pd(_mm512_mul_pd(i, m2),
                                   _mm512_mul_pd(j, m5)),
                     _mm512_mul_pd(k, m8));
  vnormalize(oi, oj, ok);
}

// hex2d rounding, vectorized with blends in place of the scalar's
// branches (each region's conditions are evaluated on all lanes and the
// matching region's (i, j) selected — identical comparisons, identical
// arithmetic, so identical results lane by lane).
H3_TGT static inline void vhex2d_to_ijk(__m512d x, __m512d y, __m512d& io,
                                        __m512d& jo, __m512d& ko) {
  const __m512d half = _mm512_set1_pd(0.5), one = _mm512_set1_pd(1.0),
                two = _mm512_set1_pd(2.0),
                third = _mm512_set1_pd(1.0 / 3.0),
                two_third = _mm512_set1_pd(2.0 / 3.0),
                sin60 = _mm512_set1_pd(kSin60),
                z = _mm512_setzero_pd();
  __m512d a1 = _mm512_abs_pd(x), a2 = _mm512_abs_pd(y);
  __m512d x2 = _mm512_div_pd(a2, sin60);
  __m512d x1 = _mm512_add_pd(a1, _mm512_mul_pd(x2, half));
  __m512d m1 = vfloor(x1), m2 = vfloor(x2);
  __m512d r1 = _mm512_sub_pd(x1, m1), r2 = _mm512_sub_pd(x2, m2);
  __m512d m1p = _mm512_add_pd(m1, one), m2p = _mm512_add_pd(m2, one);

  // region masks on r1 (exclusive, matching the scalar's nesting)
  __mmask8 lt_half = _mm512_cmp_pd_mask(r1, half, _CMP_LT_OQ);
  __mmask8 lt_third = _mm512_cmp_pd_mask(r1, third, _CMP_LT_OQ);
  __mmask8 lt_2third = _mm512_cmp_pd_mask(r1, two_third, _CMP_LT_OQ);
  __mmask8 rA = lt_half & lt_third;                    // r1 < 1/3
  __mmask8 rB = lt_half & (__mmask8)~lt_third;         // [1/3, 1/2)
  __mmask8 rC = (__mmask8)~lt_half & lt_2third;        // [1/2, 2/3)
  __mmask8 rD = (__mmask8)~lt_half & (__mmask8)~lt_2third;  // >= 2/3

  __m512d one_m_r1 = _mm512_sub_pd(one, r1);
  // region A: i=m1; j = r2 < (1+r1)*0.5 ? m2 : m2+1
  __mmask8 jA = _mm512_cmp_pd_mask(
      r2, _mm512_mul_pd(_mm512_add_pd(one, r1), half), _CMP_LT_OQ);
  // regions B, C share j = r2 < (1-r1) ? m2 : m2+1
  __mmask8 jBC = _mm512_cmp_pd_mask(r2, one_m_r1, _CMP_LT_OQ);
  // region B: i = ((1-r1) <= r2 && r2 < 2*r1) ? m1+1 : m1
  __mmask8 iB = _mm512_cmp_pd_mask(one_m_r1, r2, _CMP_LE_OQ) &
                _mm512_cmp_pd_mask(r2, _mm512_mul_pd(two, r1), _CMP_LT_OQ);
  // region C: i = ((2*r1-1) < r2 && r2 < (1-r1)) ? m1 : m1+1
  __mmask8 iC = _mm512_cmp_pd_mask(
                    _mm512_sub_pd(_mm512_mul_pd(two, r1), one), r2,
                    _CMP_LT_OQ) &
                _mm512_cmp_pd_mask(r2, one_m_r1, _CMP_LT_OQ);
  // region D: i=m1+1; j = r2 < r1*0.5 ? m2 : m2+1
  __mmask8 jD = _mm512_cmp_pd_mask(r2, _mm512_mul_pd(r1, half), _CMP_LT_OQ);

  __m512d i = m1, j = m2;
  i = _mm512_mask_mov_pd(i, rB & iB, m1p);
  i = _mm512_mask_mov_pd(i, rC & (__mmask8)~iC, m1p);
  i = _mm512_mask_mov_pd(i, rD, m1p);
  j = _mm512_mask_mov_pd(j, rA & (__mmask8)~jA, m2p);
  j = _mm512_mask_mov_pd(j, (rB | rC) & (__mmask8)~jBC, m2p);
  j = _mm512_mask_mov_pd(j, rD & (__mmask8)~jD, m2p);

  // x < 0 fold.  j >= 0 here, so fdiv(j,2) == floor(j*0.5) and
  // fdiv(j+1,2) == floor((j+1)*0.5), both exact (mul by 0.5 is exact).
  __mmask8 xneg = _mm512_cmp_pd_mask(x, z, _CMP_LT_OQ);
  __m512d jhalf = _mm512_mul_pd(j, half);
  __m512d jfl = vfloor(jhalf);
  __mmask8 j_even = _mm512_cmp_pd_mask(jfl, jhalf, _CMP_EQ_OQ);
  __m512d axisi = _mm512_mask_mov_pd(
      vfloor(_mm512_mul_pd(_mm512_add_pd(j, one), half)), j_even, jfl);
  __m512d diff = _mm512_sub_pd(i, axisi);
  __m512d twodiff = _mm512_mul_pd(two, diff);
  __m512d folded = _mm512_sub_pd(i, twodiff);                  // j even
  __m512d folded_odd = _mm512_sub_pd(i, _mm512_add_pd(twodiff, one));
  __m512d xfold = _mm512_mask_mov_pd(folded_odd, j_even, folded);
  i = _mm512_mask_mov_pd(i, xneg, xfold);

  // y < 0 fold: i -= fdiv(2j+1, 2); j = -j.  j >= 0, so
  // fdiv(2j+1,2) == floor(j + 0.5) == j exactly — but keep the full
  // formula so the equivalence is the formula's, not this comment's.
  __mmask8 yneg = _mm512_cmp_pd_mask(y, z, _CMP_LT_OQ);
  __m512d halfterm = vfloor(_mm512_mul_pd(
      _mm512_add_pd(_mm512_mul_pd(two, j), one), half));
  i = _mm512_mask_mov_pd(i, yneg, _mm512_sub_pd(i, halfterm));
  j = _mm512_mask_mov_pd(j, yneg, _mm512_sub_pd(z, j));

  __m512d k = z;
  vnormalize(i, j, k);
  io = i; jo = j; ko = k;
}

// One 8-lane block: poly trig -> face argmax -> projection -> hex
// rounding -> digit chain, PLUS the decision-margin proof.  Returns in
// `fallback` the lanes whose outputs must NOT be used (trig out of
// range / non-finite input / margin below tolerance) — the caller
// redoes those with scalar snap_one, keeping the library bit-identical
// to the scalar reference everywhere.
H3_TGT static void snap_block8(const Tables& T, int res,
                               bool res_class_iii, const float* latf,
                               const float* lngf, int32_t* face_out,
                               double* p_out, double* i_out,
                               double* j_out, double* k_out,
                               __mmask8* fallback) {
  __m512d la = _mm512_cvtps_pd(_mm256_loadu_ps(latf));
  __m512d lo = _mm512_cvtps_pd(_mm256_loadu_ps(lngf));
  __m512d sla, cla, slo, clo;
  __mmask8 bad_la, bad_lo;
  vsincos(la, &sla, &cla, &bad_la);
  vsincos(lo, &slo, &clo, &bad_lo);
  __mmask8 redo = bad_la | bad_lo;
  __m512d v0 = _mm512_mul_pd(cla, clo);
  __m512d v1 = _mm512_mul_pd(cla, slo);
  __m512d v2 = sla;

  // --- face argmax: d > best keeps the FIRST maximal face, as scalar;
  //     second-best dot rides along for the decision margin
  __m512d best = _mm512_set1_pd(-2.0), best2 = _mm512_set1_pd(-2.0);
  __m512i face = _mm512_setzero_si512();
  for (int f = 0; f < 20; ++f) {
    __m512d fx = _mm512_set1_pd(T.face_xyz[3 * f]),
            fy = _mm512_set1_pd(T.face_xyz[3 * f + 1]),
            fz = _mm512_set1_pd(T.face_xyz[3 * f + 2]);
    __m512d d = _mm512_add_pd(
        _mm512_add_pd(_mm512_mul_pd(v0, fx), _mm512_mul_pd(v1, fy)),
        _mm512_mul_pd(v2, fz));
    __mmask8 gt = _mm512_cmp_pd_mask(d, best, _CMP_GT_OQ);
    best2 = _mm512_mask_mov_pd(_mm512_max_pd(best2, d), gt, best);
    best = _mm512_mask_mov_pd(best, gt, d);
    face = _mm512_mask_mov_epi64(face, gt, _mm512_set1_epi64(f));
  }
  redo |= _mm512_cmp_pd_mask(
      _mm512_sub_pd(best, best2), _mm512_set1_pd(kFaceMarginTol),
      _CMP_LT_OQ);
  __m256i face32 = _mm512_cvtepi64_epi32(face);
  __m256i idx3 = _mm256_mullo_epi32(face32, _mm256_set1_epi32(3));

  // --- gnomonic projection with per-lane face tables (gathers) -------
  __m512d fx = _mm512_i32gather_pd(idx3, T.face_xyz, 8);
  __m512d fy = _mm512_i32gather_pd(
      _mm256_add_epi32(idx3, _mm256_set1_epi32(1)), T.face_xyz, 8);
  __m512d fz = _mm512_i32gather_pd(
      _mm256_add_epi32(idx3, _mm256_set1_epi32(2)), T.face_xyz, 8);
  __m512d p0 = _mm512_sub_pd(_mm512_div_pd(v0, best), fx);
  __m512d p1 = _mm512_sub_pd(_mm512_div_pd(v1, best), fy);
  __m512d p2 = _mm512_sub_pd(_mm512_div_pd(v2, best), fz);
  __m512d u1x = _mm512_i32gather_pd(idx3, T.u1, 8);
  __m512d u1y = _mm512_i32gather_pd(
      _mm256_add_epi32(idx3, _mm256_set1_epi32(1)), T.u1, 8);
  __m512d u1z = _mm512_i32gather_pd(
      _mm256_add_epi32(idx3, _mm256_set1_epi32(2)), T.u1, 8);
  __m512d u2x = _mm512_i32gather_pd(idx3, T.u2, 8);
  __m512d u2y = _mm512_i32gather_pd(
      _mm256_add_epi32(idx3, _mm256_set1_epi32(1)), T.u2, 8);
  __m512d u2z = _mm512_i32gather_pd(
      _mm256_add_epi32(idx3, _mm256_set1_epi32(2)), T.u2, 8);
  __m512d x = _mm512_add_pd(
      _mm512_add_pd(_mm512_mul_pd(p0, u1x), _mm512_mul_pd(p1, u1y)),
      _mm512_mul_pd(p2, u1z));
  __m512d y = _mm512_add_pd(
      _mm512_add_pd(_mm512_mul_pd(p0, u2x), _mm512_mul_pd(p1, u2y)),
      _mm512_mul_pd(p2, u2z));
  if (res_class_iii) {
    __m512d rc = _mm512_set1_pd(T.rot_cos), rs = _mm512_set1_pd(T.rot_sin);
    __m512d xr = _mm512_add_pd(_mm512_mul_pd(x, rc), _mm512_mul_pd(y, rs));
    y = _mm512_sub_pd(_mm512_mul_pd(y, rc), _mm512_mul_pd(x, rs));
    x = xr;
  }
  __m512d sc = _mm512_set1_pd(T.scale);
  x = _mm512_mul_pd(x, sc);
  y = _mm512_mul_pd(y, sc);

  // --- hex rounding + digit chain ------------------------------------
  __m512d i, j, k;
  vhex2d_to_ijk(x, y, i, j, k);

  // decision margin for the rounding: distance from (x, y) to the
  // rounded cell's nearest edge.  Cell center via the lattice inverse
  // (i' = i-k, j' = j-k; cx = i' - j'/2, cy = j'*sin60 — unit
  // spacing), then margin = 1/2 - max |projection on the 3 neighbor
  // directions (1,0), (±1/2, sin60)|.  A lane inside the tolerance
  // band could round differently under libm-vs-poly trig: redo scalar.
  {
    __m512d ip = _mm512_sub_pd(i, k), jp = _mm512_sub_pd(j, k);
    __m512d cx = _mm512_sub_pd(
        ip, _mm512_mul_pd(jp, _mm512_set1_pd(0.5)));
    __m512d cy = _mm512_mul_pd(jp, _mm512_set1_pd(kSin60));
    __m512d dx = _mm512_sub_pd(x, cx), dy = _mm512_sub_pd(y, cy);
    __m512d hdx = _mm512_mul_pd(dx, _mm512_set1_pd(0.5));
    __m512d sdy = _mm512_mul_pd(dy, _mm512_set1_pd(kSin60));
    __m512d proj = _mm512_max_pd(
        _mm512_abs_pd(dx),
        _mm512_max_pd(_mm512_abs_pd(_mm512_add_pd(hdx, sdy)),
                      _mm512_abs_pd(_mm512_sub_pd(sdy, hdx))));
    __m512d margin = _mm512_sub_pd(_mm512_set1_pd(0.5), proj);
    redo |= _mm512_cmp_pd_mask(margin, _mm512_set1_pd(kHexMarginTol),
                               _CMP_LT_OQ);
  }
  *fallback = redo;

  __m512d p = _mm512_setzero_pd();
  for (int r = res; r >= 1; --r) {
    __m512d li = i, lj = j, lk = k, ci, cj, ck;
    if (r & 1) {
      vup_ap7(true, i, j, k);
      vlin3(T.down_ap7, i, j, k, ci, cj, ck);
    } else {
      vup_ap7(false, i, j, k);
      vlin3(T.down_ap7r, i, j, k, ci, cj, ck);
    }
    __m512d di = _mm512_sub_pd(li, ci), dj = _mm512_sub_pd(lj, cj),
            dk = _mm512_sub_pd(lk, ck);
    vnormalize(di, dj, dk);
    // digit = 4di + 2dj + dk in {0..6}; p |= digit << 3*(res-r), done
    // in f64 as p += digit * 8^(res-r) (p < 2^30: exact)
    __m512d digit = _mm512_add_pd(
        _mm512_add_pd(_mm512_mul_pd(_mm512_set1_pd(4.0), di),
                      _mm512_mul_pd(_mm512_set1_pd(2.0), dj)),
        dk);
    double pw = (double)(1ull << (3 * (res - r)));
    p = _mm512_add_pd(p, _mm512_mul_pd(digit, _mm512_set1_pd(pw)));
  }

  _mm256_storeu_si256((__m256i*)face_out, face32);
  _mm512_storeu_pd(p_out, p);
  _mm512_storeu_pd(i_out, i);
  _mm512_storeu_pd(j_out, j);
  _mm512_storeu_pd(k_out, k);
}

H3_TGT static void snap_avx512(const Tables& T, int res,
                               bool res_class_iii, const float* lat,
                               const float* lng, int64_t n, uint32_t* hi,
                               uint32_t* lo) {
  alignas(64) double pbuf[8], ibuf[8], jbuf[8], kbuf[8];
  alignas(32) int32_t faces[8];
  int64_t idx = 0;
  for (; idx + 8 <= n; idx += 8) {
    __mmask8 fallback = 0;
    snap_block8(T, res, res_class_iii, lat + idx, lng + idx, faces, pbuf,
                ibuf, jbuf, kbuf, &fallback);
    for (int t = 0; t < 8; ++t) {
      if ((fallback >> t) & 1) {
        // non-finite / out-of-range trig input, or a face-argmax /
        // hex-rounding decision inside the margin tolerance: the poly
        // trig may not reproduce libm's discrete outcome, so this lane
        // is redone scalar end-to-end — the bit-identical guarantee
        // holds by construction
        snap_one(T, res, res_class_iii, lat[idx + t], lng[idx + t],
                 &hi[idx + t], &lo[idx + t]);
        continue;
      }
      int face = faces[t];
      int64_t i = (int64_t)ibuf[t], j = (int64_t)jbuf[t],
              k = (int64_t)kbuf[t];
      // pentagon base cells take the deleted-subsequence branch; redo
      // those lanes scalar end-to-end (rare: 12 of 122 base cells)
      int64_t ic = i < 0 ? 0 : (i > 2 ? 2 : i);
      int64_t jc = j < 0 ? 0 : (j > 2 ? 2 : j);
      int64_t kc = k < 0 ? 0 : (k > 2 ? 2 : k);
      int flat = (int)(((face * 3 + ic) * 3 + jc) * 3 + kc);
      int bc = T.face_ijk_bc[flat];
      if (res > 0 && T.bc_pent[bc] != 0) {
        snap_one(T, res, res_class_iii, lat[idx + t], lng[idx + t],
                 &hi[idx + t], &lo[idx + t]);
        continue;
      }
      finish_cell(T, res, face, i, j, k, (uint32_t)pbuf[t], &hi[idx + t],
                  &lo[idx + t]);
    }
  }
  for (; idx < n; ++idx)
    snap_one(T, res, res_class_iii, lat[idx], lng[idx], &hi[idx],
             &lo[idx]);
}

static bool avx512_ok() {
  static const bool ok = __builtin_cpu_supports("avx512f") &&
                         __builtin_cpu_supports("avx512dq");
  return ok;
}

#endif  // H3_SNAP_AVX512

}  // namespace

extern "C" {

// lat/lng: float32 radians (n points); outputs hi/lo: uint32 halves of the
// 64-bit H3-compatible index.  Tables are the flat arrays of
// hexgrid.device._DeviceTables / _projection_bases, passed from Python.
void h3_snap_f32(
    const float* lat, const float* lng, int64_t n, int res,
    const double* face_xyz,     // (20,3)
    const double* u1,           // (20,3) — includes 1/RES0_U scale
    const double* u2,           // (20,3)
    double rot_cos, double rot_sin,  // Class III ap7 rotation
    double scale,               // sqrt(7)^res
    const int32_t* down_ap7,    // 9
    const int32_t* down_ap7r,   // 9
    const int32_t* face_ijk_bc,   // 540
    const int32_t* face_ijk_rot,  // 540
    const int32_t* bc_pent,       // 122
    const int32_t* pent_cw_off,   // 2440 = 122*20
    const int32_t* ccw_pow,       // 42 = 6*7
    int k_axes_digit,
    uint32_t* hi, uint32_t* lo) {
  const bool res_class_iii = (res & 1) != 0;
  const Tables T = {face_xyz, u1,  u2,          rot_cos,      rot_sin,
                    scale,    down_ap7, down_ap7r, face_ijk_bc,
                    face_ijk_rot, bc_pent, pent_cw_off, ccw_pow,
                    k_axes_digit};
#ifdef H3_SNAP_AVX512
  if (n >= 16 && avx512_ok()) {
    snap_avx512(T, res, res_class_iii, lat, lng, n, hi, lo);
    return;
  }
#endif
  for (int64_t idx = 0; idx < n; ++idx)
    snap_one(T, res, res_class_iii, lat[idx], lng[idx], &hi[idx],
             &lo[idx]);
}

// Scalar-only entry for differential tests: always takes the reference
// path regardless of CPU features.
void h3_snap_f32_scalar(
    const float* lat, const float* lng, int64_t n, int res,
    const double* face_xyz, const double* u1, const double* u2,
    double rot_cos, double rot_sin, double scale,
    const int32_t* down_ap7, const int32_t* down_ap7r,
    const int32_t* face_ijk_bc, const int32_t* face_ijk_rot,
    const int32_t* bc_pent, const int32_t* pent_cw_off,
    const int32_t* ccw_pow, int k_axes_digit,
    uint32_t* hi, uint32_t* lo) {
  const bool res_class_iii = (res & 1) != 0;
  const Tables T = {face_xyz, u1,  u2,          rot_cos,      rot_sin,
                    scale,    down_ap7, down_ap7r, face_ijk_bc,
                    face_ijk_rot, bc_pent, pent_cw_off, ccw_pow,
                    k_axes_digit};
  for (int64_t idx = 0; idx < n; ++idx)
    snap_one(T, res, res_class_iii, lat[idx], lng[idx], &hi[idx],
             &lo[idx]);
}

}  // extern "C"
