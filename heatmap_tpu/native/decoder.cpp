// Fast JSON-lines GPS-event decoder: bytes in, columnar arrays out.
//
// The reference pays a per-row Python round trip for every event (JSON parse
// in Spark + Python UDF, SURVEY.md §3.3 bottleneck #1); sustaining millions
// of events/sec needs ingest decode at memory speed (SURVEY.md §7 hard part
// #3).  This is a schema-specialized scanner for the canonical 8-field event
// (reference: heatmap_stream.py:52-61) — not a general JSON parser: it walks
// top-level key/value pairs per line, extracts lat/lon/speedKmh/ts/provider/
// vehicleId, interns the two strings into stable int ids, validates with the
// same rules as the Python path (stream/events.py), and writes straight into
// caller-provided numpy buffers.
//
// C ABI (used via ctypes from heatmap_tpu/native/__init__.py):
//   dec_new / dec_free                  — decoder with persistent interns
//   dec_decode(buf, len, cap, out...)   — returns events decoded; *dropped
//   dec_intern_count / dec_intern_get / dec_intern_len — read the string
//     tables (get+len: names may contain NUL bytes after unescaping)
//
// Build: g++ -O3 -shared -fPIC decoder.cpp -o _native.so   (no deps)

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <locale.h>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Intern {
    std::unordered_map<std::string, int32_t> map;
    std::vector<std::string> names;
    int32_t get(const char* s, size_t n) {
        std::string key(s, n);
        auto it = map.find(key);
        if (it != map.end()) return it->second;
        int32_t id = (int32_t)names.size();
        names.push_back(key);
        map.emplace(std::move(key), id);
        return id;
    }
};

struct Decoder {
    Intern providers;
    Intern vehicles;
    std::string scratch;  // reused unescape buffer
};

// ---- scanning helpers -----------------------------------------------------

inline const char* skip_ws(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p;
}

// Parse a JSON string starting at the opening quote; returns pointer past
// the closing quote, sets [s, n) to the raw contents (escapes left as-is;
// callers that need the decoded text run unescape() on the slice).
inline const char* parse_string(const char* p, const char* end,
                                const char** s, size_t* n) {
    ++p;  // opening quote
    *s = p;
    while (p < end && *p != '"') {
        if (*p == '\\' && p + 1 < end) ++p;
        ++p;
    }
    *n = (size_t)(p - *s);
    return p < end ? p + 1 : p;
}

inline void append_utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) out += (char)cp;
    else if (cp < 0x800) {
        out += (char)(0xC0 | (cp >> 6));
        out += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
        out += (char)(0xE0 | (cp >> 12));
        out += (char)(0x80 | ((cp >> 6) & 0x3F));
        out += (char)(0x80 | (cp & 0x3F));
    } else {
        out += (char)(0xF0 | (cp >> 18));
        out += (char)(0x80 | ((cp >> 12) & 0x3F));
        out += (char)(0x80 | ((cp >> 6) & 0x3F));
        out += (char)(0x80 | (cp & 0x3F));
    }
}

inline int hex4(const char* s) {
    int v = 0;
    for (int i = 0; i < 4; ++i) {
        char c = s[i];
        int d = (c >= '0' && c <= '9')   ? c - '0'
                : (c >= 'a' && c <= 'f') ? c - 'a' + 10
                : (c >= 'A' && c <= 'F') ? c - 'A' + 10
                                         : -1;
        if (d < 0) return -1;
        v = (v << 4) | d;
    }
    return v;
}

// Decode JSON escapes in [s, s+n) into `out` (UTF-8, surrogate pairs merged)
// so interned names match what Python's json module produces.
void unescape(const char* s, size_t n, std::string& out) {
    out.clear();
    out.reserve(n);
    size_t i = 0;
    while (i < n) {
        char c = s[i];
        if (c != '\\') { out += c; ++i; continue; }
        if (i + 1 >= n) { out += c; break; }
        char e = s[i + 1];
        i += 2;
        switch (e) {
            case '"': out += '"'; break;
            case '\\': out += '\\'; break;
            case '/': out += '/'; break;
            case 'b': out += '\b'; break;
            case 'f': out += '\f'; break;
            case 'n': out += '\n'; break;
            case 'r': out += '\r'; break;
            case 't': out += '\t'; break;
            case 'u': {
                if (i + 4 > n) { out += "\\u"; break; }
                int hi = hex4(s + i);
                if (hi < 0) { out += "\\u"; break; }
                i += 4;
                uint32_t cp = (uint32_t)hi;
                if (hi >= 0xD800 && hi <= 0xDBFF && i + 6 <= n &&
                    s[i] == '\\' && s[i + 1] == 'u') {
                    int lo = hex4(s + i + 2);
                    if (lo >= 0xDC00 && lo <= 0xDFFF) {
                        cp = 0x10000 + (((uint32_t)hi - 0xD800) << 10) +
                             ((uint32_t)lo - 0xDC00);
                        i += 6;
                    }
                }
                append_utf8(out, cp);
                break;
            }
            default: out += '\\'; out += e; break;
        }
    }
}

// Skip any JSON value (object/array/string/number/bool/null).
const char* skip_value(const char* p, const char* end) {
    p = skip_ws(p, end);
    if (p >= end) return p;
    if (*p == '"') {
        const char* s; size_t n;
        return parse_string(p, end, &s, &n);
    }
    if (*p == '{' || *p == '[') {
        char open = *p, close = (*p == '{') ? '}' : ']';
        int depth = 0;
        while (p < end) {
            if (*p == '"') {
                const char* s; size_t n;
                p = parse_string(p, end, &s, &n);
                continue;
            }
            if (*p == open) ++depth;
            else if (*p == close && --depth == 0) return p + 1;
            ++p;
        }
        return p;
    }
    while (p < end && *p != ',' && *p != '}' && *p != ']' &&
           *p != '\n') ++p;
    return p;
}

// ISO-8601 "YYYY-MM-DD[T ]HH:MM:SS[.frac][Z|+hh:mm|-hh:mm]" -> epoch secs.
// Days-from-civil (Howard Hinnant's algorithm), no locale, no libc tz.
bool parse_iso8601(const char* s, size_t n, double* out) {
    if (n < 19) return false;
    auto digit = [&](size_t i) { return s[i] >= '0' && s[i] <= '9'; };
    for (size_t i : {0u, 1u, 2u, 3u, 5u, 6u, 8u, 9u, 11u, 12u, 14u, 15u, 17u, 18u})
        if (!digit(i)) return false;
    if (s[4] != '-' || s[7] != '-' || (s[10] != 'T' && s[10] != ' ') ||
        s[13] != ':' || s[16] != ':')
        return false;
    int y = (s[0]-'0')*1000 + (s[1]-'0')*100 + (s[2]-'0')*10 + (s[3]-'0');
    unsigned m = (s[5]-'0')*10 + (s[6]-'0');
    unsigned d = (s[8]-'0')*10 + (s[9]-'0');
    int hh = (s[11]-'0')*10 + (s[12]-'0');
    int mi = (s[14]-'0')*10 + (s[15]-'0');
    int ss = (s[17]-'0')*10 + (s[18]-'0');
    if (m < 1 || m > 12 || d < 1 || d > 31 || hh > 23 || mi > 59 || ss > 60)
        return false;
    size_t i = 19;
    double frac = 0.0;
    if (i < n && s[i] == '.') {
        ++i;
        double scale = 0.1;
        while (i < n && digit(i)) { frac += (s[i]-'0') * scale; scale *= 0.1; ++i; }
    }
    long off = 0;  // seconds east of UTC
    if (i < n) {
        if (s[i] == 'Z') { ++i; }
        else if (s[i] == '+' || s[i] == '-') {
            int sign = (s[i] == '+') ? 1 : -1;
            if (i + 5 < n + 1 && n - i >= 6 && digit(i+1) && digit(i+2) &&
                s[i+3] == ':' && digit(i+4) && digit(i+5)) {
                off = sign * (((s[i+1]-'0')*10 + (s[i+2]-'0')) * 3600 +
                              ((s[i+4]-'0')*10 + (s[i+5]-'0')) * 60);
                i += 6;
            } else return false;
        } else return false;
    }
    // days from civil
    int yy = y - (m <= 2);
    int era = (yy >= 0 ? yy : yy - 399) / 400;
    unsigned yoe = (unsigned)(yy - era * 400);
    unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    long days = (long)era * 146097 + (long)doe - 719468;
    *out = (double)days * 86400.0 + hh * 3600 + mi * 60 + ss + frac - off;
    return true;
}

// Full-string number parse with Python float() semantics: surrounding
// whitespace allowed, optional sign, decimal digits with '_' group
// separators (between digits only), optional fraction/exponent, and the
// inf/infinity/nan words.  The grammar is validated BEFORE strtod so C99
// extensions float() rejects (hex floats) never slip through, and the
// sanitized buffer is parsed under the C locale (strtod_l) so a host
// LC_NUMERIC cannot change which events are accepted.
bool parse_number_string(const char* s, size_t n, double* out) {
    static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    size_t i = 0, j = n;
    auto is_ws = [](char c) {
        return c == ' ' || c == '\t' || c == '\r' || c == '\n' ||
               c == '\f' || c == '\v';
    };
    while (i < j && is_ws(s[i])) ++i;
    while (j > i && is_ws(s[j - 1])) --j;
    if (i >= j) return false;
    std::string buf;
    buf.reserve(j - i);
    size_t k = i;
    if (s[k] == '+' || s[k] == '-') buf += s[k++];
    // word forms float() accepts (any case): inf, infinity, nan
    {
        std::string w;
        for (size_t t = k; t < j; ++t)
            w += (char)tolower((unsigned char)s[t]);
        if (w == "inf" || w == "infinity") { buf += "inf"; }
        else if (w == "nan") { buf += "nan"; }
        else w.clear();
        if (!buf.empty() && (buf.back() == 'f' || buf.back() == 'n')) {
            char* end = nullptr;
            *out = strtod_l(buf.c_str(), &end, c_loc);
            return end && *end == '\0';
        }
    }
    // digits[_digits]* [. digits[_digits]*] [eE[+-]digits[_digits]*]
    auto copy_digits = [&](size_t& t) -> bool {
        bool any = false, prev_digit = false;
        while (t < j) {
            char c = s[t];
            if (c >= '0' && c <= '9') {
                buf += c; any = prev_digit = true; ++t;
            } else if (c == '_') {
                // Python: '_' only BETWEEN digits
                if (!prev_digit || t + 1 >= j || s[t + 1] < '0' ||
                    s[t + 1] > '9')
                    return false;
                prev_digit = false; ++t;
            } else break;
        }
        return any;
    };
    bool int_part = copy_digits(k);
    bool frac_part = false;
    if (k < j && s[k] == '.') {
        buf += '.'; ++k;
        frac_part = copy_digits(k);
    }
    if (!int_part && !frac_part) return false;
    if (k < j && (s[k] == 'e' || s[k] == 'E')) {
        buf += 'e'; ++k;
        if (k < j && (s[k] == '+' || s[k] == '-')) buf += s[k++];
        if (!copy_digits(k)) return false;
    }
    if (k != j) return false;
    char* end = nullptr;
    double v = strtod_l(buf.c_str(), &end, c_loc);
    if (!end || *end != '\0') return false;
    *out = v;
    return true;
}

struct Fields {
    double lat = NAN, lon = NAN, speed = NAN, ts = NAN;
    const char* provider = nullptr; size_t provider_n = 0;
    const char* vehicle = nullptr;  size_t vehicle_n = 0;
    bool provider_null = true, vehicle_null = true;
};

inline bool key_is(const char* k, size_t n, const char* lit) {
    return strlen(lit) == n && memcmp(k, lit, n) == 0;
}

}  // namespace

extern "C" {

void* dec_new() { return new Decoder(); }
void dec_free(void* d) { delete (Decoder*)d; }

int64_t dec_intern_count(void* dv, int which) {
    Decoder* d = (Decoder*)dv;
    return (int64_t)(which == 0 ? d->providers.names.size()
                                : d->vehicles.names.size());
}

const char* dec_intern_get(void* dv, int which, int64_t i) {
    Decoder* d = (Decoder*)dv;
    auto& v = which == 0 ? d->providers.names : d->vehicles.names;
    if (i < 0 || (size_t)i >= v.size()) return "";
    return v[(size_t)i].data();
}

// Byte length of intern i (names may contain NUL from \u0000 escapes, so
// readers must use this rather than strlen).
int64_t dec_intern_len(void* dv, int which, int64_t i) {
    Decoder* d = (Decoder*)dv;
    auto& v = which == 0 ? d->providers.names : d->vehicles.names;
    if (i < 0 || (size_t)i >= v.size()) return 0;
    return (int64_t)v[(size_t)i].size();
}

// Decode up to `cap` events from newline-separated JSON in [buf, buf+len).
// Writes columnar outputs; returns count decoded; *n_dropped counts invalid
// lines; *consumed is the byte offset of the first unprocessed line (always
// at a line boundary), so callers can stream arbitrarily chunked buffers.
int64_t dec_decode(void* dv, const char* buf, int64_t len, int64_t cap,
                   float* lat, float* lon, float* speed, int32_t* ts,
                   int32_t* provider_id, int32_t* vehicle_id,
                   int64_t* n_dropped, int64_t* consumed) {
    Decoder* d = (Decoder*)dv;
    const char* p = buf;
    const char* end = buf + len;
    int64_t out = 0, dropped = 0;
    *consumed = 0;

    while (p < end && out < cap) {
        const char* line = p;
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        if (!nl) break;  // partial trailing line: leave unconsumed for streaming
        const char* lend = nl;
        p = nl + 1;

        const char* q = skip_ws(line, lend);
        if (q >= lend) { *consumed = (int64_t)(p - buf); continue; }
        if (*q != '{') { ++dropped; *consumed = (int64_t)(p - buf); continue; }
        ++q;

        Fields f;
        bool ok = true;
        while (ok && q < lend) {
            q = skip_ws(q, lend);
            if (q < lend && *q == '}') break;
            if (q >= lend || *q != '"') { ok = false; break; }
            const char* k; size_t kn;
            q = parse_string(q, lend, &k, &kn);
            q = skip_ws(q, lend);
            if (q >= lend || *q != ':') { ok = false; break; }
            q = skip_ws(q + 1, lend);
            if (q >= lend) { ok = false; break; }

            if (*q == '"') {
                const char* s; size_t sn;
                q = parse_string(q, lend, &s, &sn);
                if (key_is(k, kn, "provider")) {
                    f.provider = s; f.provider_n = sn; f.provider_null = false;
                } else if (key_is(k, kn, "vehicleId")) {
                    f.vehicle = s; f.vehicle_n = sn; f.vehicle_null = false;
                } else if (key_is(k, kn, "ts")) {
                    double t;
                    if (parse_iso8601(s, sn, &t)) f.ts = t;
                } else if (key_is(k, kn, "lat") || key_is(k, kn, "lon") ||
                           key_is(k, kn, "speedKmh")) {
                    // string-encoded numerics: the Python path coerces via
                    // float() (stream/events.py), so "42.36" must parse the
                    // same here or acceptance becomes toolchain-dependent
                    double v;
                    if (parse_number_string(s, sn, &v)) {
                        if (k[0] == 'l' && k[1] == 'a') f.lat = v;
                        else if (k[0] == 'l') f.lon = v;
                        else f.speed = v;
                    }
                }
            } else if ((*q >= '0' && *q <= '9') || *q == '-' || *q == '+') {
                char* numend = nullptr;
                double v = strtod(q, &numend);
                if (numend == q || numend > lend) { q = skip_value(q, lend); }
                else {
                    if (key_is(k, kn, "lat")) f.lat = v;
                    else if (key_is(k, kn, "lon")) f.lon = v;
                    else if (key_is(k, kn, "speedKmh")) f.speed = v;
                    else if (key_is(k, kn, "ts")) f.ts = v;
                    else if (key_is(k, kn, "vehicleId")) {
                        // numeric identity: the Python path str()-coerces
                        // (stream/events.py:106) and the reference's Spark
                        // StringType schema casts — capture the literal
                        // token so an unwrapped numeric MBTA label
                        // (producers/mbta.py, ref :68) is accepted here
                        // too, not dropped as null.  Identities are opaque
                        // keys: the token spelling ("17.50") is kept as-is
                        // rather than re-canonicalized like Python's
                        // str(17.5).
                        f.vehicle = q; f.vehicle_n = (size_t)(numend - q);
                        f.vehicle_null = false;
                    } else if (key_is(k, kn, "provider")) {
                        f.provider = q; f.provider_n = (size_t)(numend - q);
                        f.provider_null = false;
                    }
                    q = numend;
                }
            } else {
                q = skip_value(q, lend);  // null / bool / nested
            }
            q = skip_ws(q, lend);
            if (q < lend && *q == ',') ++q;
        }

        // validation — mirror stream/events.py (reference filters,
        // heatmap_stream.py:96-104)
        if (!ok || f.provider_null || f.vehicle_null ||
            !std::isfinite(f.lat) || !std::isfinite(f.lon) ||
            f.lat < -90.0 || f.lat > 90.0 ||
            f.lon < -180.0 || f.lon > 180.0 ||
            !std::isfinite(f.ts) || f.ts < 0.0 || f.ts >= 2147483648.0) {
            ++dropped;
            *consumed = (int64_t)(p - buf);
            continue;
        }
        double sp = f.speed;
        if (!std::isfinite(sp)) sp = 0.0;

        lat[out] = (float)f.lat;
        lon[out] = (float)f.lon;
        speed[out] = (float)sp;
        ts[out] = (int32_t)f.ts;
        // fast path: no escapes → intern the raw slice directly
        if (memchr(f.provider, '\\', f.provider_n)) {
            unescape(f.provider, f.provider_n, d->scratch);
            provider_id[out] = d->providers.get(d->scratch.data(), d->scratch.size());
        } else {
            provider_id[out] = d->providers.get(f.provider, f.provider_n);
        }
        if (memchr(f.vehicle, '\\', f.vehicle_n)) {
            unescape(f.vehicle, f.vehicle_n, d->scratch);
            vehicle_id[out] = d->vehicles.get(d->scratch.data(), d->scratch.size());
        } else {
            vehicle_id[out] = d->vehicles.get(f.vehicle, f.vehicle_n);
        }
        ++out;
        *consumed = (int64_t)(p - buf);
    }
    *n_dropped = dropped;
    return out;
}

}  // extern "C"

namespace {

// Strict UTF-8 validity (rejects overlongs, surrogates, >U+10FFFF) — the
// binary path must drop exactly what Python's bytes.decode("utf-8") rejects
// (stream/binfmt.py decode_event), so acceptance is toolchain-independent.
bool utf8_valid(const unsigned char* s, size_t n) {
    size_t i = 0;
    while (i < n) {
        unsigned char c = s[i];
        if (c < 0x80) { ++i; continue; }
        int extra;
        uint32_t cp;
        if ((c & 0xE0) == 0xC0) { extra = 1; cp = c & 0x1F; }
        else if ((c & 0xF0) == 0xE0) { extra = 2; cp = c & 0x0F; }
        else if ((c & 0xF8) == 0xF0) { extra = 3; cp = c & 0x07; }
        else return false;
        if (i + extra >= n) return false;
        for (int k = 1; k <= extra; ++k) {
            unsigned char cc = s[i + k];
            if ((cc & 0xC0) != 0x80) return false;
            cp = (cp << 6) | (cc & 0x3F);
        }
        if (extra == 1 && cp < 0x80) return false;          // overlong
        if (extra == 2 && cp < 0x800) return false;
        if (extra == 3 && cp < 0x10000) return false;
        if (cp >= 0xD800 && cp <= 0xDFFF) return false;     // surrogate
        if (cp > 0x10FFFF) return false;
        i += 1 + extra;
    }
    return true;
}

}  // namespace

extern "C" {

// Decode up to `cap` events from a u32-length-prefixed stream of binary
// event records (layout: stream/binfmt.py — magic 0xB1, version 1).  Same
// output contract as dec_decode; a partial trailing record is left
// unconsumed for streaming.  Invalid envelopes/fields are dropped with
// the same rules as the JSON/Python paths.
int64_t dec_decode_binary(void* dv, const char* buf, int64_t len,
                          int64_t cap,
                          float* lat, float* lon, float* speed, int32_t* ts,
                          int32_t* provider_id, int32_t* vehicle_id,
                          int64_t* n_dropped, int64_t* consumed) {
    Decoder* d = (Decoder*)dv;
    int64_t out = 0, dropped = 0;
    int64_t i = 0;
    *consumed = 0;
    while (i + 4 <= len && out < cap) {
        uint32_t n;
        memcpy(&n, buf + i, 4);
        if (i + 4 + (int64_t)n > len) break;  // partial trailing record
        const unsigned char* r = (const unsigned char*)buf + i + 4;
        i += 4 + n;
        *consumed = i;
        if (n < 32 || r[0] != 0xB1 || r[1] != 1) { ++dropped; continue; }
        uint32_t pn = r[2], vn = r[3];
        if (32 + pn + vn != n) { ++dropped; continue; }
        float f[5];
        memcpy(f, r + 4, 20);
        int64_t tsv;
        memcpy(&tsv, r + 24, 8);
        double la = f[0], lo = f[1];
        if (!std::isfinite(la) || !std::isfinite(lo) ||
            la < -90.0 || la > 90.0 || lo < -180.0 || lo > 180.0 ||
            tsv < 0 || tsv >= 2147483648LL) {
            ++dropped;
            continue;
        }
        if (!utf8_valid(r + 32, pn) || !utf8_valid(r + 32 + pn, vn)) {
            ++dropped;
            continue;
        }
        float sp = f[2];
        if (!std::isfinite(sp)) sp = 0.0f;
        lat[out] = (float)la;
        lon[out] = (float)lo;
        speed[out] = sp;
        ts[out] = (int32_t)tsv;
        provider_id[out] = d->providers.get((const char*)r + 32, pn);
        vehicle_id[out] = d->vehicles.get((const char*)r + 32 + pn, vn);
        ++out;
    }
    *n_dropped = dropped;
    return out;
}

}  // extern "C"

// ---- columnar strtab offsets (stream/colfmt.py hot path) -------------
//
// Parses the [u16 len][bytes]*n string-table blob into per-entry
// (offset, length) arrays in one pass — the Python loop doing this
// (struct.unpack_from per entry) was the top term of the round-5 ingest
// profile.  Returns 0 on success, -1 when an entry runs past the blob.

extern "C" {

int cf_strtab_offsets(const uint8_t* blob, int64_t blob_len, int32_t n,
                      int32_t* offs, int32_t* lens) {
  int64_t off = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (off + 2 > blob_len) return -1;
    uint16_t ln = (uint16_t)(blob[off] | ((uint16_t)blob[off + 1] << 8));
    off += 2;
    if (off + ln > blob_len) return -1;
    offs[i] = (int32_t)off;
    lens[i] = (int32_t)ln;
    off += ln;
  }
  return 0;
}

}  // extern "C"
