// kafka_codec.cpp — RecordBatch v2 decode + CRC32C, in C++.
//
// The Kafka ingest hot path: a Fetch response's records blob is decoded
// straight to a newline-joined VALUES buffer ready for the columnar JSON
// decoder (decoder.cpp), plus per-value kafka offsets so the consumer's
// partial-take/offset bookkeeping keeps working.  Replaces the pure-Python
// per-record zigzag-varint walk and (especially) the per-byte Python
// CRC32C loop in heatmap_tpu/kafka/records.py, whose throughput ceiling
// (~10 MB/s) is far below the BASELINE ingest target.
//
// Semantics mirror records._decode(tolerant=True) exactly: truncated tail
// batches stop the scan; batches with bad CRC / unsupported magic /
// compression are skipped whole with their offset range advanced via the
// header's lastOffsetDelta.  Values containing raw \n or \r (impossible
// in compact JSON, possible in arbitrary payloads) are not emitted —
// they're counted so the caller can fall back to the Python record path
// for that blob.
//
// CRC32C uses the SSE4.2 hardware instruction when compiled with
// -msse4.2 (the build wrapper adds it on x86-64), else a slice-by-8
// table.

#include <cstdint>
#include <cstring>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

// ---- CRC32C --------------------------------------------------------------

#if !defined(__SSE4_2__)
struct Crc32cTable {
    uint32_t t[8][256];
    Crc32cTable() {
        const uint32_t poly = 0x82F63B78u;
        for (uint32_t n = 0; n < 256; n++) {
            uint32_t c = n;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
            t[0][n] = c;
        }
        for (uint32_t n = 0; n < 256; n++)
            for (int k = 1; k < 8; k++)
                t[k][n] = (t[k - 1][n] >> 8) ^ t[0][t[k - 1][n] & 0xFF];
    }
};
const Crc32cTable kTbl;
#endif

uint32_t crc32c_impl(const uint8_t* p, int64_t n, uint32_t crc) {
    crc ^= 0xFFFFFFFFu;
#if defined(__SSE4_2__)
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, p, 8);
        crc = (uint32_t)_mm_crc32_u64(crc, v);
        p += 8;
        n -= 8;
    }
    while (n-- > 0) crc = _mm_crc32_u8(crc, *p++);
#else
    while (n >= 8) {
        uint32_t lo, hi;
        std::memcpy(&lo, p, 4);
        std::memcpy(&hi, p + 4, 4);
        lo ^= crc;
        crc = kTbl.t[7][lo & 0xFF] ^ kTbl.t[6][(lo >> 8) & 0xFF] ^
              kTbl.t[5][(lo >> 16) & 0xFF] ^ kTbl.t[4][lo >> 24] ^
              kTbl.t[3][hi & 0xFF] ^ kTbl.t[2][(hi >> 8) & 0xFF] ^
              kTbl.t[1][(hi >> 16) & 0xFF] ^ kTbl.t[0][hi >> 24];
        p += 8;
        n -= 8;
    }
    while (n-- > 0)
        crc = kTbl.t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
#endif
    return crc ^ 0xFFFFFFFFu;
}

// ---- big-endian / varint readers ----------------------------------------

inline int32_t be32(const uint8_t* p) {
    return (int32_t)(((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                     ((uint32_t)p[2] << 8) | p[3]);
}
inline int64_t be64(const uint8_t* p) {
    return ((int64_t)be32(p) << 32) | (uint32_t)be32(p + 4);
}
inline int16_t be16(const uint8_t* p) {
    return (int16_t)(((uint16_t)p[0] << 8) | p[1]);
}

// zigzag varint; returns false on truncation
inline bool zvarint(const uint8_t* buf, int64_t end, int64_t& i,
                    int64_t& out) {
    uint64_t acc = 0;
    int shift = 0;
    while (i < end && shift <= 63) {
        uint8_t b = buf[i++];
        acc |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            out = (int64_t)(acc >> 1) ^ -(int64_t)(acc & 1);
            return true;
        }
        shift += 7;
    }
    return false;
}

}  // namespace

extern "C" {

uint32_t kc_crc32c(const uint8_t* p, int64_t n, uint32_t crc) {
    return crc32c_impl(p, n, crc);
}

// Decode a Fetch records blob into a joined values buffer.
//
//   framing   : 0 = newline-joined (JSON values; records whose value
//               contains a raw \n/\r are counted as oddballs and the
//               caller falls back); 1 = u32-length-prefixed (arbitrary
//               bytes — the binary event format, stream/binfmt.py)
//   blob      : out, >= len + cap_vals * (framing ? 4 : 1) bytes
//   val_off   : out, kafka offset of emitted value v
//   val_pos   : out, start of value v's frame in blob
//   out_state : [blob_len, next_offset, n_skipped_batches, n_oddballs,
//               n_null]
//
// Emits only records with offset >= start_offset and non-null values.
// Returns the number of emitted values, or -1 when an output capacity is
// exceeded (caller sizes capacities so this cannot happen for well-formed
// input; -1 therefore means malformed varints, and the caller falls back
// to the Python path).
int64_t kc_decode_values(
    const uint8_t* buf, int64_t len,
    int64_t start_offset, int32_t verify_crc, int32_t framing,
    uint8_t* blob, int64_t blob_cap,
    int64_t* val_off, int64_t* val_pos, int64_t cap_vals,
    int64_t* out_state) {
    int64_t n_vals = 0, blob_len = 0, skipped = 0, n_odd = 0, n_null = 0;
    int64_t next_offset = start_offset;
    int64_t i = 0;
    while (i + 12 <= len) {
        int64_t base_offset = be64(buf + i);
        int32_t batch_len = be32(buf + i + 8);
        int64_t end = i + 12 + batch_len;
        if (batch_len <= 0 || end > len) break;  // truncated tail
        bool ok = end - i >= 61;
        int8_t magic = ok ? (int8_t)buf[i + 16] : -1;
        if (ok && magic != 2) ok = false;
        if (ok) {
            uint32_t crc = (uint32_t)be32(buf + i + 17);
            int16_t attributes = be16(buf + i + 21);
            if (attributes & 0x07) ok = false;  // compressed
            if (ok && verify_crc &&
                crc32c_impl(buf + i + 21, end - (i + 21), 0) != crc)
                ok = false;
        }
        if (!ok) {
            // skip whole batch; advance offsets via lastOffsetDelta when
            // readable (fixed position i+23, mirror records.py)
            if (i + 27 <= len) {
                int32_t last_delta = be32(buf + i + 23);
                int64_t cand = base_offset + last_delta + 1;
                if (cand > next_offset) next_offset = cand;
            } else if (base_offset + 1 > next_offset) {
                next_offset = base_offset + 1;
            }
            skipped++;
            i = end;
            continue;
        }
        int32_t n = be32(buf + i + 57);
        int64_t j = i + 61;
        for (int32_t r = 0; r < n; r++) {
            int64_t rec_len;
            if (!zvarint(buf, end, j, rec_len)) return -1;
            int64_t rec_end = j + rec_len;
            if (rec_end > end) return -1;
            int64_t k = j;
            k++;  // record attributes
            int64_t ts_delta, off_delta, kn, vn;
            if (!zvarint(buf, rec_end, k, ts_delta)) return -1;
            if (!zvarint(buf, rec_end, k, off_delta)) return -1;
            if (!zvarint(buf, rec_end, k, kn)) return -1;
            k += kn > 0 ? kn : 0;
            if (!zvarint(buf, rec_end, k, vn)) return -1;
            int64_t voff = base_offset + off_delta;
            if (voff + 1 > next_offset) next_offset = voff + 1;
            if (voff >= start_offset) {
                if (vn < 0) {
                    n_null++;
                } else {
                    if (k + vn > rec_end) return -1;
                    bool odd = false;
                    if (framing == 0) {
                        for (int64_t t = 0; t < vn; t++) {
                            uint8_t c = buf[k + t];
                            if (c == '\n' || c == '\r') { odd = true; break; }
                        }
                    }
                    if (odd) {
                        n_odd++;
                    } else {
                        int64_t frame = framing ? vn + 4 : vn + 1;
                        if (n_vals >= cap_vals ||
                            blob_len + frame > blob_cap)
                            return -1;
                        val_off[n_vals] = voff;
                        val_pos[n_vals] = blob_len;
                        if (framing) {
                            uint32_t vlen = (uint32_t)vn;
                            std::memcpy(blob + blob_len, &vlen, 4);
                            blob_len += 4;
                        }
                        std::memcpy(blob + blob_len, buf + k, vn);
                        blob_len += vn;
                        if (!framing) blob[blob_len++] = '\n';
                        n_vals++;
                    }
                }
            }
            j = rec_end;
        }
        i = end;
    }
    out_state[0] = blob_len;
    out_state[1] = next_offset;
    out_state[2] = skipped;
    out_state[3] = n_odd;
    out_state[4] = n_null;
    return n_vals;
}

}  // extern "C"
