// tile_ops.cpp — packed-emit rows -> BSON update-op documents, in C++.
//
// The sink hot path of the streaming runtime: each micro-batch's device
// emit arrives on the host as the packed (E+1, 13) uint32 matrix
// (heatmap_tpu/engine/step.py pack_emit).  The reference built one Python
// dict per tile row on the Spark driver and let pymongo's C extension
// encode it (reference: heatmap_stream.py:163-196); here the whole
// row -> {q: {_id}, u: {$set: doc}, upsert: true} transformation runs in
// C++ straight from the columnar buffer to wire-ready BSON, so the Python
// layer never touches individual tile rows.
//
// The output is the concatenated op documents of the `update` command's
// "updates" document sequence (OP_MSG section kind 1); per-op end offsets
// let the caller chunk at the reference's 1000-op bulk size without
// re-parsing.  Field order and numeric semantics replicate
// sink/base.py::TileDoc + stream/runtime.py::_emit_docs exactly (the
// differential test decodes both and compares).
//
// Build: part of the heatmap-tpu native library (see native/__init__.py);
// no dependencies beyond the C++17 standard library.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cmath>
#include <vector>

namespace {

// ---- little-endian appenders into a caller-provided buffer ---------------

struct Buf {
    uint8_t* p;
    int64_t cap;
    int64_t len = 0;
    bool overflow = false;

    void need(int64_t n) {
        if (len + n > cap) overflow = true;
    }
    void raw(const void* src, int64_t n) {
        need(n);
        if (!overflow) std::memcpy(p + len, src, n);
        len += n;  // track virtual length even on overflow (for sizing)
    }
    void u8(uint8_t v) { raw(&v, 1); }
    void i32(int32_t v) { raw(&v, 4); }
    void i64(int64_t v) { raw(&v, 8); }
    void f64(double v) { raw(&v, 8); }
    void cstr(const char* s) { raw(s, (int64_t)std::strlen(s) + 1); }
    // reserve an int32 length slot; return its offset for backpatching
    int64_t mark() { int64_t at = len; i32(0); return at; }
    void patch(int64_t at) {
        if (overflow) return;
        int32_t total = (int32_t)(len - at);
        std::memcpy(p + at, &total, 4);
    }
};

// BSON element writers (type byte + name cstring + payload)
void el_str(Buf& b, const char* name, const char* s, int64_t n) {
    b.u8(0x02); b.cstr(name);
    b.i32((int32_t)(n + 1)); b.raw(s, n); b.u8(0);
}
void el_i32(Buf& b, const char* name, int32_t v) { b.u8(0x10); b.cstr(name); b.i32(v); }
void el_f64(Buf& b, const char* name, double v) { b.u8(0x01); b.cstr(name); b.f64(v); }
void el_dt(Buf& b, const char* name, int64_t ms) { b.u8(0x09); b.cstr(name); b.i64(ms); }
void el_bool(Buf& b, const char* name, bool v) { b.u8(0x08); b.cstr(name); b.u8(v ? 1 : 0); }
int64_t doc_open(Buf& b, const char* name) {  // subdocument element
    b.u8(0x03); b.cstr(name); return b.mark();
}
void doc_close(Buf& b, int64_t at) { b.u8(0); b.patch(at); }

// ---- civil-calendar conversion (Howard Hinnant's algorithm) --------------

void iso_z_from_epoch(int64_t sec, char out[24]) {
    int64_t days = sec / 86400;
    int64_t rem = sec % 86400;
    if (rem < 0) { rem += 86400; days -= 1; }
    int64_t z = days + 719468;
    int64_t era = (z >= 0 ? z : z - 146096) / 146097;
    int64_t doe = z - era * 146097;
    int64_t yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
    int64_t y = yoe + era * 400;
    int64_t doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    int64_t mp = (5 * doy + 2) / 153;
    int64_t d = doy - (153 * mp + 2) / 5 + 1;
    int64_t m = mp < 10 ? mp + 3 : mp - 9;
    if (m <= 2) y += 1;
    std::snprintf(out, 24, "%04lld-%02lld-%02lldT%02lld:%02lld:%02lldZ",
                  (long long)y, (long long)m, (long long)d,
                  (long long)(rem / 3600), (long long)((rem / 60) % 60),
                  (long long)(rem % 60));
}

int hex_u64(uint64_t v, char out[17]) {  // lowercase, no leading zeros
    if (v == 0) { out[0] = '0'; out[1] = 0; return 1; }
    char tmp[16];
    int n = 0;
    while (v) { tmp[n++] = "0123456789abcdef"[v & 0xF]; v >>= 4; }
    for (int i = 0; i < n; i++) out[i] = tmp[n - 1 - i];
    out[n] = 0;
    return n;
}

inline float as_f32(uint32_t bits) {
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
}

}  // namespace

extern "C" {

// body: (n_rows, 13) uint32 row-major — the packed emit matrix WITHOUT its
// head row (lanes: key_hi, key_lo, ws, count, sum_speed, sum_speed2,
// sum_lat, sum_lon, valid, p95, anchor_speed, anchor_lat, anchor_lon;
// float lanes bitcast, see engine/step.py).  The sum lanes are residual
// sums about the anchor lanes; averages recombine anchor + resid/count
// here in double precision (the device has no f64 — engine/state.py).
// Writes concatenated BSON update-op docs into out (skipping rows with
// valid==0 or count<=0), records each op's END offset in offsets[i]
// (i = 0..n_docs-1), sets *bytes_out to the total length, and returns the
// doc count.  Returns -(needed_bytes) when cap is too small — call again
// with a buffer of at least that size.
int64_t enc_tile_ops(
    const uint32_t* body, int64_t n_rows,
    const char* city, const char* grid,
    int64_t window_ms, int64_t ttl_ms,
    int32_t window_minutes_tag, int32_t with_p95,
    uint8_t* out, int64_t cap,
    int64_t* offsets, int64_t* bytes_out) {
    Buf b{out, cap};
    int64_t n_docs = 0;
    char cell_hex[17];
    char iso[24];
    // _id = city|grid|cellhex|iso — sized from the actual inputs so no
    // row is ever skipped (the Python fallback drops none either)
    std::vector<char> idbuf(std::strlen(city) + std::strlen(grid)
                            + 16 + 23 + 3 + 1);

    for (int64_t r = 0; r < n_rows; r++) {
        const uint32_t* row = body + r * 13;
        if (row[8] == 0) continue;                 // valid lane
        int32_t count = (int32_t)row[3];
        if (count <= 0) continue;

        uint64_t cell = ((uint64_t)row[0] << 32) | row[1];
        int64_t ws = (int32_t)row[2];
        double sum_speed = as_f32(row[4]);
        double sum_speed2 = as_f32(row[5]);
        double sum_lat = as_f32(row[6]);
        double sum_lon = as_f32(row[7]);
        double p95 = as_f32(row[9]);
        double anchor_speed = as_f32(row[10]);
        double anchor_lat = as_f32(row[11]);
        double anchor_lon = as_f32(row[12]);

        hex_u64(cell, cell_hex);
        iso_z_from_epoch(ws, iso);
        int idn = std::snprintf(idbuf.data(), idbuf.size(), "%s|%s|%s|%s",
                                city, grid, cell_hex, iso);

        // residual moments: mean_r recombines with the anchor for the
        // average; variance is anchor-invariant (Var(v) = E[r^2]-E[r]^2)
        double mean_r = sum_speed / count;
        double avg_speed = anchor_speed + mean_r;
        double var = sum_speed2 / count - mean_r * mean_r;
        if (var < 0.0) var = 0.0;
        double stddev = std::sqrt(var);
        int64_t ws_ms = ws * 1000;
        int64_t we_ms = ws_ms + window_ms;

        int64_t op = b.mark();                     // op document
        {
            int64_t q = doc_open(b, "q");
            el_str(b, "_id", idbuf.data(), idn);
            doc_close(b, q);

            int64_t u = doc_open(b, "u");
            {
                int64_t set = doc_open(b, "$set");
                el_str(b, "_id", idbuf.data(), idn);
                el_str(b, "city", city, (int64_t)std::strlen(city));
                el_str(b, "grid", grid, (int64_t)std::strlen(grid));
                el_str(b, "cellId", cell_hex,
                       (int64_t)std::strlen(cell_hex));
                el_dt(b, "windowStart", ws_ms);
                el_dt(b, "windowEnd", we_ms);
                el_i32(b, "count", count);
                el_f64(b, "avgSpeedKmh", avg_speed);
                {
                    int64_t c = doc_open(b, "centroid");
                    el_str(b, "type", "Point", 5);
                    // BSON array = doc with "0","1" keys
                    b.u8(0x04); b.cstr("coordinates");
                    int64_t arr = b.mark();
                    el_f64(b, "0", anchor_lon + sum_lon / count);
                    el_f64(b, "1", anchor_lat + sum_lat / count);
                    b.u8(0); b.patch(arr);
                    doc_close(b, c);
                }
                el_dt(b, "staleAt", we_ms + ttl_ms);
                el_f64(b, "stddevSpeedKmh", stddev);
                if (with_p95) el_f64(b, "p95SpeedKmh", p95);
                if (window_minutes_tag)
                    el_i32(b, "windowMinutes", window_minutes_tag);
                doc_close(b, set);
            }
            doc_close(b, u);

            el_bool(b, "upsert", true);
        }
        b.u8(0);
        b.patch(op);
        if (offsets) offsets[n_docs] = b.len;
        n_docs++;
    }
    *bytes_out = b.len;
    if (b.overflow) return -b.len;
    return n_docs;
}

}  // extern "C"

// ---- binary wire-frame column writer (serve/wire.py fast path) ----------
//
// The serve tier's compact tile/delta frame: the header is assembled in
// Python (a few dozen bytes); this writes the column section — per-doc
// flag bytes, zigzag-varint cell-id deltas, varint counts, the three
// float columns (raw f64 bits or x100 fixed-point zigzag varints — the
// ENCODING DECISION is made in Python by the same helper the pure-Python
// writer uses, so both bodies are byte-identical by construction), varint
// windowMinutes, and raw i64 per-doc window overrides.  Float columns
// arrive as int64 arrays either way: f64 BITS for enc 0 (memcpy'd
// little-endian, exactly what struct.pack("<d") emits), scaled ints for
// enc 1.  Returns 0 and sets *bytes_out, or -needed_bytes on overflow
// (same resize convention as enc_tile_ops).

namespace {

inline void put_varint(Buf& b, uint64_t u) {
    while (true) {
        uint8_t x = (uint8_t)(u & 0x7F);
        u >>= 7;
        if (u) b.u8(x | 0x80);
        else { b.u8(x); return; }
    }
}

inline uint64_t zigzag64(int64_t v) {
    return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
}

inline void put_float_col(Buf& b, int32_t enc, const int64_t* vals,
                          int64_t n) {
    b.u8((uint8_t)enc);
    if (enc == 0) {
        b.raw(vals, 8 * n);  // little-endian f64 bits
    } else {
        for (int64_t i = 0; i < n; i++) put_varint(b, zigzag64(vals[i]));
    }
}

}  // namespace

extern "C" {

int64_t enc_wire_cols(
    const uint8_t* flags, int64_t n,
    const int64_t* deltas,
    const int64_t* counts,
    int32_t s_enc, const int64_t* speeds,
    int32_t p_enc, const int64_t* p95, int64_t n_p95,
    int32_t d_enc, const int64_t* stddev, int64_t n_std,
    const int64_t* wmin, int64_t n_wmin,
    const int64_t* overrides, int64_t n_ovr_vals,
    uint8_t* out, int64_t cap, int64_t* bytes_out) {
    Buf b{out, cap};
    b.raw(flags, n);
    for (int64_t i = 0; i < n; i++) put_varint(b, zigzag64(deltas[i]));
    for (int64_t i = 0; i < n; i++) put_varint(b, (uint64_t)counts[i]);
    put_float_col(b, s_enc, speeds, n);
    put_float_col(b, p_enc, p95, n_p95);
    put_float_col(b, d_enc, stddev, n_std);
    for (int64_t i = 0; i < n_wmin; i++)
        put_varint(b, (uint64_t)wmin[i]);
    b.raw(overrides, 8 * n_ovr_vals);
    *bytes_out = b.len;
    if (b.overflow) return -b.len;
    return 0;
}

}  // extern "C"
