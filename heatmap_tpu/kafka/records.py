"""Kafka RecordBatch v2 (magic 2) encode/decode with CRC32C.

This is the on-wire unit both Produce and Fetch move (message format v2,
the only format modern brokers write).  Compression is not used — the
pipeline's JSON events are small and the decode hot path feeds the native
columnar decoder, so attributes are always 0 (no codec, create-time
timestamps).  Compressed inbound batches raise; the source logs and skips.

CRC32C (Castagnoli) is table-driven; the checksum covers the bytes from
``attributes`` through the end of the batch, per the spec.
"""

from __future__ import annotations

import dataclasses
import struct

from heatmap_tpu.kafka.protocol import Reader, Writer

# ---- CRC32C ----------------------------------------------------------------

_POLY = 0x82F63B78


def _make_table():
    tbl = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        tbl.append(c)
    return tbl


_TABLE = _make_table()
_NATIVE_CRC = None
_NATIVE_PROBED = False


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C; dispatches to the C++ implementation (hardware SSE4.2 on
    x86) when the toolchain allows — the Python table walk is ~10 MB/s,
    three orders below the ingest target."""
    global _NATIVE_CRC, _NATIVE_PROBED
    if not _NATIVE_PROBED:
        _NATIVE_PROBED = True
        try:
            from heatmap_tpu.native import crc32c_native

            if crc32c_native(b"123456789") == 0xE3069283:  # spec check value
                _NATIVE_CRC = crc32c_native
        except Exception:
            _NATIVE_CRC = None
    if _NATIVE_CRC is not None:
        return _NATIVE_CRC(bytes(data), crc)
    crc ^= 0xFFFFFFFF
    tbl = _TABLE
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---- records ---------------------------------------------------------------

@dataclasses.dataclass
class Record:
    offset: int
    timestamp_ms: int
    key: bytes | None
    value: bytes | None
    headers: list[tuple[str, bytes]] = dataclasses.field(default_factory=list)


def encode_batch(records: list[Record], base_offset: int = 0) -> bytes:
    """One RecordBatch v2; offsets/timestamps are taken from the records
    relative to records[0]."""
    if not records:
        raise ValueError("empty batch")
    base_ts = records[0].timestamp_ms
    max_ts = max(r.timestamp_ms for r in records)
    body = Writer()
    for i, r in enumerate(records):
        rec = Writer()
        rec.i8(0)  # record attributes (unused)
        rec.varint(r.timestamp_ms - base_ts)
        rec.varint(i)
        for blob in (r.key, r.value):
            if blob is None:
                rec.varint(-1)
            else:
                rec.varint(len(blob))
                rec.raw(blob)
        rec.varint(len(r.headers))
        for hk, hv in r.headers:
            kb = hk.encode("utf-8")
            rec.varint(len(kb))
            rec.raw(kb)
            rec.varint(len(hv))
            rec.raw(hv)
        payload = rec.build()
        body.varint(len(payload))
        body.raw(payload)
    records_bytes = body.build()

    crced = Writer()
    crced.i16(0)                       # attributes: no compression
    crced.i32(len(records) - 1)        # lastOffsetDelta
    crced.i64(base_ts)
    crced.i64(max_ts)
    crced.i64(-1).i16(-1).i32(-1)      # producerId/Epoch, baseSequence
    crced.i32(len(records))
    crced.raw(records_bytes)
    crced_bytes = crced.build()

    head = Writer()
    head.i64(base_offset)
    head.i32(4 + 1 + 4 + len(crced_bytes))  # batchLength: after this field
    head.i32(-1)                       # partitionLeaderEpoch
    head.i8(2)                         # magic
    head.u32(crc32c(crced_bytes))
    return head.build() + crced_bytes


def decode_batches(buf: bytes, verify_crc: bool = True) -> list[Record]:
    """All records from a (possibly multi-batch, possibly truncated-tail)
    Fetch records blob; a truncated final batch is skipped, matching broker
    semantics (brokers may return partial batches at the end).  Raises
    ValueError on corrupt/compressed batches — streaming consumers that
    must keep moving use ``decode_batches_tolerant``."""
    return _decode(buf, verify_crc, tolerant=False)[0]


def decode_batches_tolerant(buf: bytes, start_offset: int,
                            verify_crc: bool = True
                            ) -> tuple[list[Record], int, int]:
    """(records, next_offset, n_skipped_batches): undecodable batches
    (bad CRC, unsupported compression/magic) are skipped whole — their
    offset range is still advanced past via the batch header, so a
    poisoned batch can never wedge the consumer at the same offset."""
    return _decode(buf, verify_crc, tolerant=True, start_offset=start_offset)


def _decode(buf: bytes, verify_crc: bool, tolerant: bool,
            start_offset: int = 0) -> tuple[list[Record], int, int]:
    out: list[Record] = []
    next_offset = start_offset
    skipped = 0
    i = 0
    while i + 12 <= len(buf):
        base_offset, batch_len = struct.unpack_from(">qi", buf, i)
        end = i + 12 + batch_len
        if batch_len <= 0 or end > len(buf):
            break  # truncated tail
        r = Reader(buf, i + 12)
        r.i32()  # partitionLeaderEpoch
        magic = r.i8()
        crc = r.u32()
        try:
            if magic != 2:
                raise ValueError(f"unsupported record magic {magic}")
            crced = buf[r.i:end]
            if verify_crc and crc32c(crced) != crc:
                raise ValueError("record batch CRC32C mismatch")
            attributes = r.i16()
            if attributes & 0x07:
                raise ValueError("compressed record batches unsupported")
        except ValueError:
            if not tolerant:
                raise
            # lastOffsetDelta sits at a fixed position (after epoch(4) +
            # magic(1) + crc(4) + attributes(2)); readable even when the
            # CRC/codec check failed
            try:
                last_delta = struct.unpack_from(">i", buf, i + 12 + 11)[0]
                next_offset = max(next_offset, base_offset + last_delta + 1)
            except struct.error:
                next_offset = max(next_offset, base_offset + 1)
            skipped += 1
            i = end
            continue
        r.i32()  # lastOffsetDelta
        base_ts = r.i64()
        r.i64()  # maxTimestamp
        r.i64()  # producerId
        r.i16()  # producerEpoch
        r.i32()  # baseSequence
        n = r.i32()
        for _ in range(n):
            length = r.varint()
            rec_end = r.i + length
            r.i8()  # record attributes
            ts_delta = r.varint()
            off_delta = r.varint()
            kn = r.varint()
            key = bytes(r.buf[r.i:r.i + kn]) if kn >= 0 else None
            r.i += max(kn, 0)
            vn = r.varint()
            value = bytes(r.buf[r.i:r.i + vn]) if vn >= 0 else None
            r.i += max(vn, 0)
            hn = r.varint()
            headers = []
            for _ in range(hn):
                hkn = r.varint()
                hk = bytes(r.buf[r.i:r.i + hkn]).decode("utf-8")
                r.i += hkn
                hvn = r.varint()
                hv = bytes(r.buf[r.i:r.i + hvn]) if hvn >= 0 else b""
                r.i += max(hvn, 0)
                headers.append((hk, hv))
            r.i = rec_end
            out.append(Record(base_offset + off_delta, base_ts + ts_delta,
                              key, value, headers))
            next_offset = max(next_offset, base_offset + off_delta + 1)
        i = end
    return out, next_offset, skipped
