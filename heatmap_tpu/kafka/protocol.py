"""Kafka protocol primitives and request/response framing.

Non-flexible (pre-KIP-482) encodings only — no tagged fields.  The
client negotiates per-connection version RANGES within that encoding
family (client.py `_SUPPORTED`): ApiVersions v0, Metadata v1-v7,
ListOffsets v1-v3, Produce v3-v7, Fetch v4-v11 — floors serve pre-KIP
brokers (0.11+, message format v2), ceilings survive the KIP-896
(Kafka 4.0) removals of early versions.  Kept deliberately small; see
kafka/client.py for negotiation and use.
"""

from __future__ import annotations

import struct


class Reader:
    __slots__ = ("buf", "i")

    def __init__(self, buf: bytes, i: int = 0):
        self.buf = buf
        self.i = i

    def _take(self, n: int) -> bytes:
        b = self.buf[self.i:self.i + n]
        if len(b) != n:
            raise EOFError("truncated Kafka frame")
        self.i += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> str | None:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)

    def array(self, fn):
        n = self.i32()
        if n < 0:
            return None
        return [fn() for _ in range(n)]

    def varint(self) -> int:
        """Zigzag varint (record encoding)."""
        shift, acc = 0, 0
        while True:
            b = self.buf[self.i]
            self.i += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def remaining(self) -> int:
        return len(self.buf) - self.i


class Writer:
    __slots__ = ("parts",)

    def __init__(self):
        self.parts: list[bytes] = []

    def i8(self, v: int):
        self.parts.append(struct.pack(">b", v))
        return self

    def i16(self, v: int):
        self.parts.append(struct.pack(">h", v))
        return self

    def i32(self, v: int):
        self.parts.append(struct.pack(">i", v))
        return self

    def i64(self, v: int):
        self.parts.append(struct.pack(">q", v))
        return self

    def u32(self, v: int):
        self.parts.append(struct.pack(">I", v))
        return self

    def string(self, v: str | None):
        if v is None:
            return self.i16(-1)
        b = v.encode("utf-8")
        self.i16(len(b))
        self.parts.append(b)
        return self

    def bytes_(self, v: bytes | None):
        if v is None:
            return self.i32(-1)
        self.i32(len(v))
        self.parts.append(bytes(v))
        return self

    def array(self, items, fn):
        self.i32(len(items))
        for it in items:
            fn(it)
        return self

    def varint(self, v: int):
        """Zigzag varint (record encoding)."""
        z = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                self.parts.append(bytes([b | 0x80]))
            else:
                self.parts.append(bytes([b]))
                return self

    def raw(self, b: bytes):
        self.parts.append(bytes(b))
        return self

    def build(self) -> bytes:
        return b"".join(self.parts)


def frame_request(api_key: int, api_version: int, correlation_id: int,
                  client_id: str, body: bytes) -> bytes:
    head = Writer().i16(api_key).i16(api_version).i32(correlation_id) \
                   .string(client_id).build()
    return struct.pack(">i", len(head) + len(body)) + head + body


def read_frame(recv_exact) -> tuple[int, Reader]:
    """(correlation_id, body reader) from a length-prefixed response."""
    (size,) = struct.unpack(">i", recv_exact(4))
    buf = recv_exact(size)
    r = Reader(buf)
    return r.i32(), r


# API keys used by the client
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_VERSIONS = 18

ERRORS = {
    0: "NONE",
    1: "OFFSET_OUT_OF_RANGE",
    3: "UNKNOWN_TOPIC_OR_PARTITION",
    5: "LEADER_NOT_AVAILABLE",
    6: "NOT_LEADER_OR_FOLLOWER",
    7: "REQUEST_TIMED_OUT",
    35: "UNSUPPORTED_VERSION",
}
