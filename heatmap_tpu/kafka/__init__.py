"""kafka — native Kafka wire-protocol client (no librdkafka, no kafka-python).

The reference rides external Kafka clients: kafka-python in the producer
(mbta_to_kafka.py:33-39) and Spark's spark-sql-kafka connector in the
consumer (heatmap_stream.py:79-86; README.md:131-133).  Neither exists in
this image, and SURVEY.md §2b calls for an in-framework consumer feeding
host buffers.  This package implements the Kafka binary protocol directly
over stdlib sockets:

- ``protocol`` — primitive codecs + request/response framing
- ``records``  — RecordBatch v2 encode/decode with CRC32C
- ``client``   — broker client: metadata, produce, fetch, list_offsets,
                 with per-partition leader routing

Design choice: **no consumer groups.**  The reference's offsets live in the
Spark checkpoint, not the broker (README.md:214-215); this framework keeps
the same ownership — per-partition offsets are committed through
``stream.checkpoint``, so JoinGroup/SyncGroup/OffsetCommit are never
needed and replay after crash is exact.
"""

from heatmap_tpu.kafka.client import (  # noqa: F401
    BrokerClient, FetchResult, KafkaClient, KafkaError,
)
from heatmap_tpu.kafka.records import (  # noqa: F401
    Record, decode_batches, decode_batches_tolerant, encode_batch,
)
