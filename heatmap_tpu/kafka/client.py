"""Kafka broker client: metadata, produce, fetch, list_offsets.

``BrokerClient`` is one TCP connection to one broker.  ``KafkaClient``
adds cluster awareness: it bootstraps metadata, routes produce/fetch to
each partition's leader, and refreshes + retries once on leadership
errors (NOT_LEADER_OR_FOLLOWER / LEADER_NOT_AVAILABLE / UNKNOWN_TOPIC).

API versions are NEGOTIATED per connection: ``BrokerClient`` reads the
broker's ApiVersions response and uses the highest version inside both
the broker's range and this client's implemented range (``_SUPPORTED``,
all non-flexible encodings), failing at connect with an actionable
message when there is no overlap (e.g. a post-4.x broker that finally
drops them).
Offsets are the caller's responsibility (framework checkpoint ownership,
see package docstring).
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
import time
import typing
from heatmap_tpu.kafka import records as rec
from heatmap_tpu.kafka.protocol import (
    API_FETCH, API_LIST_OFFSETS, API_METADATA, API_PRODUCE, API_VERSIONS,
    ERRORS, Reader, Writer, frame_request, read_frame,
)

_corr = itertools.count(1)

# Implemented per-API version RANGES (all non-flexible encodings; flexible
# starts at Produce v9 / Fetch v12 / Metadata v9 / ListOffsets v6).  Each
# connection negotiates the highest version inside both this range and the
# broker's advertised range (ApiVersions), so the client works against any
# broker era with an overlap: the floors are what kafka-python-era clients
# use (kept by every broker through at least 4.x), the ceilings cover the
# KIP-896 (Kafka 4.0) removals of early versions.
_SUPPORTED = {API_PRODUCE: (3, 7), API_FETCH: (4, 11),
              API_LIST_OFFSETS: (1, 3), API_METADATA: (1, 7),
              API_VERSIONS: (0, 0)}

EARLIEST = -2
LATEST = -1


def murmur2(data: bytes) -> int:
    """Kafka's murmur2 (the Java client's default partitioner hash), so
    keys produced here land on the same partitions any stock client uses."""
    mask = 0xFFFFFFFF
    m, r = 0x5BD1E995, 24
    h = (0x9747B28C ^ len(data)) & mask
    i = 0
    while len(data) - i >= 4:
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * m) & mask
        k ^= k >> r
        k = (k * m) & mask
        h = (h * m) & mask
        h ^= k
        i += 4
    rem = len(data) - i
    if rem >= 3:
        h ^= data[i + 2] << 16
    if rem >= 2:
        h ^= data[i + 1] << 8
    if rem >= 1:
        h ^= data[i]
        h = (h * m) & mask
    h ^= h >> 13
    h = (h * m) & mask
    h ^= h >> 15
    return h


def partition_for_key(key: bytes, n_partitions: int) -> int:
    return (murmur2(key) & 0x7FFFFFFF) % n_partitions


class KafkaError(RuntimeError):
    def __init__(self, code: int, where: str):
        super().__init__(f"{where}: {ERRORS.get(code, code)} ({code})")
        self.code = code


_RETRIABLE = {3, 5, 6}  # unknown topic/partition, leader not available/moved


class FetchResult(typing.NamedTuple):
    """``next_offset`` is where the next fetch should resume: past every
    decoded record AND past any skipped (corrupt/compressed) batch, so a
    poisoned batch or a tail tombstone can never wedge the consumer."""

    high_watermark: int
    records: list
    next_offset: int
    skipped_batches: int


class BrokerClient:
    """One connection, synchronous request/response."""

    def __init__(self, host: str, port: int, client_id: str = "heatmap-tpu",
                 timeout_s: float = 10.0):
        self.host, self.port = host, port
        self.client_id = client_id
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._dead = False
        # per-API versions in use on THIS connection; ApiVersions itself
        # must go out before negotiation completes, hence the seed entry
        self._use: dict[int, int] = {API_VERSIONS: 0}
        try:
            self._check_versions()
        except Exception:
            # fail-at-connect must not leak the just-opened socket (a
            # reconnect loop against an incompatible broker would pile
            # up open connections until GC)
            self.close()
            raise

    def _recv_exact(self, n: int) -> bytes:
        from heatmap_tpu.utils.netio import recv_exact

        return recv_exact(self._sock, n)

    def request(self, api_key: int, body: bytes) -> Reader:
        if self._dead:
            raise ConnectionError("connection poisoned; reconnect")
        cid = next(_corr)
        msg = frame_request(api_key, self._use[api_key], cid,
                            self.client_id, body)
        with self._lock:
            try:
                self._sock.sendall(msg)
                got_cid, r = read_frame(self._recv_exact)
            except OSError:
                self._dead = True
                self.close()
                raise
        if got_cid != cid:
            self._dead = True
            self.close()
            raise ConnectionError(
                f"correlation id {got_cid} != {cid} (desynced)")
        return r

    def _check_versions(self) -> None:
        r = self.request(API_VERSIONS, b"")
        err = r.i16()
        if err:
            raise KafkaError(err, "ApiVersions")
        supported = {}
        for _ in range(r.i32()):
            k, lo, hi = r.i16(), r.i16(), r.i16()
            supported[k] = (lo, hi)
        names = {API_PRODUCE: "Produce", API_FETCH: "Fetch",
                 API_LIST_OFFSETS: "ListOffsets", API_METADATA: "Metadata"}
        for k, (lo_i, hi_i) in _SUPPORTED.items():
            if k == API_VERSIONS:
                continue
            lo_b, hi_b = supported.get(k, (0, -1))
            use = min(hi_i, hi_b)
            if use < max(lo_i, lo_b):
                # no overlap between what we implement and what the broker
                # serves — fail AT CONNECT with the ranges and a remedy
                raise KafkaError(
                    35,
                    f"broker serves {names.get(k, f'api {k}')} "
                    f"v{lo_b}..v{hi_b}; this client implements "
                    f"v{lo_i}..v{hi_i} (non-flexible encodings) with no "
                    f"overlap — use a broker within Kafka 2.1..4.x-era "
                    f"protocol support, or HEATMAP_KAFKA_IMPL="
                    f"confluent/kafka-python for a library client")
            self._use[k] = use

    # ---- requests ---------------------------------------------------------

    def metadata(self, topics: list[str] | None = None) -> dict:
        v = self._use[API_METADATA]
        w = Writer()
        if topics is None:
            w.i32(-1)
        else:
            w.array(topics, w.string)
        if v >= 4:
            w.i8(1)  # allow_auto_topic_creation (v1-v3 behavior)
        r = self.request(API_METADATA, w.build())
        if v >= 3:
            r.i32()  # throttle_time_ms
        brokers = {}
        for _ in range(r.i32()):
            node, host, port = r.i32(), r.string(), r.i32()
            r.string()  # rack
            brokers[node] = (host, port)
        if v >= 2:
            r.string()  # cluster_id
        r.i32()  # controller id
        topics_out = {}
        for _ in range(r.i32()):
            terr, name = r.i16(), r.string()
            r.i8()  # is_internal
            parts = {}
            for _ in range(r.i32()):
                perr, pid, leader = r.i16(), r.i32(), r.i32()
                if v >= 7:
                    r.i32()  # leader_epoch
                r.array(r.i32)  # replicas
                r.array(r.i32)  # isr
                if v >= 5:
                    r.array(r.i32)  # offline_replicas
                parts[pid] = {"leader": leader, "error": perr}
            topics_out[name] = {"error": terr, "partitions": parts}
        return {"brokers": brokers, "topics": topics_out}

    def list_offsets(self, topic: str, partitions: dict[int, int]) -> dict[int, int]:
        """partitions: {partition: timestamp(-1 latest / -2 earliest)} →
        {partition: offset}."""
        v = self._use[API_LIST_OFFSETS]
        w = Writer()
        w.i32(-1)  # replica_id
        if v >= 2:
            w.i8(0)  # isolation_level: read_uncommitted
        w.i32(1)   # one topic
        w.string(topic)
        w.i32(len(partitions))
        for p, ts in partitions.items():
            w.i32(p).i64(ts)
        r = self.request(API_LIST_OFFSETS, w.build())
        if v >= 2:
            r.i32()  # throttle_time_ms
        out = {}
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                pid, err = r.i32(), r.i16()
                r.i64()  # timestamp
                off = r.i64()
                if err:
                    raise KafkaError(err, f"ListOffsets {topic}[{pid}]")
                out[pid] = off
        return out

    def produce(self, topic: str, partition: int, batch: bytes,
                acks: int = 1, timeout_ms: int = 10_000) -> int:
        """Returns the base offset assigned to the batch."""
        v = self._use[API_PRODUCE]
        w = Writer()
        w.string(None)  # transactional_id
        w.i16(acks).i32(timeout_ms)
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.bytes_(batch)  # request encoding is identical across v3-v7
        r = self.request(API_PRODUCE, w.build())
        base = -1
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                pid, err, base = r.i32(), r.i16(), r.i64()
                r.i64()  # log_append_time (v2+)
                if v >= 5:
                    r.i64()  # log_start_offset
                if err:
                    raise KafkaError(err, f"Produce {topic}[{pid}]")
        return base

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20, max_wait_ms: int = 100,
              min_bytes: int = 1) -> tuple[int, bytes]:
        """(high_watermark, raw records blob)."""
        v = self._use[API_FETCH]
        w = Writer()
        w.i32(-1)                       # replica_id
        w.i32(max_wait_ms).i32(min_bytes).i32(max_bytes)
        w.i8(0)                         # isolation: read_uncommitted
        if v >= 7:
            # sessionless full fetch: no incremental-session state to
            # carry for a single-partition request
            w.i32(0).i32(-1)            # session_id, session_epoch
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        if v >= 9:
            w.i32(-1)                   # current_leader_epoch: unknown
        w.i64(offset)
        if v >= 5:
            w.i64(-1)                   # log_start_offset (consumer: -1)
        w.i32(max_bytes)
        if v >= 7:
            w.i32(0)                    # forgotten_topics_data: none
        if v >= 11:
            w.string("")                # rack_id
        r = self.request(API_FETCH, w.build())
        r.i32()  # throttle
        if v >= 7:
            err = r.i16()               # session-level error
            r.i32()                     # session_id
            if err:
                raise KafkaError(err, f"Fetch {topic} (session)")
        hw, blob = 0, b""
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                pid, err = r.i32(), r.i16()
                hw = r.i64()
                r.i64()       # last_stable_offset
                if v >= 5:
                    r.i64()   # log_start_offset
                r.array(lambda: (r.i64(), r.i64()))  # aborted txns
                if v >= 11:
                    r.i32()   # preferred_read_replica (KIP-392)
                blob = r.bytes_() or b""
                if err:
                    raise KafkaError(err, f"Fetch {topic}[{pid}]")
        return hw, blob

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _parse_bootstrap(bootstrap: str) -> list[tuple[str, int]]:
    out = []
    for hp in bootstrap.split(","):
        hp = hp.strip()
        if not hp:
            continue
        host, sep, port = hp.rpartition(":")
        if sep and port.isdigit():
            out.append((host or "localhost", int(port)))
        else:
            out.append((hp, 9092))  # bare hostname: Kafka default port
    return out


class KafkaClient:
    """Cluster-aware client: leader routing + one metadata-refresh retry."""

    def __init__(self, bootstrap: str, client_id: str = "heatmap-tpu",
                 timeout_s: float = 10.0):
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._bootstrap = _parse_bootstrap(bootstrap)
        self._conns: dict[tuple[str, int], BrokerClient] = {}
        self._leaders: dict[tuple[str, int], tuple[str, int]] = {}
        self._bootstrap_conn()  # fail fast when nothing is reachable

    def _connect(self, host: str, port: int) -> BrokerClient:
        key = (host, port)
        c = self._conns.get(key)
        if c is None or c._dead:
            c = BrokerClient(host, port, self.client_id, self.timeout_s)
            self._conns[key] = c
        return c

    def _bootstrap_conn(self) -> BrokerClient:
        """A live connection to any bootstrap broker; reconnects after the
        previous one was poisoned (a transient socket error must not kill
        the client for good)."""
        last_err: Exception | None = None
        for host, port in self._bootstrap:
            try:
                return self._connect(host, port)
            except OSError as e:
                last_err = e
        raise ConnectionError(f"no bootstrap broker reachable: {last_err}")

    def refresh_metadata(self, topic: str) -> dict[int, tuple[str, int]]:
        md = self._bootstrap_conn().metadata([topic])
        t = md["topics"].get(topic)
        if t is None or t["error"] not in (0, 5):
            raise KafkaError(t["error"] if t else 3, f"Metadata {topic}")
        for pid, p in t["partitions"].items():
            if p["leader"] in md["brokers"]:
                self._leaders[(topic, pid)] = md["brokers"][p["leader"]]
        return {pid: self._leaders[(topic, pid)]
                for pid in t["partitions"]
                if (topic, pid) in self._leaders}

    def partitions(self, topic: str) -> list[int]:
        return sorted(self.refresh_metadata(topic))

    def _leader_conn(self, topic: str, partition: int) -> BrokerClient:
        key = (topic, partition)
        if key not in self._leaders:
            self.refresh_metadata(topic)
        if key not in self._leaders:
            raise KafkaError(5, f"no leader for {topic}[{partition}]")
        return self._connect(*self._leaders[key])

    def _with_retry(self, topic: str, partition: int, fn):
        try:
            return fn(self._leader_conn(topic, partition))
        except (KafkaError, ConnectionError, OSError) as e:
            if isinstance(e, KafkaError) and e.code not in _RETRIABLE:
                raise
            time.sleep(0.1)
            self.refresh_metadata(topic)
            return fn(self._leader_conn(topic, partition))

    # ---- public ops -------------------------------------------------------

    def produce(self, topic: str, partition: int,
                records: list[rec.Record], acks: int = 1) -> int:
        batch = rec.encode_batch(records)
        return self._with_retry(
            topic, partition, lambda c: c.produce(topic, partition, batch,
                                                  acks=acks))

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20,
              max_wait_ms: int = 100) -> "FetchResult":
        hw, blob = self._with_retry(
            topic, partition,
            lambda c: c.fetch(topic, partition, offset, max_bytes,
                              max_wait_ms))
        records, next_off, skipped = rec.decode_batches_tolerant(blob, offset)
        records = [r for r in records if r.offset >= offset]
        return FetchResult(hw, records, max(next_off, offset), skipped)

    def fetch_values(self, topic: str, partition: int, offset: int,
                     max_bytes: int = 1 << 20, max_wait_ms: int = 100,
                     framing: str = "newline"):
        """Fetch + decode straight to a joined values blob via the C++
        batch decoder (native.kafka_decode_values) — the consumer hot
        path, skipping per-record Python entirely.  ``framing``:
        "newline" for JSON values, "lp" (u32 length prefixes) for binary
        event values.  Returns (high_watermark, KafkaValues) or, when the
        native path can't take this blob (no toolchain, malformed varints,
        newline-bearing values under newline framing), (high_watermark,
        FetchResult) from the Python decoder."""
        from heatmap_tpu.native import kafka_decode_values

        hw, blob = self._with_retry(
            topic, partition,
            lambda c: c.fetch(topic, partition, offset, max_bytes,
                              max_wait_ms))
        kv = kafka_decode_values(blob, offset, framing=framing)
        if kv is not None:
            kv.next_offset = max(kv.next_offset, offset)
            return hw, kv
        records, next_off, skipped = rec.decode_batches_tolerant(blob, offset)
        records = [r for r in records if r.offset >= offset]
        return hw, FetchResult(hw, records, max(next_off, offset), skipped)

    def list_offsets(self, topic: str, timestamp: int = LATEST) -> dict[int, int]:
        parts = self.partitions(topic)
        out: dict[int, int] = {}
        by_leader: dict[tuple[str, int], list[int]] = {}
        for p in parts:
            by_leader.setdefault(self._leaders[(topic, p)], []).append(p)
        for leader, pids in by_leader.items():
            c = self._connect(*leader)
            out.update(c.list_offsets(topic, {p: timestamp for p in pids}))
        return out

    def close(self) -> None:
        for c in self._conns.values():
            c.close()
        self._conns.clear()
