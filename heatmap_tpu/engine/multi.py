"""MultiAggregator — every (resolution, window) pair fused into ONE program.

The hex-pyramid and multi-window configs (BASELINE configs #4/#5) need
3+ concurrent aggregations of the *same* micro-batch.  Driving one
SingleAggregator per pair costs, per batch, P separate dispatches and P
separate device->host emit pulls — and re-snaps the batch once per window
length even though the snap only depends on the resolution.

This class fuses all pairs into a single jitted step:

  * the H3 snap runs once per **unique resolution** (a 3-window config
    snaps once, not three times);
  * each pair's ``merge_batch`` fold runs inside the same XLA program, so
    the per-step dispatch overhead (ruinous on remote-attached chips) is
    paid once;
  * the per-pair packed emits are stacked into one (P, E+1, 13) matrix —
    the whole batch's output crosses the device->host link in ONE pull.

Host API mirrors SingleAggregator per pair via :class:`PairView` (the
stream runtime checkpoints each (res, window) state independently;
reference parity: heatmap_stream.py:112-133 run once per configuration).
"""

from __future__ import annotations

import time
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from heatmap_tpu.engine.state import (TileState, donate_state_argnums,
                                      init_state)
from heatmap_tpu.engine.step import (
    AggParams,
    merge_batch,
    pack_emit,
    read_stats_rider,
    ride_stats,
    snap_and_window,
    window_start,
)


def fused_fold(params_list, states, lat_rad, lng_rad, speed, ts, valid,
               cutoff, prekeys=None):
    """THE per-batch multi-pair fold (trace-time): one H3 snap per unique
    resolution shared across its windows, then each pair's merge_batch on
    its own state slab.  Shared by MultiAggregator's jitted step and by
    bench.py's scanned chunks, so the benchmark always measures exactly
    the production fusion.  Returns (new_states, [(emit, stats)] in pair
    order).

    ``prekeys``: optional dict res -> (hi, lo) of PRE-COMPUTED cell keys
    (the host C++ snap, hexgrid.native_snap) — the fold then runs no
    in-program snap for those resolutions, only the valid-mask.  This is
    how HEATMAP_H3_IMPL=native integrates: snapping stays host-side
    (callbacks inside jit proved deadlock-prone on the CPU runtime), and
    the masking below keeps the invalid-row contract identical to
    snap_and_window's."""
    from heatmap_tpu.engine.state import EMPTY_KEY_HI, EMPTY_KEY_LO

    lat_deg = lat_rad * jnp.float32(180.0 / np.pi)
    lon_deg = lng_rad * jnp.float32(180.0 / np.pi)
    by_res: dict[int, tuple] = {}
    for p in params_list:
        if p.res not in by_res:
            if prekeys is not None and p.res in prekeys:
                hi, lo = prekeys[p.res]
                hi = jnp.where(valid, hi, jnp.uint32(EMPTY_KEY_HI))
                lo = jnp.where(valid, lo, jnp.uint32(EMPTY_KEY_LO))
            else:
                hi, lo, _ = snap_and_window(lat_rad, lng_rad, ts, valid, p)
            by_res[p.res] = (hi, lo)
    new_states, folded = [], []
    for p, st in zip(params_list, states):
        hi, lo = by_res[p.res]
        ws = window_start(ts, valid, p.window_s)
        st2, emit, stats = merge_batch(
            st, hi, lo, ws, speed, lat_deg, lon_deg, ts, valid, cutoff, p)
        new_states.append(st2)
        folded.append((emit, stats))
    return tuple(new_states), folded


class MultiAggregator:
    """Fused aggregation over P (resolution, window_s) pairs, one device.

    All pairs share capacity / hist_bins / emit capacity so states and
    emits stack along a leading pair axis.

    ``device``: optional explicit jax device this aggregator's state and
    feeds are committed to.  The partitioned mesh fast path
    (parallel.sharded.PartitionedAggregator) runs one MultiAggregator
    per mesh device this way — jit follows the committed inputs, so each
    shard's program executes on its own chip with no collectives and no
    shared dispatch stream.  ``None`` (the default) keeps the historical
    default-device behavior.
    """

    n_shards = 1

    def __init__(
        self,
        pairs: Sequence[tuple[int, int]],   # (res, window_s), unique
        capacity: int,
        batch_size: int,
        emit_capacity: int,
        hist_bins: int = 0,
        speed_hist_max: float = 256.0,
        device=None,
    ):
        if len(set(pairs)) != len(pairs):
            raise ValueError(f"duplicate (res, window) pairs: {pairs}")
        self.pairs = list(pairs)
        self.capacity_per_shard = capacity
        self.batch_size = batch_size
        self.device = device
        self.params = [
            AggParams(res=r, window_s=w, emit_capacity=emit_capacity,
                      speed_hist_max=speed_hist_max)
            for r, w in self.pairs
        ]
        self.states: list[TileState] = [
            TileState(*[self._put(leaf)
                        for leaf in init_state(capacity, hist_bins)])
            for _ in self.pairs
        ]
        # host wall spent in step dispatch, per local shard (one entry
        # here: the fused single-device program).  The dispatch is async,
        # so this clocks trace+enqueue, not device execution — the
        # runtime's "pull" span is where a slow device shows up; a
        # growing dispatch clock means retraces or host-side stalls.
        # Read by stream.runtime's callback gauges at /metrics scrapes.
        self.device_seconds = [0.0]
        self.n_steps = 0

        param_list = self.params

        def _step(states, lat, lng, speed, ts, valid, cutoff):
            new_states, folded = fused_fold(param_list, states, lat, lng,
                                            speed, ts, valid, cutoff)
            # ride the step stats in the packed head row, so the host
            # needs NO second transfer for them (see stats_from_packed)
            packs = [ride_stats(pack_emit(emit, p.speed_hist_max), stats)
                     for p, (emit, stats) in zip(param_list, folded)]
            return new_states, jnp.stack(packs)

        self._step = jax.jit(_step,
                     donate_argnums=donate_state_argnums())

        uniq_res = list(dict.fromkeys(p.res for p in param_list))
        self._uniq_res = uniq_res

        def _step_pre(states, keys, lat, lng, speed, ts, valid, cutoff):
            prekeys = {r: keys[i] for i, r in enumerate(uniq_res)}
            new_states, folded = fused_fold(param_list, states, lat, lng,
                                            speed, ts, valid, cutoff,
                                            prekeys=prekeys)
            packs = [ride_stats(pack_emit(emit, p.speed_hist_max), stats)
                     for p, (emit, stats) in zip(param_list, folded)]
            return new_states, jnp.stack(packs)

        self._step_pre = jax.jit(
            _step_pre, donate_argnums=donate_state_argnums())

    def _put(self, x):
        """Commit ``x`` to this aggregator's device (a no-op asarray on
        the default-device path, and a no-op device_put for arrays
        already committed there)."""
        if self.device is not None:
            return jax.device_put(x, self.device)
        return jnp.asarray(x)

    def instrument(self, wrap) -> None:
        """Wrap the jitted entry points with a compile tracker
        (obs.runtimeinfo.CompileTracker.wrap): per-function compile
        counts / compile seconds / retrace-after-warmup detection.
        Idempotent enough for one runtime: call once, right after
        construction and before the first step."""
        self._step = wrap("multi_step", self._step)
        self._step_pre = wrap("multi_step_pre", self._step_pre)

    def step_packed_all(self, lat_rad, lng_rad, speed, ts, valid,
                        watermark_cutoff, prekeys=None):
        """Fold one batch into every pair's state.

        Returns the packed emits on device: (P, E+1, 13) uint32 — one
        ``unpack_emit`` row block per pair in ``self.pairs`` order, with
        that pair's step stats ridden in head-row slots 2..7
        (``stats_from_packed``).

        ``prekeys``: optional dict res -> (hi, lo) numpy arrays of
        host-computed cell keys.  Unlike fused_fold's per-res optional
        contract, THIS method requires keys for EVERY unique resolution
        when prekeys is given (a partial dict raises) — the pre-jitted
        _step_pre signature takes the full key tuple.
        """
        t0 = time.monotonic()
        if prekeys is not None:
            missing = [r for r in self._uniq_res if r not in prekeys]
            if missing:
                raise ValueError(f"prekeys missing resolutions {missing}")
            keys = tuple(
                (self._put(prekeys[r][0]), self._put(prekeys[r][1]))
                for r in self._uniq_res)
            states, packed = self._step_pre(
                tuple(self.states), keys,
                self._put(lat_rad), self._put(lng_rad),
                self._put(speed), self._put(ts), self._put(valid),
                jnp.int32(watermark_cutoff),
            )
        else:
            states, packed = self._step(
                tuple(self.states),
                self._put(lat_rad), self._put(lng_rad),
                self._put(speed), self._put(ts), self._put(valid),
                jnp.int32(watermark_cutoff),
            )
        self.states = list(states)
        self.device_seconds[0] += time.monotonic() - t0
        self.n_steps += 1
        return packed

    def view(self, res: int, window_s: int) -> "PairView":
        return PairView(self, self.pairs.index((res, window_s)))

    def grow(self, new_capacity: int) -> None:
        """Resize EVERY pair's slab (pairs share one capacity so the fused
        step keeps uniform shapes).  The next step retraces on the new
        shape; sortedness is preserved (EMPTY pads the tail).  Emit
        capacity grows with the slab (a larger slab means a batch can
        touch more groups than the old min(batch, cap) bound) — the
        in-place params update is read at that retrace."""
        from heatmap_tpu.engine.state import resize_state

        self.states = [
            TileState(*[self._put(leaf)
                        for leaf in resize_state(st, new_capacity)])
            for st in self.states
        ]
        self.capacity_per_shard = new_capacity
        new_emit = min(self.batch_size, new_capacity)
        self.params[:] = [
            p._replace(emit_capacity=max(p.emit_capacity, new_emit))
            for p in self.params
        ]


class PairView:
    """Checkpoint adapter for one pair of a MultiAggregator (SingleAggregator
    snapshot/restore API)."""

    n_shards = 1

    def __init__(self, multi: MultiAggregator, idx: int):
        self._multi = multi
        self._idx = idx

    @property
    def capacity_per_shard(self) -> int:  # tracks growth
        return self._multi.capacity_per_shard

    @property
    def state(self) -> TileState:
        return self._multi.states[self._idx]

    def snapshot(self) -> TileState:
        from heatmap_tpu.engine.state import to_host

        return to_host(self._multi.states[self._idx])

    def device_snapshot(self) -> TileState:
        """Fresh-buffer on-device copy (see SingleAggregator)."""
        from heatmap_tpu.engine.state import device_copy

        return device_copy(self._multi.states[self._idx])

    @staticmethod
    def to_host(snap: TileState) -> TileState:
        from heatmap_tpu.engine.state import to_host

        return to_host(snap)

    def restore(self, st: TileState) -> None:
        cur = self._multi.states[self._idx]
        want = (cur.key_hi.shape, cur.hist.shape)
        got = (st.key_hi.shape, st.hist.shape)
        if want != got:
            raise ValueError(f"state shape {got} != configured {want}")
        self._multi.states[self._idx] = TileState(
            *[self._multi._put(leaf) for leaf in st])


class MultiStats(NamedTuple):
    """Host-side StepStats (field order MUST match engine.step.StepStats —
    the rider is decoded positionally, see step.ride_stats)."""

    n_valid: int
    n_late: int
    n_evicted: int
    n_active: int
    state_overflow: int
    batch_max_ts: int


def stats_from_packed(packed_pair: np.ndarray) -> MultiStats:
    """Decode the StepStats ridden in a pair's packed head row (written by
    MultiAggregator's step; avoids a separate stats transfer)."""
    return read_stats_rider(packed_pair, MultiStats)
