"""engine — on-device windowed tile aggregation.

The TPU-native replacement for the reference's Spark shuffle aggregation
(reference: heatmap_stream.py:112-133 ``groupBy(window(eventTs), cellId)``
with count/avg aggregates, watermark at :107).  Instead of a hash-partitioned
shuffle across JVM executors, the engine keeps a *compact, key-sorted state
slab* in device memory and folds each fixed-shape micro-batch in with a
single lexicographic sort + segment scatter — shapes are static, control flow
is compiler-friendly, and the whole step is one fused XLA program.

See ``state`` for the state layout and ``step`` for the batch fold.
"""

from heatmap_tpu.engine.state import TileState, init_state, EMPTY_KEY_HI  # noqa: F401
from heatmap_tpu.engine.step import (  # noqa: F401
    AggParams,
    BatchEmit,
    StepStats,
    aggregate_batch,
    merge_batch,
    snap_and_window,
)
