"""The per-micro-batch aggregation fold (device hot path).

Replaces one Spark micro-batch's parse → H3-UDF → shuffle → stateful-agg
chain (reference: heatmap_stream.py:88-133 and call stack SURVEY.md §3.3)
with a single jitted XLA program:

  1. ``snap_and_window`` — vectorized H3 snap (hexgrid.device) + tumbling
     window-start computation; invalid/late rows get the EMPTY key (the
     moral equivalent of the reference's null/bounds filters,
     heatmap_stream.py:96-108, and its 10-minute watermark drop, :107).
  2. ``merge_batch`` — merge-sort the batch into the compact sorted state
     slab: one ``lax.sort`` over (state ∥ batch) keys, segment-id
     derivation, then masked scatters to rebuild the slab.  Watermark
     eviction of closed windows is folded into the same sort (evicted rows
     are relabeled EMPTY so they sink to the tail and their slots recycle).

Everything is static-shape; the only dynamic quantities (number of distinct
keys, number of touched groups) are carried as masks and counters.

Degradation semantics: if the number of distinct live groups ever exceeds the
slab capacity, the groups with the highest composite keys are dropped —
including, possibly, pre-existing rows whose aggregates are then lost (their
next re-emit restarts the count).  ``StepStats.state_overflow`` counts the
dropped segments; the stream runtime treats any nonzero value as a loud
misconfiguration error (capacity must be sized for the active-cell
cardinality, SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from heatmap_tpu.engine.state import (
    EMPTY_KEY_HI,
    EMPTY_KEY_LO,
    EMPTY_WS,
    TileState,
)
from heatmap_tpu.hexgrid import device as hexdev

I32_MIN = jnp.int32(-(2**31))


class AggParams(NamedTuple):
    """Static parameters of one (resolution, window) aggregation."""

    res: int                 # H3 resolution (heatmap_stream.py:26)
    window_s: int            # tumbling window seconds (heatmap_stream.py:29)
    emit_capacity: int       # max groups emitted per batch (update mode)
    speed_hist_max: float = 256.0   # km/h mapped onto the last hist bin


class BatchEmit(NamedTuple):
    """Update-mode output: current aggregates of every group touched by this
    batch (the reference's outputMode("update") contract,
    heatmap_stream.py:241-247).  Fixed capacity; ``valid`` marks live rows."""

    key_hi: jnp.ndarray
    key_lo: jnp.ndarray
    key_ws: jnp.ndarray
    count: jnp.ndarray
    sum_speed: jnp.ndarray
    sum_speed2: jnp.ndarray
    sum_lat: jnp.ndarray
    sum_lon: jnp.ndarray
    hist: jnp.ndarray
    valid: jnp.ndarray       # bool
    n_emitted: jnp.ndarray   # int32 scalar — true touched-group count
    overflowed: jnp.ndarray  # bool scalar — touched groups > emit capacity


class StepStats(NamedTuple):
    n_valid: jnp.ndarray       # events aggregated
    n_late: jnp.ndarray        # events dropped by the watermark
    n_evicted: jnp.ndarray     # state rows recycled (closed windows)
    n_active: jnp.ndarray      # live groups after the merge
    state_overflow: jnp.ndarray  # distinct keys beyond capacity (dropped)
    batch_max_ts: jnp.ndarray  # int32 — max valid event ts (watermark input)


def snap_and_window(lat_rad, lng_rad, ts_s, valid, params: AggParams):
    """Compute (key_hi, key_lo, window_start) per event; invalid → EMPTY."""
    hi, lo = hexdev.latlng_to_cell_vec(lat_rad, lng_rad, params.res)
    ws = (ts_s // params.window_s) * params.window_s
    hi = jnp.where(valid, hi, EMPTY_KEY_HI)
    lo = jnp.where(valid, lo, EMPTY_KEY_LO)
    ws = jnp.where(valid, ws, EMPTY_WS)
    return hi, lo, ws


@functools.partial(jax.jit, static_argnames=("params",))
def merge_batch(
    state: TileState,
    ev_hi,
    ev_lo,
    ev_ws,
    ev_speed,
    ev_lat_deg,
    ev_lon_deg,
    ev_ts,
    ev_valid,
    watermark_cutoff,          # int32 scalar: evict windows ending before this
    params: AggParams,
):
    """Fold one batch into the state. Returns (state, BatchEmit, StepStats)."""
    C = state.capacity
    N = ev_hi.shape[0]
    B = state.hist_bins

    # --- late-event drop + window eviction (watermark semantics) ---------
    # an event is late when its window closed: ws + window <= cutoff
    late = ev_valid & (ev_ws + params.window_s <= watermark_cutoff)
    ev_valid = ev_valid & ~late
    ev_hi = jnp.where(ev_valid, ev_hi, EMPTY_KEY_HI)
    ev_lo = jnp.where(ev_valid, ev_lo, EMPTY_KEY_LO)
    ev_ws = jnp.where(ev_valid, ev_ws, EMPTY_WS)

    live = state.key_hi != EMPTY_KEY_HI
    evict = live & (state.key_ws + params.window_s <= watermark_cutoff)
    keep = live & ~evict
    st_hi = jnp.where(keep, state.key_hi, EMPTY_KEY_HI)
    st_lo = jnp.where(keep, state.key_lo, EMPTY_KEY_LO)
    st_ws = jnp.where(keep, state.key_ws, EMPTY_WS)

    # --- merge-sort state ∥ batch by (hi, lo, ws); carry origin row ------
    all_hi = jnp.concatenate([st_hi, ev_hi])
    all_lo = jnp.concatenate([st_lo, ev_lo])
    all_ws = jnp.concatenate([st_ws, ev_ws])
    orig = jnp.arange(C + N, dtype=jnp.int32)  # <C: state row, >=C: batch row
    s_hi, s_lo, s_ws, s_orig = jax.lax.sort(
        (all_hi, all_lo, all_ws, orig), num_keys=3
    )

    nonempty = s_hi != EMPTY_KEY_HI
    changed = (
        (s_hi != jnp.roll(s_hi, 1))
        | (s_lo != jnp.roll(s_lo, 1))
        | (s_ws != jnp.roll(s_ws, 1))
    )
    is_start = changed.at[0].set(True)
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1  # sorted-order segment id

    # --- per-origin-row new segment (the scatter routing tables) ---------
    # state row r (kept) lands in segment state_seg[r]; batch row i in batch_seg[i]
    st_idx = jnp.where(s_orig < C, s_orig, C)
    state_seg = jnp.full((C,), C, jnp.int32).at[st_idx].set(seg, mode="drop")
    bt_idx = jnp.where(s_orig >= C, s_orig - C, N)
    batch_seg = jnp.full((N,), C, jnp.int32).at[bt_idx].set(seg, mode="drop")
    # route empties/evictions/lates to the drop bin
    state_seg = jnp.where(keep, state_seg, C)
    batch_seg = jnp.where(ev_valid, batch_seg, C)

    # --- rebuild the slab ------------------------------------------------
    def scat(init, idx, vals):
        return init.at[idx].add(vals, mode="drop")

    key_hi = jnp.full((C,), EMPTY_KEY_HI, jnp.uint32).at[seg].set(s_hi, mode="drop")
    key_lo = jnp.full((C,), EMPTY_KEY_LO, jnp.uint32).at[seg].set(s_lo, mode="drop")
    key_ws = jnp.full((C,), EMPTY_WS, jnp.int32).at[seg].set(s_ws, mode="drop")
    # rows of the EMPTY segment must stay sentinel even though scatters above
    # wrote EMPTY there anyway; values below only ever add masked amounts.

    zc = jnp.zeros((C,), jnp.int32)
    zf = jnp.zeros((C,), jnp.float32)
    one = ev_valid.astype(jnp.int32)
    count = scat(scat(zc, state_seg, jnp.where(keep, state.count, 0)), batch_seg, one)
    fmask = ev_valid.astype(jnp.float32)
    kf = keep.astype(jnp.float32)
    sum_speed = scat(scat(zf, state_seg, state.sum_speed * kf), batch_seg, ev_speed * fmask)
    sum_speed2 = scat(
        scat(zf, state_seg, state.sum_speed2 * kf), batch_seg, ev_speed * ev_speed * fmask
    )
    sum_lat = scat(scat(zf, state_seg, state.sum_lat * kf), batch_seg, ev_lat_deg * fmask)
    sum_lon = scat(scat(zf, state_seg, state.sum_lon * kf), batch_seg, ev_lon_deg * fmask)

    if B > 0:
        bin_w = params.speed_hist_max / B
        ev_bin = jnp.clip((ev_speed / bin_w).astype(jnp.int32), 0, B - 1)
        hist = jnp.zeros((C, B), jnp.int32)
        hist = hist.at[state_seg].add(
            state.hist * keep[:, None].astype(jnp.int32), mode="drop"
        )
        hist = hist.at[batch_seg, ev_bin].add(one, mode="drop")
    else:
        hist = state.hist

    new_state = TileState(
        key_hi=key_hi, key_lo=key_lo, key_ws=key_ws, count=count,
        sum_speed=sum_speed, sum_speed2=sum_speed2,
        sum_lat=sum_lat, sum_lon=sum_lon, hist=hist,
    )

    # --- update-mode emit: groups touched by this batch -------------------
    E = params.emit_capacity
    touched = jnp.zeros((C,), bool).at[batch_seg].set(True, mode="drop")
    n_emitted = jnp.sum(touched.astype(jnp.int32))
    emit_idx = jnp.nonzero(touched, size=E, fill_value=C)[0]
    emit_ok = emit_idx < C
    gi = jnp.where(emit_ok, emit_idx, 0)
    emit = BatchEmit(
        key_hi=jnp.where(emit_ok, key_hi[gi], EMPTY_KEY_HI),
        key_lo=jnp.where(emit_ok, key_lo[gi], EMPTY_KEY_LO),
        key_ws=jnp.where(emit_ok, key_ws[gi], EMPTY_WS),
        count=jnp.where(emit_ok, count[gi], 0),
        sum_speed=jnp.where(emit_ok, sum_speed[gi], 0.0),
        sum_speed2=jnp.where(emit_ok, sum_speed2[gi], 0.0),
        sum_lat=jnp.where(emit_ok, sum_lat[gi], 0.0),
        sum_lon=jnp.where(emit_ok, sum_lon[gi], 0.0),
        hist=hist[gi] * emit_ok[:, None].astype(jnp.int32) if B > 0
        else jnp.zeros((E, 0), jnp.int32),
        valid=emit_ok,
        n_emitted=n_emitted,
        overflowed=n_emitted > E,
    )

    # --- stats ------------------------------------------------------------
    n_seg_total = seg[-1] + 1  # includes the single EMPTY segment if present
    has_empty = ~nonempty[-1]  # empties (if any) sort last
    n_distinct = n_seg_total - has_empty.astype(jnp.int32)
    stats = StepStats(
        n_valid=jnp.sum(one),
        n_late=jnp.sum(late.astype(jnp.int32)),
        n_evicted=jnp.sum(evict.astype(jnp.int32)),
        n_active=jnp.sum((key_hi != EMPTY_KEY_HI).astype(jnp.int32)),
        state_overflow=jnp.maximum(n_distinct - C, 0),
        batch_max_ts=jnp.max(jnp.where(ev_valid, ev_ts, I32_MIN)),
    )
    return new_state, emit, stats


def aggregate_batch(
    state: TileState,
    lat_rad,
    lng_rad,
    speed_kmh,
    ts_s,
    valid,
    watermark_cutoff,
    params: AggParams,
):
    """Convenience: snap + window + merge in one call (used by stream/)."""
    hi, lo, ws = snap_and_window(lat_rad, lng_rad, ts_s, valid, params)
    lat_deg = lat_rad * (180.0 / jnp.pi)
    lon_deg = lng_rad * (180.0 / jnp.pi)
    return merge_batch(
        state, hi, lo, ws, speed_kmh, lat_deg, lon_deg, ts_s, valid,
        watermark_cutoff, params,
    )
