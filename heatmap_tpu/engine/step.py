"""The per-micro-batch aggregation fold (device hot path).

Replaces one Spark micro-batch's parse → H3-UDF → shuffle → stateful-agg
chain (reference: heatmap_stream.py:88-133 and call stack SURVEY.md §3.3)
with a single jitted XLA program:

  1. ``snap_and_window`` — vectorized H3 snap (hexgrid.device) + tumbling
     window-start computation; invalid/late rows get the EMPTY key (the
     moral equivalent of the reference's null/bounds filters,
     heatmap_stream.py:96-108, and its 10-minute watermark drop, :107).
  2. ``merge_batch`` — merge-sort the batch into the compact sorted state
     slab: one ``lax.sort`` over (state ∥ batch) keys, segment-id
     derivation, then masked scatters to rebuild the slab.  Watermark
     eviction of closed windows is folded into the same sort (evicted rows
     are relabeled EMPTY so they sink to the tail and their slots recycle).

Everything is static-shape; the only dynamic quantities (number of distinct
keys, number of touched groups) are carried as masks and counters.

Degradation semantics: if the number of distinct live groups ever exceeds the
slab capacity, the groups with the highest composite keys are dropped —
including, possibly, pre-existing rows whose aggregates are then lost (their
next re-emit restarts the count).  ``StepStats.state_overflow`` counts the
dropped segments; the stream runtime surfaces any nonzero value as
per-batch ``state_overflow_groups`` / ``state_overflow_last_epoch``
counters at /metrics plus a rate-limited ERROR log, and with
``HEATMAP_ON_OVERFLOW=fail`` stops the run (capacity must be sized for
the active-cell cardinality, SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
import os
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp

from heatmap_tpu.engine.state import (
    EMPTY_KEY_HI,
    EMPTY_KEY_LO,
    EMPTY_WS,
    TileState,
)
from heatmap_tpu.hexgrid import device as hexdev

I32_MIN = jnp.int32(-(2**31))

# Events this many windows ahead of an active watermark are dropped as
# clock-skew poison (and keep the live span well inside the 4096-window
# sort-key compression, see merge_batch).
FUTURE_WINDOWS = 2048

# Merge-fold routing (sort|rank|probe|auto).  ``MERGE_IMPL`` is the
# process-wide OVERRIDE slot (bench sweeps and tests assign it); when it
# is None — the normal state — HEATMAP_MERGE_IMPL is read at TRACE time
# by _resolve_merge_impl(), so a library user who sets the env var after
# importing this module is honored rather than silently served the
# import-time snapshot (round-3 advisor footgun).  All impls are
# bit-identical by construction and differential test, so programs
# traced before and after an env change still agree on results.
MERGE_IMPL: "str | None" = None


def _resolve_merge_impl() -> str:
    return (MERGE_IMPL if MERGE_IMPL is not None
            else os.environ.get("HEATMAP_MERGE_IMPL", "auto"))


# In-program snap routing (xla|pallas|auto) — same override-slot pattern
# as MERGE_IMPL: ``SNAP_IMPL`` wins when set (the stream runtime assigns
# it to pin the checkpointed impl across a resume — unlike the merge
# impls, the two snaps are NOT bit-identical on f32 cell-edge points, so
# a mid-stream flip would re-key a handful of groups); otherwise
# HEATMAP_H3_IMPL is read at trace time.
SNAP_IMPL: "str | None" = None

# Frozen bank verdict for the merge-impl ``auto`` path.  Sentinel
# ``_BANK_LIVE`` (the import-time default) means "consult
# hwbank.merge_winner() at trace time" — right for standalone
# merge_batch users (bench, tests, notebooks).  The stream runtime
# REPLACES it at init with a one-shot snapshot (a winner name or None),
# because (a) re-reading the bank at every trace would let a bank file
# rewritten MID-RUN — hw_burst --loop is the documented companion —
# flip the impl after the multihost startup collective validated a
# snapshot, compiling divergent lockstep programs across hosts, and
# (b) the getmtime stat has no place on the per-batch hot path.  The
# collective demotes the snapshot to None when hosts' banks disagree
# (every host then shares the static capacity-ratio rule; the merge
# impls are bit-identical, so results never depend on the choice).
_BANK_LIVE = object()
MERGE_BANK_PIN: "str | None | object" = _BANK_LIVE


def _resolve_snap_impl() -> str:
    return (SNAP_IMPL if SNAP_IMPL is not None
            else os.environ.get("HEATMAP_H3_IMPL", "auto"))


def resolve_snap_policy(ignore_pin: bool = False) -> str:
    """The in-program snap POLICY ("pallas" | "xla"): explicit
    env/override wins; "auto" consults the hardware bank.  Per-res
    eligibility (res <= 10, kernel lowers) still applies at trace time,
    so a policy of "pallas" deterministically degrades to the XLA snap
    for ineligible resolutions — recording the policy is enough to
    reproduce the exact per-res kernel choice across a resume.
    The stream runtime FREEZES this in ``SNAP_IMPL`` at init so a bank
    file appearing/changing mid-run cannot flip the kernel at a
    growth retrace or float the checkpointed name.  ``ignore_pin``
    resolves from env+bank even when the slot is set (the runtime uses
    it to detect a conflicting pin left by another runtime in the
    process — comparing against the slot-reading resolution would
    always agree with itself)."""
    impl = (os.environ.get("HEATMAP_H3_IMPL", "auto") if ignore_pin
            else _resolve_snap_impl())
    if impl == "auto":
        from heatmap_tpu import hwbank

        impl = hwbank.snap_winner() or "xla"
    # "native" is handled upstream via host prekeys; any other value
    # (incl. typos) keeps the safe default
    return impl if impl == "pallas" else "xla"


def inprogram_snap_name(res: int = 8) -> str:
    """The in-program snap ``_snap_impl`` would hand back right now,
    as a checkpointable name ("pallas" | "xla")."""
    if resolve_snap_policy() == "pallas" and res <= 10:
        from heatmap_tpu.hexgrid import pallas_kernel

        if pallas_kernel.pallas_available():
            return "pallas"
    return "xla"

# _merge_probe tunables (resolved once at import — they only shape the
# probe impl's internal loop, not results, and tests patch the module
# constants directly): probe rounds before the per-batch sort fallback,
# and the unique-key budget divisor (budget = batch/PROBE_UNIQ_DIV,
# floor 256).
PROBE_ROUNDS = int(os.environ.get("HEATMAP_PROBE_ROUNDS", "16"))
PROBE_UNIQ_DIV = int(os.environ.get("HEATMAP_PROBE_UNIQ_DIV", "8"))

# Steady-state fast path (HEATMAP_FASTPATH=0 disables; module override
# slot for tests).  Read at trace time like the merge impl.
FASTPATH: "bool | None" = None


def _resolve_fastpath() -> bool:
    if FASTPATH is not None:
        return FASTPATH
    return os.environ.get("HEATMAP_FASTPATH", "1") != "0"


class AggParams(NamedTuple):
    """Static parameters of one (resolution, window) aggregation."""

    res: int                 # H3 resolution (heatmap_stream.py:26)
    window_s: int            # tumbling window seconds (heatmap_stream.py:29)
    emit_capacity: int       # max groups emitted per batch (update mode)
    speed_hist_max: float = 256.0   # km/h mapped onto the last hist bin


class BatchEmit(NamedTuple):
    """Update-mode output: current aggregates of every group touched by this
    batch (the reference's outputMode("update") contract,
    heatmap_stream.py:241-247).  Fixed capacity; ``valid`` marks live rows."""

    key_hi: jnp.ndarray
    key_lo: jnp.ndarray
    key_ws: jnp.ndarray
    count: jnp.ndarray
    sum_speed: jnp.ndarray   # residual sums about the anchor_* lanes
    sum_speed2: jnp.ndarray  # (engine.state.TileState docstring)
    sum_lat: jnp.ndarray
    sum_lon: jnp.ndarray
    anchor_speed: jnp.ndarray  # per-group anchors: consumers recombine
    anchor_lat: jnp.ndarray    # anchor + resid/count in f64 host-side
    anchor_lon: jnp.ndarray
    hist: jnp.ndarray
    valid: jnp.ndarray       # bool
    n_emitted: jnp.ndarray   # int32 scalar — true touched-group count
    overflowed: jnp.ndarray  # bool scalar — touched groups > emit capacity


class StepStats(NamedTuple):
    n_valid: jnp.ndarray       # events aggregated
    n_late: jnp.ndarray        # events dropped by the watermark
    n_evicted: jnp.ndarray     # state rows recycled (closed windows)
    n_active: jnp.ndarray      # live groups after the merge
    state_overflow: jnp.ndarray  # distinct keys beyond capacity (dropped)
    batch_max_ts: jnp.ndarray  # int32 — max valid event ts (watermark input)


def _snap_impl(res: int):
    """IN-PROGRAM H3 snap implementation: pure-XLA by default; the fused
    Pallas geometry kernel (hexgrid.pallas_kernel) via
    HEATMAP_H3_IMPL=pallas.  Falls back to XLA when the kernel doesn't
    apply (res > 10) or doesn't lower on the current backend.

    HEATMAP_H3_IMPL=native is NOT dispatched here: the C++ host snap
    (hexgrid.native_snap, ~11x faster per CPU core and f64-exact)
    integrates as host-computed ``prekeys`` fed into the fold
    (engine.multi.fused_fold; the stream runtime and bench do this) —
    a pure_callback inside the jitted program deadlocked intermittently
    on the CPU runtime, see hexgrid/native_snap.py."""
    # measured-winner default under "auto" (hwbank, HARDWARE.md): on the
    # v5e the Pallas kernel lowers and wins 2.6-3.1x vs the XLA snap in
    # same-unit A/Bs with >=99.78% cell agreement; without a banked A/B
    # for the live platform "auto" resolves to the XLA snap (CPU's
    # `auto` winner — the native host pre-snap — never reaches here: it
    # rides the prekeys path upstream)
    if inprogram_snap_name(res) == "pallas":
        from heatmap_tpu.hexgrid import pallas_kernel

        return pallas_kernel.latlng_to_cell_pallas
    return hexdev.latlng_to_cell_vec


def window_start(ts_s, valid, window_s: int):
    """Tumbling window start per event; invalid → EMPTY_WS.  The single
    definition of window assignment (engine.multi shares it)."""
    ws = (ts_s // window_s) * window_s
    return jnp.where(valid, ws, EMPTY_WS)


def snap_and_window(lat_rad, lng_rad, ts_s, valid, params: AggParams):
    """Compute (key_hi, key_lo, window_start) per event; invalid → EMPTY."""
    hi, lo = _snap_impl(params.res)(lat_rad, lng_rad, params.res)
    hi = jnp.where(valid, hi, EMPTY_KEY_HI)
    lo = jnp.where(valid, lo, EMPTY_KEY_LO)
    return hi, lo, window_start(ts_s, valid, params.window_s)


def _drop_and_evict(state, ev_hi, ev_lo, ev_ws, ev_valid, watermark_cutoff,
                    params: AggParams):
    """Shared prologue: late/future-event drop + window eviction masks.

    late: the window already closed (ws + window <= cutoff).  future:
    more than FUTURE_WINDOWS ahead of the watermark — a clock-skewed
    producer poison pill; dropping it also guarantees the live window
    span stays < 4096 windows, which the 12-bit window-index key
    compression relies on.  (With the watermark disabled the span bound
    is the caller's responsibility — bounded replays only.)
    """
    late = ev_valid & (ev_ws + params.window_s <= watermark_cutoff)
    if FUTURE_WINDOWS:
        has_wm = watermark_cutoff > jnp.int32(-(2**31))
        future = ev_valid & has_wm & (
            (ev_ws - watermark_cutoff) >= FUTURE_WINDOWS * params.window_s
        )
        late = late | future
    ev_valid = ev_valid & ~late
    ev_hi = jnp.where(ev_valid, ev_hi, EMPTY_KEY_HI)
    ev_lo = jnp.where(ev_valid, ev_lo, EMPTY_KEY_LO)
    ev_ws = jnp.where(ev_valid, ev_ws, EMPTY_WS)

    live = state.key_hi != EMPTY_KEY_HI
    evict = live & (state.key_ws + params.window_s <= watermark_cutoff)
    keep = live & ~evict
    st_hi = jnp.where(keep, state.key_hi, EMPTY_KEY_HI)
    st_lo = jnp.where(keep, state.key_lo, EMPTY_KEY_LO)
    st_ws = jnp.where(keep, state.key_ws, EMPTY_WS)
    return (late, ev_valid, ev_hi, ev_lo, ev_ws,
            evict, keep, st_hi, st_lo, st_ws)


def _compress_key(hi, ws, empty, params: AggParams):
    """96-bit composite key → u32 upper sort key (the low word is `lo`).

    With `res` static, hi's upper bits (mode/res) are constant and its
    variable part (base cell + coarse digits) fits 20 bits; the window
    start folds to a 12-bit window index (mod 4096).  Distinct live keys
    stay distinct while the active window span is < 4096 windows —
    guaranteed by any sane watermark (4096 x 5 min ≈ 14 days);
    k1 = 0xFFFFFFFF is unreachable for live rows (base cell <= 121) and
    marks empties."""
    wix = (ws // params.window_s).astype(jnp.uint32) & jnp.uint32(0xFFF)
    return jnp.where(
        empty,
        jnp.uint32(0xFFFFFFFF),
        (wix << 20) | (hi & jnp.uint32(0xFFFFF)),
    )


def merge_batch(
    state: TileState,
    ev_hi,
    ev_lo,
    ev_ws,
    ev_speed,
    ev_lat_deg,
    ev_lon_deg,
    ev_ts,
    ev_valid,
    watermark_cutoff,          # int32 scalar: evict windows ending before this
    params: AggParams,
    impl: str | None = None,
):
    """Fold one batch into the state. Returns (state, BatchEmit, StepStats).

    Two equivalent routing implementations (differential-tested against
    each other): the default full merge-sort over (state ∥ batch), or —
    with ``HEATMAP_MERGE_IMPL=rank`` — a batch-only sort merged into the
    already-sorted slab by rank (searchsorted), which does ~sort(N)
    instead of ~sort(C+N) work and wins when the slab dwarfs the batch
    (latency-oriented streaming configs).  ``auto`` (the default) picks
    by the measured crossover: rank when capacity >= 4x batch.  The
    round-5 warm-slab arg-passing A/B (the only valid methodology —
    closed-over batch arrays get constant-folded by XLA and an empty
    slab drops every state-side scatter, both of which silently flatter
    rank) confirms it on CPU: sort wins 2^18-batch shapes, rank wins
    2^14-batch streaming shapes by ~1.5x; on-chip crossover pending
    tools/hw_burst.py merge units.  The env var is
    read at trace time (module override slot ``MERGE_IMPL`` wins when
    set — bench sweeps and tests use it); pass ``impl`` explicitly to
    override per call."""
    if impl is None:
        impl = _resolve_merge_impl()
    if impl == "auto":
        # a banked on-chip crossover (tools/hw_burst.py merge units,
        # HARDWARE.md) outranks the static capacity-ratio rule: on the
        # v5e sort won ALL three shapes, including the streaming shape
        # the 4x rule would hand to rank (rank is the measured CPU
        # winner there, so the static rule stays as the fallback)
        if MERGE_BANK_PIN is _BANK_LIVE:
            from heatmap_tpu import hwbank

            banked = hwbank.merge_winner()
        else:
            banked = MERGE_BANK_PIN
        impl = (banked
                or ("rank" if state.capacity >= 4 * ev_hi.shape[0]
                    else "sort"))
    slow = {"rank": _merge_rank, "probe": _merge_probe,
            "sort": _merge_sort}[impl]
    if _resolve_fastpath():
        return _merge_fastpath(state, ev_hi, ev_lo, ev_ws, ev_speed,
                               ev_lat_deg, ev_lon_deg, ev_ts, ev_valid,
                               watermark_cutoff, params, impl)
    return slow(state, ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg,
                ev_lon_deg, ev_ts, ev_valid, watermark_cutoff, params)


@functools.partial(jax.jit, static_argnames=("params",))
def _merge_sort(
    state: TileState,
    ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg, ev_lon_deg, ev_ts, ev_valid,
    watermark_cutoff,
    params: AggParams,
):
    """Routing via one merge-sort of (state ∥ batch) compressed keys."""
    C = state.capacity
    N = ev_hi.shape[0]

    (late, ev_valid, ev_hi, ev_lo, ev_ws, evict, keep, st_hi, st_lo,
     st_ws) = _drop_and_evict(state, ev_hi, ev_lo, ev_ws, ev_valid,
                              watermark_cutoff, params)

    # --- merge-sort state ∥ batch; carry origin row -----------------------
    # Halving the sort operands (2 u32 keys instead of the 96-bit
    # composite) nearly halves the cost of the dominant op in this fold.
    all_hi = jnp.concatenate([st_hi, ev_hi])
    all_lo = jnp.concatenate([st_lo, ev_lo])
    all_ws = jnp.concatenate([st_ws, ev_ws])
    k1 = _compress_key(all_hi, all_ws, all_hi == EMPTY_KEY_HI, params)
    orig = jnp.arange(C + N, dtype=jnp.int32)  # <C: state row, >=C: batch row
    s_k1, s_k2, s_orig = jax.lax.sort((k1, all_lo, orig), num_keys=2)

    nonempty = s_k1 != jnp.uint32(0xFFFFFFFF)
    changed = (s_k1 != jnp.roll(s_k1, 1)) | (s_k2 != jnp.roll(s_k2, 1))
    is_start = changed.at[0].set(True)
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1  # sorted-order segment id

    # --- per-origin-row new segment (the scatter routing tables) ---------
    # state row r (kept) lands in segment state_seg[r]; batch row i in batch_seg[i]
    st_idx = jnp.where(s_orig < C, s_orig, C)
    state_seg = jnp.full((C,), C, jnp.int32).at[st_idx].set(seg, mode="drop")
    bt_idx = jnp.where(s_orig >= C, s_orig - C, N)
    batch_seg = jnp.full((N,), C, jnp.int32).at[bt_idx].set(seg, mode="drop")
    # route empties/evictions/lates to the drop bin
    state_seg = jnp.where(keep, state_seg, C)
    batch_seg = jnp.where(ev_valid, batch_seg, C)

    n_seg_total = seg[-1] + 1  # includes the single EMPTY segment if present
    has_empty = ~nonempty[-1]  # empties (if any) sort last
    n_distinct = n_seg_total - has_empty.astype(jnp.int32)
    return _apply_routing(state, ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg,
                          ev_lon_deg, ev_ts, ev_valid, late, evict, keep,
                          state_seg, batch_seg, n_distinct, params)


def _searchsorted_pair(a1, a2, q1, q2):
    """Leftmost insertion index of each (q1, q2) query into the array
    sorted lexicographically by (a1, a2) — u32 pairs, since the default
    no-x64 JAX config has no u64 (a static unrolled binary search; each
    step is two gathers over the query vector)."""
    n = a1.shape[0]
    lo = jnp.zeros(q1.shape, jnp.int32)
    hi = jnp.full(q1.shape, n, jnp.int32)
    for _ in range(max(n, 1).bit_length()):
        mid = (lo + hi) >> 1
        i = jnp.clip(mid, 0, n - 1)
        m1 = a1[i]
        m2 = a2[i]
        a_lt_q = (m1 < q1) | ((m1 == q1) & (m2 < q2))
        lo = jnp.where(a_lt_q, mid + 1, lo)
        hi = jnp.where(a_lt_q, hi, mid)
    return lo


@functools.partial(jax.jit, static_argnames=("params",))
def _merge_rank(
    state: TileState,
    ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg, ev_lon_deg, ev_ts, ev_valid,
    watermark_cutoff,
    params: AggParams,
):
    """Routing via a batch-only sort merged into the sorted slab by rank.

    The slab's sortedness invariant means the state side never needs
    re-sorting: evicted rows compact out with a cumsum, the batch's
    unique keys binary-search their insertion points, and every row's
    final position is (state rank) + (count of smaller new keys).  Work
    is ~sort(N) + O((C+N) log) instead of ~sort(C+N)."""
    C = state.capacity
    N = ev_hi.shape[0]
    U32MAX = jnp.uint32(0xFFFFFFFF)

    (late, ev_valid, ev_hi, ev_lo, ev_ws, evict, keep, st_hi, st_lo,
     st_ws) = _drop_and_evict(state, ev_hi, ev_lo, ev_ws, ev_valid,
                              watermark_cutoff, params)

    # compressed key pair: (k1, lo); k1 == U32MAX marks empty/invalid and
    # is unreachable for live rows (see _compress_key)
    st_k1 = _compress_key(st_hi, st_ws, ~keep, params)
    ev_k1 = _compress_key(ev_hi, ev_ws, ~ev_valid, params)

    # --- compact the kept state rows (stays sorted: subsequence) ---------
    c1, c2, pos_k, n_keep = _compact_state(keep, st_k1, st_lo, C)

    # --- sort the batch only ---------------------------------------------
    u1, u2, uid_of_event = _sorted_batch_uniques(ev_k1, ev_lo, N)

    state_seg, batch_seg, n_distinct = _route_via_uniques(
        c1, c2, pos_k, keep, n_keep, u1, u2, uid_of_event, ev_valid, C)
    return _apply_routing(state, ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg,
                          ev_lon_deg, ev_ts, ev_valid, late, evict, keep,
                          state_seg, batch_seg, n_distinct, params)


def _compact_state(keep, st_k1, st_lo, C: int):
    """Compact the kept state rows to the slab prefix (stays sorted: a
    subsequence of a sorted sequence).  THE definition of the compacted
    (c1, c2) slab both rank and probe routing search against."""
    U32MAX = jnp.uint32(0xFFFFFFFF)
    keep_i = keep.astype(jnp.int32)
    pos_k = jnp.cumsum(keep_i) - 1                # target rank per kept row
    n_keep = jnp.sum(keep_i)
    st_dst = jnp.where(keep, pos_k, C)
    c1 = jnp.full((C,), U32MAX, jnp.uint32).at[st_dst].set(st_k1, mode="drop")
    c2 = jnp.full((C,), U32MAX, jnp.uint32).at[st_dst].set(st_lo, mode="drop")
    return c1, c2, pos_k, n_keep


def _sorted_batch_uniques(ev_k1, ev_lo, N: int):
    """Batch sort + dedup: ascending unique (k1, lo) keys padded with
    (MAX, MAX), and each event's index into them.  THE definition of the
    sort route — _merge_rank always takes it, _merge_probe falls back to
    it, and bit-identity between those paths depends on both calling
    this one function."""
    U32MAX = jnp.uint32(0xFFFFFFFF)
    orig = jnp.arange(N, dtype=jnp.int32)
    s_k1, s_k2, s_orig = jax.lax.sort((ev_k1, ev_lo, orig), num_keys=2)
    is_start = ((s_k1 != jnp.roll(s_k1, 1))
                | (s_k2 != jnp.roll(s_k2, 1))).at[0].set(True)
    seg_b = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    u1 = jnp.full((N,), U32MAX, jnp.uint32).at[seg_b].set(s_k1)
    u2 = jnp.full((N,), U32MAX, jnp.uint32).at[seg_b].set(s_k2)
    uid_of_event = jnp.zeros((N,), jnp.int32).at[s_orig].set(seg_b)
    return u1, u2, uid_of_event


def _route_via_uniques(c1, c2, pos_k, keep, n_keep, u1, u2, uid_of_event,
                       ev_valid, C: int):
    """Shared rank-merge tail: given the compacted sorted slab (c1, c2),
    the ascending unique batch keys (u1, u2 — any length, (MAX, MAX)
    padded) and each event's index into them, produce the scatter
    routing tables (state_seg, batch_seg, n_distinct)."""
    U32MAX = jnp.uint32(0xFFFFFFFF)
    u_valid = u1 != U32MAX

    # --- rank the uniques against the compacted slab ---------------------
    p_state = _searchsorted_pair(c1, c2, u1, u2)
    i = jnp.clip(p_state, 0, C - 1)
    matched = u_valid & (p_state < C) & (c1[i] == u1) & (c2[i] == u2)
    is_new = u_valid & ~matched
    new_i = is_new.astype(jnp.int32)
    before = jnp.cumsum(new_i) - new_i        # new keys strictly smaller
    out_u = jnp.where(u_valid, p_state + before, C)

    # state-side shift without a second search: slab row j moves right by
    # #{new keys < c[j]} = #{new: p_state <= j} (a new key inserting at j
    # is strictly smaller than c[j] — never equal, else it would have
    # matched), i.e. an inclusive cumsum of insertion-point counts
    cnt_new = (jnp.zeros((C,), jnp.int32)
               .at[jnp.where(is_new, p_state, C)].add(1, mode="drop"))
    out_state_pos = jnp.arange(C, dtype=jnp.int32) + jnp.cumsum(cnt_new)

    # --- routing tables ---------------------------------------------------
    state_seg = jnp.where(
        keep, out_state_pos[jnp.clip(pos_k, 0, C - 1)], C)
    batch_seg = jnp.where(ev_valid, out_u[uid_of_event], C)
    n_distinct = n_keep + jnp.sum(new_i)
    return state_seg, batch_seg, n_distinct


@functools.partial(jax.jit, static_argnames=("params",))
def _merge_probe(
    state: TileState,
    ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg, ev_lon_deg, ev_ts, ev_valid,
    watermark_cutoff,
    params: AggParams,
):
    """Routing via hash-probe dedup instead of a batch sort.

    The batch sort is the dominant cost of ``rank`` at streaming shapes,
    yet a batch of N events typically holds ~N/10 distinct (cell,
    window) keys.  This impl dedups the batch into a 2N-slot linear-
    probing table with R rounds of gather/scatter (O(R·N) memory traffic
    — no log²N sorting network), then sorts only a fixed N/PROBE_UNIQ_DIV
    unique budget and reuses the rank machinery.  On a sort-hostile
    backend (TPU: lax.sort is ~log²N serial stages) the probe rounds
    replace ~98 stages with ~PROBE_ROUNDS passes.

    Correctness never depends on the probe converging: if any event is
    still unplaced after R rounds, or the distinct count exceeds the
    unique budget, a ``lax.cond`` falls back to the full batch-sort
    route for THIS batch (same routing-table contract, bit-identical
    ``_apply_routing`` epilogue).  Tunables: HEATMAP_PROBE_ROUNDS
    (default 16), HEATMAP_PROBE_UNIQ_DIV (default 8 → budget N/8,
    floor 256)."""
    C = state.capacity
    N = ev_hi.shape[0]
    U32MAX = jnp.uint32(0xFFFFFFFF)
    M = 1 << (2 * N - 1).bit_length()        # pow2 table, load <= 0.5
    U = min(N, max(256, N // PROBE_UNIQ_DIV))

    (late, ev_valid, ev_hi, ev_lo, ev_ws, evict, keep, st_hi, st_lo,
     st_ws) = _drop_and_evict(state, ev_hi, ev_lo, ev_ws, ev_valid,
                              watermark_cutoff, params)

    st_k1 = _compress_key(st_hi, st_ws, ~keep, params)
    ev_k1 = _compress_key(ev_hi, ev_ws, ~ev_valid, params)

    c1, c2, pos_k, n_keep = _compact_state(keep, st_k1, st_lo, C)

    # --- probe-dedup the batch -------------------------------------------
    h = ((ev_k1 * jnp.uint32(0x9E3779B9))
         ^ (ev_lo * jnp.uint32(0x85EBCA6B)))
    eidx = jnp.arange(N, dtype=jnp.int32)

    def probe_round(_, carry):
        tk1, tk2, placed, slot, off = carry
        idx = ((h + off.astype(jnp.uint32))
               & jnp.uint32(M - 1)).astype(jnp.int32)
        want = ~placed
        cur1 = tk1[idx]
        cur2 = tk2[idx]
        empty = cur1 == U32MAX
        mine = want & ~empty & (cur1 == ev_k1) & (cur2 == ev_lo)
        claim = want & empty
        # lowest event index wins a contested empty slot.  ALL losers of
        # an empty-slot contest re-check the SAME slot next round (off
        # unchanged): same-key losers then match the installed key;
        # different-key losers see a foreign key and advance — i.e. an
        # empty-slot loss costs one stalled round before advancing, so
        # worst-case placement needs (probe-chain length + contested
        # rounds), not just the chain length; size PROBE_ROUNDS (and
        # trust the fallback) accordingly
        claim_arr = (jnp.full((M,), N, jnp.int32)
                     .at[jnp.where(claim, idx, M)].min(eidx, mode="drop"))
        winner = claim & (claim_arr[idx] == eidx)
        widx = jnp.where(winner, idx, M)
        tk1 = tk1.at[widx].set(ev_k1, mode="drop")
        tk2 = tk2.at[widx].set(ev_lo, mode="drop")
        advance = want & ~empty & ~mine
        return (tk1, tk2, placed | mine | winner,
                jnp.where(mine | winner, idx, slot),
                off + advance.astype(jnp.int32))

    init = (jnp.full((M,), U32MAX, jnp.uint32),
            jnp.full((M,), U32MAX, jnp.uint32),
            ~ev_valid,                            # invalid rows never probe
            jnp.zeros_like(eidx),
            jnp.zeros_like(eidx))
    if PROBE_ROUNDS > 0:
        # round 0 unrolled: under shard_map the fori_loop carry must have
        # uniform "varying over shards" types, but the fresh tables above
        # are replicated constants while the loop's outputs depend on the
        # (sharded) batch.  One unrolled round makes every carry
        # component batch-derived before the loop sees it.
        init = probe_round(0, init)
    tk1, tk2, placed, slot, _ = jax.lax.fori_loop(
        1, PROBE_ROUNDS, probe_round, init)

    # --- compact + sort only the unique budget ---------------------------
    occupied = tk1 != U32MAX
    comp_pos = jnp.cumsum(occupied.astype(jnp.int32)) - 1     # over M slots
    n_uniq = jnp.sum(occupied.astype(jnp.int32))
    dst = jnp.where(occupied & (comp_pos < U), comp_pos, U)
    cu1 = jnp.full((U,), U32MAX, jnp.uint32).at[dst].set(tk1, mode="drop")
    cu2 = jnp.full((U,), U32MAX, jnp.uint32).at[dst].set(tk2, mode="drop")
    cid = jnp.arange(U, dtype=jnp.int32)
    s_u1, s_u2, s_cid = jax.lax.sort((cu1, cu2, cid), num_keys=2)
    rank_of_compact = jnp.zeros((U,), jnp.int32).at[s_cid].set(cid)
    compact_of_slot = jnp.clip(comp_pos, 0, U - 1)
    uid_of_event = rank_of_compact[compact_of_slot[jnp.clip(slot, 0, M - 1)]]

    fallback = jnp.any(ev_valid & ~placed) | (n_uniq > U)

    def probe_route(_):
        return _route_via_uniques(c1, c2, pos_k, keep, n_keep, s_u1, s_u2,
                                  uid_of_event, ev_valid & placed, C)

    def sort_route(_):
        u1, u2, uid = _sorted_batch_uniques(ev_k1, ev_lo, N)
        return _route_via_uniques(c1, c2, pos_k, keep, n_keep, u1, u2,
                                  uid, ev_valid, C)

    state_seg, batch_seg, n_distinct = jax.lax.cond(
        fallback, sort_route, probe_route, None)
    return _apply_routing(state, ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg,
                          ev_lon_deg, ev_ts, ev_valid, late, evict, keep,
                          state_seg, batch_seg, n_distinct, params)


def _fastpath_probe_full(state, ev_hi, ev_lo, ev_ws, ev_valid,
                         watermark_cutoff, params: AggParams):
    """The fast-path predicate: per-event binary search against the
    sorted slab.  Returns the masked prologue outputs, compressed keys,
    per-event row position, hit mask, and the tier-1 fast_ok scalar.

    The prologue runs on masked COPIES of the event arrays; the slow
    branch gets the ORIGINALS (its own prologue must see late rows to
    count them in its stats)."""
    C = state.capacity
    (late, ev_valid_m, ev_hi_m, ev_lo_m, ev_ws_m, evict, keep, st_hi,
     st_lo, st_ws) = _drop_and_evict(state, ev_hi, ev_lo, ev_ws, ev_valid,
                                     watermark_cutoff, params)
    st_k1 = _compress_key(st_hi, st_ws, ~keep, params)
    ev_k1 = _compress_key(ev_hi_m, ev_ws_m, ~ev_valid_m, params)
    pos = _searchsorted_pair(st_k1, st_lo, ev_k1, ev_lo_m)
    i = jnp.clip(pos, 0, C - 1)
    hit = (ev_valid_m & (pos < C) & (st_k1[i] == ev_k1)
           & (st_lo[i] == ev_lo_m))
    # with evictions the slab has EMPTY holes mid-array and the search
    # above ran against an unsorted sequence — `hit` is then garbage,
    # but the evict term already forces the slow branch
    fast_ok = jnp.all(hit == ev_valid_m) & ~jnp.any(evict)
    return (late, ev_valid_m, ev_hi_m, ev_lo_m, ev_ws_m, evict, keep,
            ev_k1, st_k1, st_lo, pos, hit, fast_ok)


def _fastpath_probe(state, ev_hi, ev_lo, ev_ws, ev_valid,
                    watermark_cutoff, params: AggParams):
    """Compact view of `_fastpath_probe_full` for the predicate tests:
    (late, masked ev_valid, positions, hit mask, tier-1 fast_ok)."""
    (late, ev_valid_m, _hi, _lo, _ws, _evict, _keep, _k1, _sk1, _slo,
     pos, hit, fast_ok) = _fastpath_probe_full(
        state, ev_hi, ev_lo, ev_ws, ev_valid, watermark_cutoff, params)
    return late, ev_valid_m, pos, hit, fast_ok


@functools.partial(jax.jit, static_argnames=("params", "slow_impl"))
def _merge_fastpath(
    state: TileState,
    ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg, ev_lon_deg, ev_ts, ev_valid,
    watermark_cutoff,
    params: AggParams,
    slow_impl: str,
):
    """Steady-state fast path wrapped around any routing impl.

    In a warmed stream most batches touch ONLY existing (cell, window)
    groups and evict nothing — yet every impl above rebuilds the entire
    slab (sort/scatter every lane of every row) per batch, which is the
    dominant cost at production shapes (~4/5 of the fold wall on CPU,
    round-5 attribution).  This wrapper binary-searches each event
    against the sorted slab directly (no batch sort, no dedup —
    duplicate hits are scatter-adds) and, when every valid event hits an
    existing row and no window evicts, applies the batch with in-place
    scatter-adds on the touched rows only; otherwise it falls through to
    the configured slow impl for THIS batch via ``lax.cond``.

    Three tiers, cheapest condition first (``lax.cond`` nest):

    1. **all-hit**: every valid event matched an existing row and no
       window evicts — in-place scatter-adds only, the slab untouched.
    2. **few misses** (≤ max(1024, N/16) events): hit events take their
       searched positions directly; only the miss events compact into a
       small buffer, sort, and ride the rank impl's insertion rails
       (`_route_via_uniques` + `_apply_routing`).  This replaces rank's
       full-batch sort — the dominant term at production batches — with
       a sort of just the misses, and produces the exact routing tables
       rank would (proof sketch: a matched unique's shift `before(u)`
       equals `cumsum(cnt_new)[p_state(u)]` because a new key inserting
       at or before a matched row is strictly smaller than it).
    3. otherwise (evictions, miss burst, window turnover): the
       configured slow impl, unchanged.

    Bit-identity with the slow path on tier-1/2 batches is by
    construction: tier 1 replicates `_apply_routing`'s arithmetic under
    its no-new-keys/no-evict conditions — including the slow path's
    Kahan rewrite of untouched rows (sum' = sum - comp, comp' absorbs
    it) — and tier 2 feeds `_apply_routing` itself with rank-identical
    routing tables.  Differential-tested per batch
    (tests/test_merge_fastpath.py).  The slab's sorted invariant is
    preserved by every tier."""
    C = state.capacity
    N = ev_hi.shape[0]
    M = max(1024, N // 16)  # miss-event budget for the insert tier
    (late, ev_valid_m, ev_hi_m, ev_lo_m, ev_ws_m, evict, keep,
     ev_k1, st_k1, st_lo_m, pos, hit, fast_ok) = _fastpath_probe_full(
        state, ev_hi, ev_lo, ev_ws, ev_valid, watermark_cutoff, params)

    def fast(_):
        B = state.hist_bins
        E = params.emit_capacity
        gi = jnp.where(hit, pos, C)          # drop bin for misses
        gic = jnp.clip(gi, 0, C - 1)
        one = hit.astype(jnp.int32)
        count = state.count.at[gi].add(one, mode="drop")

        resid = lambda ev, anc: jnp.where(hit, ev - anc[gic], 0.0)
        r_speed = resid(ev_speed, state.anchor_speed)
        r_lat = resid(ev_lat_deg, state.anchor_lat)
        r_lon = resid(ev_lon_deg, state.anchor_lon)
        ev_vals = jnp.stack([
            r_speed, r_speed * r_speed, r_lat, r_lon,
        ], axis=1)
        # the slow path's epilogue Kahan-rewrites EVERY row (untouched
        # rows become sum-comp with comp absorbing the shift); replicate
        # it exactly so fast and slow batches interleave bit-identically
        base = jnp.stack([
            state.sum_speed, state.sum_speed2, state.sum_lat,
            state.sum_lon,
        ], axis=1)
        delta = jnp.zeros((C, 4), jnp.float32).at[gi].add(
            ev_vals, mode="drop")
        y = delta - state.comp
        t = base + y
        comp = (t - base) - y
        sum_speed, sum_speed2, sum_lat, sum_lon = (
            t[:, 0], t[:, 1], t[:, 2], t[:, 3]
        )
        if B > 0:
            bin_w = params.speed_hist_max / B
            ev_bin = jnp.clip((ev_speed / bin_w).astype(jnp.int32), 0,
                              B - 1)
            hist = state.hist.at[gi, ev_bin].add(one, mode="drop")
        else:
            hist = state.hist
        new_state = TileState(
            key_hi=state.key_hi, key_lo=state.key_lo, key_ws=state.key_ws,
            count=count, sum_speed=sum_speed, sum_speed2=sum_speed2,
            sum_lat=sum_lat, sum_lon=sum_lon, hist=hist,
            anchor_speed=state.anchor_speed, anchor_lat=state.anchor_lat,
            anchor_lon=state.anchor_lon, comp=comp,
        )

        touched = jnp.zeros((C,), bool).at[gi].set(True, mode="drop")
        n_emitted = jnp.sum(touched.astype(jnp.int32))
        emit_idx = jnp.nonzero(touched, size=E, fill_value=C)[0]
        emit_ok = emit_idx < C
        g = jnp.where(emit_ok, emit_idx, 0)
        emit = BatchEmit(
            key_hi=jnp.where(emit_ok, state.key_hi[g], EMPTY_KEY_HI),
            key_lo=jnp.where(emit_ok, state.key_lo[g], EMPTY_KEY_LO),
            key_ws=jnp.where(emit_ok, state.key_ws[g], EMPTY_WS),
            count=jnp.where(emit_ok, count[g], 0),
            sum_speed=jnp.where(emit_ok, sum_speed[g], 0.0),
            sum_speed2=jnp.where(emit_ok, sum_speed2[g], 0.0),
            sum_lat=jnp.where(emit_ok, sum_lat[g], 0.0),
            sum_lon=jnp.where(emit_ok, sum_lon[g], 0.0),
            anchor_speed=jnp.where(emit_ok, state.anchor_speed[g], 0.0),
            anchor_lat=jnp.where(emit_ok, state.anchor_lat[g], 0.0),
            anchor_lon=jnp.where(emit_ok, state.anchor_lon[g], 0.0),
            hist=hist[g] * emit_ok[:, None].astype(jnp.int32) if B > 0
            else jnp.zeros((E, 0), jnp.int32),
            valid=emit_ok,
            n_emitted=n_emitted,
            overflowed=n_emitted > E,
        )
        n_valid = jnp.sum(one)
        stats = StepStats(
            n_valid=n_valid,
            n_late=jnp.sum(late.astype(jnp.int32)),
            # zero by the tier-1 predicate, but derived from varying data
            # (a literal 0 would give this branch an unvarying aval and
            # break lax.cond type agreement under shard_map)
            n_evicted=jnp.sum(evict.astype(jnp.int32)),
            n_active=jnp.sum((state.key_hi != EMPTY_KEY_HI)
                             .astype(jnp.int32)),
            state_overflow=0 * n_valid,
            batch_max_ts=jnp.max(jnp.where(ev_valid_m, ev_ts, I32_MIN)),
        )
        return new_state, emit, stats

    miss = ev_valid_m & ~hit
    n_miss = jnp.sum(miss.astype(jnp.int32))
    insert_ok = (~jnp.any(evict)) & (n_miss <= M) & (n_miss > 0)

    def insert(_):
        """Tier 2: hits keep their searched rows; only the miss events
        sort (M rows, not N) and ride the rank insertion rails."""
        U32MAX = jnp.uint32(0xFFFFFFFF)
        midx = jnp.nonzero(miss, size=M, fill_value=N)[0]
        mvalid = midx < N
        mi = jnp.clip(midx, 0, N - 1)
        mk1 = jnp.where(mvalid, ev_k1[mi], U32MAX)
        mk2 = jnp.where(mvalid, ev_lo_m[mi], U32MAX)
        mu1, mu2, uid_m = _sorted_batch_uniques(mk1, mk2, M)
        # event -> its M-slot -> unique id (only meaningful for misses)
        slot_of_event = (jnp.zeros((N,), jnp.int32)
                         .at[jnp.where(mvalid, midx, N)]
                         .set(jnp.arange(M, dtype=jnp.int32), mode="drop"))
        c1, c2, pos_k, n_keep = _compact_state(keep, st_k1, st_lo_m, C)
        state_seg, batch_seg_u, n_distinct = _route_via_uniques(
            c1, c2, pos_k, keep, n_keep, mu1, mu2,
            uid_m[jnp.clip(slot_of_event, 0, M - 1)],
            miss, C)
        # hits: final position = searched row + #new keys inserted at or
        # before it (== rank's `before` for a matched unique; see proof
        # sketch in the docstring).  Recover the shift from state_seg:
        # row r moved to state_seg[r], so shift lives in the same table.
        hit_rows = jnp.clip(pos, 0, C - 1)
        batch_seg = jnp.where(
            hit, state_seg[hit_rows],
            jnp.where(miss, batch_seg_u, C))
        return _apply_routing(state, ev_hi_m, ev_lo_m, ev_ws_m, ev_speed,
                              ev_lat_deg, ev_lon_deg, ev_ts, ev_valid_m,
                              late, evict, keep, state_seg, batch_seg,
                              n_distinct, params)

    def slow(_):
        fn = {"rank": _merge_rank, "probe": _merge_probe,
              "sort": _merge_sort}[slow_impl]
        return fn(state, ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg,
                  ev_lon_deg, ev_ts, ev_valid, watermark_cutoff, params)

    def not_fast(_):
        return jax.lax.cond(insert_ok, insert, slow, None)

    return jax.lax.cond(fast_ok, fast, not_fast, None)


def _apply_routing(
    state: TileState,
    ev_hi, ev_lo, ev_ws, ev_speed, ev_lat_deg, ev_lon_deg, ev_ts, ev_valid,
    late, evict, keep,
    state_seg, batch_seg, n_distinct,
    params: AggParams,
):
    """Shared epilogue: rebuild the slab from the routing tables, build the
    update-mode emit, and assemble StepStats."""
    C = state.capacity
    B = state.hist_bins

    # --- rebuild the slab ------------------------------------------------
    # keys scatter from the ORIGINAL arrays via the routing maps (the sort
    # only carried the compressed keys); rows of one segment all write the
    # same value, the EMPTY segment keeps its init sentinel.
    key_hi = (
        jnp.full((C,), EMPTY_KEY_HI, jnp.uint32)
        .at[state_seg].set(state.key_hi, mode="drop")
        .at[batch_seg].set(ev_hi, mode="drop")
    )
    key_lo = (
        jnp.full((C,), EMPTY_KEY_LO, jnp.uint32)
        .at[state_seg].set(state.key_lo, mode="drop")
        .at[batch_seg].set(ev_lo, mode="drop")
    )
    key_ws = (
        jnp.full((C,), EMPTY_WS, jnp.int32)
        .at[state_seg].set(state.key_ws, mode="drop")
        .at[batch_seg].set(ev_ws, mode="drop")
    )

    zc = jnp.zeros((C,), jnp.int32)
    one = ev_valid.astype(jnp.int32)
    count = (
        zc.at[state_seg].add(jnp.where(keep, state.count, 0), mode="drop")
        .at[batch_seg].add(one, mode="drop")
    )

    # --- residual-anchor accumulation (the f64-free precision story) ----
    # TPUs have no f64, and absolute f32 sums cannot hold the needed
    # precision: Σlat over a 1M-event hot cell reaches ~4e7 where the f32
    # ulp is 4, so even a correctly-rounded absolute sum puts the centroid
    # ~2e-6 deg off.  Each group instead carries FIXED anchors (min over
    # the events of the batch that created it — a segment-min, so both
    # merge impls derive the identical value) and accumulates residuals
    # about them.  Values within one hex cell lie within a fraction of
    # each other, so `ev - anchor` is exact (Sterbenz) and the residual
    # sums stay small enough for f32 to hold to ~1e-8 deg.  Consumers
    # recombine anchor + resid/count in f64 host-side (sink/base.py,
    # native/tile_ops.cpp); speed variance is anchor-invariant:
    # Var(v) = E[r²] − E[r]².
    inf = jnp.float32(jnp.inf)

    def group_anchor(ev, stored):
        a = (jnp.full((C,), inf, jnp.float32)
             .at[batch_seg].min(jnp.where(ev_valid, ev, inf), mode="drop"))
        # existing groups keep their stored anchor: accumulated residuals
        # are relative to it, so it must never move while the group lives
        return a.at[state_seg].set(jnp.where(keep, stored, inf), mode="drop")

    anc_speed = group_anchor(ev_speed, state.anchor_speed)
    anc_lat = group_anchor(ev_lat_deg, state.anchor_lat)
    anc_lon = group_anchor(ev_lon_deg, state.anchor_lon)

    gi_ev = jnp.clip(batch_seg, 0, C - 1)
    resid = lambda ev, anc: jnp.where(ev_valid, ev - anc[gi_ev], 0.0)
    r_speed = resid(ev_speed, anc_speed)
    r_lat = resid(ev_lat_deg, anc_lat)
    r_lon = resid(ev_lon_deg, anc_lon)
    # overflow-dropped events may read an empty row's inf anchor → non-
    # finite residuals; their scatter writes are dropped (mode="drop"),
    # so the values never land — only anchors stored/emitted must be
    # sanitized (below).

    # the four float accumulators ride one (C, 4) scatter instead of four
    kf = keep.astype(jnp.float32)
    st_vals = jnp.stack([
        state.sum_speed * kf, state.sum_speed2 * kf,
        state.sum_lat * kf, state.sum_lon * kf,
    ], axis=1)
    ev_vals = jnp.stack([
        r_speed, r_speed * r_speed, r_lat, r_lon,
    ], axis=1)
    base = jnp.zeros((C, 4), jnp.float32).at[state_seg].add(
        st_vals, mode="drop")
    delta = jnp.zeros((C, 4), jnp.float32).at[batch_seg].add(
        ev_vals, mode="drop")
    comp_r = jnp.zeros((C, 4), jnp.float32).at[state_seg].add(
        state.comp * kf[:, None], mode="drop")
    # Kahan fold of the batch delta into the carried sums: the error of
    # each fold is captured in `comp` and fed back, so the accumulated
    # error stays at per-batch scatter rounding instead of growing with
    # the group's total count.  (XLA does not reassociate float adds by
    # default, so the compensation term survives compilation.)
    y = delta - comp_r
    t = base + y
    comp = (t - base) - y
    sums = t
    sum_speed, sum_speed2, sum_lat, sum_lon = (
        sums[:, 0], sums[:, 1], sums[:, 2], sums[:, 3]
    )
    # empty/recycled rows: finite zeros (inf anchors would poison a later
    # emit pack; empties have no batch events and no kept state row)
    anc_speed = jnp.where(jnp.isfinite(anc_speed), anc_speed, 0.0)
    anc_lat = jnp.where(jnp.isfinite(anc_lat), anc_lat, 0.0)
    anc_lon = jnp.where(jnp.isfinite(anc_lon), anc_lon, 0.0)

    if B > 0:
        bin_w = params.speed_hist_max / B
        ev_bin = jnp.clip((ev_speed / bin_w).astype(jnp.int32), 0, B - 1)
        hist = jnp.zeros((C, B), jnp.int32)
        hist = hist.at[state_seg].add(
            state.hist * keep[:, None].astype(jnp.int32), mode="drop"
        )
        hist = hist.at[batch_seg, ev_bin].add(one, mode="drop")
    else:
        hist = state.hist

    new_state = TileState(
        key_hi=key_hi, key_lo=key_lo, key_ws=key_ws, count=count,
        sum_speed=sum_speed, sum_speed2=sum_speed2,
        sum_lat=sum_lat, sum_lon=sum_lon, hist=hist,
        anchor_speed=anc_speed, anchor_lat=anc_lat, anchor_lon=anc_lon,
        comp=comp,
    )

    # --- update-mode emit: groups touched by this batch -------------------
    E = params.emit_capacity
    touched = jnp.zeros((C,), bool).at[batch_seg].set(True, mode="drop")
    n_emitted = jnp.sum(touched.astype(jnp.int32))
    emit_idx = jnp.nonzero(touched, size=E, fill_value=C)[0]
    emit_ok = emit_idx < C
    gi = jnp.where(emit_ok, emit_idx, 0)
    emit = BatchEmit(
        key_hi=jnp.where(emit_ok, key_hi[gi], EMPTY_KEY_HI),
        key_lo=jnp.where(emit_ok, key_lo[gi], EMPTY_KEY_LO),
        key_ws=jnp.where(emit_ok, key_ws[gi], EMPTY_WS),
        count=jnp.where(emit_ok, count[gi], 0),
        sum_speed=jnp.where(emit_ok, sum_speed[gi], 0.0),
        sum_speed2=jnp.where(emit_ok, sum_speed2[gi], 0.0),
        sum_lat=jnp.where(emit_ok, sum_lat[gi], 0.0),
        sum_lon=jnp.where(emit_ok, sum_lon[gi], 0.0),
        anchor_speed=jnp.where(emit_ok, anc_speed[gi], 0.0),
        anchor_lat=jnp.where(emit_ok, anc_lat[gi], 0.0),
        anchor_lon=jnp.where(emit_ok, anc_lon[gi], 0.0),
        hist=hist[gi] * emit_ok[:, None].astype(jnp.int32) if B > 0
        else jnp.zeros((E, 0), jnp.int32),
        valid=emit_ok,
        n_emitted=n_emitted,
        overflowed=n_emitted > E,
    )

    # --- stats ------------------------------------------------------------
    stats = StepStats(
        n_valid=jnp.sum(one),
        n_late=jnp.sum(late.astype(jnp.int32)),
        n_evicted=jnp.sum(evict.astype(jnp.int32)),
        n_active=jnp.sum((key_hi != EMPTY_KEY_HI).astype(jnp.int32)),
        state_overflow=jnp.maximum(n_distinct - C, 0),
        batch_max_ts=jnp.max(jnp.where(ev_valid, ev_ts, I32_MIN)),
    )
    return new_state, emit, stats


def p95_from_hist_device(hist, count, hist_max: float):
    """Vectorized 95th percentile from per-row speed histograms (device).

    Same interpolation as the host oracle (tests/test_emit_pack.py);
    computing it on device means the (E, B) histogram never has to cross
    the device->host link."""
    E, B = hist.shape
    bin_w = hist_max / B
    target = 0.95 * count.astype(jnp.float32)
    cum = jnp.cumsum(hist, axis=1).astype(jnp.float32)
    i = jnp.sum((cum < target[:, None]).astype(jnp.int32), axis=1)
    ic = jnp.clip(i, 0, B - 1)
    prev = jnp.where(
        ic > 0,
        jnp.take_along_axis(cum, jnp.maximum(ic - 1, 0)[:, None], axis=1)[:, 0],
        0.0,
    )
    in_bin = jnp.take_along_axis(hist, ic[:, None], axis=1)[:, 0].astype(jnp.float32)
    frac = jnp.where(in_bin > 0, (target - prev) / in_bin, 0.0)
    p95 = jnp.where(i >= B, hist_max, (ic.astype(jnp.float32) + frac) * bin_w)
    return jnp.where(count > 0, p95, 0.0)


def pack_emit(emit: BatchEmit, speed_hist_max: float = 256.0) -> jnp.ndarray:
    """Pack a BatchEmit into one (E+1, 13) uint32 matrix.

    Remote-attached TPUs pay a full round trip per transferred leaf; one
    packed matrix makes the per-batch device->host pull a single transfer.
    Row 0 carries [n_emitted, overflowed] in slots 0..1; slots 2.. are
    reserved for a stats rider (``ride_stats`` — engine.multi and
    parallel.sharded embed their step stats there so the host needs no
    second transfer).  Rows 1.. are [key_hi, key_lo, ws, count, sum_speed,
    sum_speed2, sum_lat, sum_lon, valid, p95, anchor_speed, anchor_lat,
    anchor_lon] with float lanes bitcast — the sum lanes are per-group
    RESIDUAL sums about the anchor lanes (engine.state.TileState); the
    consumer recombines anchor + resid/count in f64.  The histogram
    itself stays on device — its p95 summary is computed here.
    ``unpack_emit`` reverses it host-side.
    """
    bc = lambda a: jax.lax.bitcast_convert_type(a, jnp.uint32)
    E = emit.key_hi.shape[0]
    if emit.hist.shape[1] > 0:
        p95 = p95_from_hist_device(emit.hist, emit.count, speed_hist_max)
    else:
        p95 = jnp.zeros((E,), jnp.float32)
    body = jnp.stack([
        emit.key_hi,
        emit.key_lo,
        bc(emit.key_ws),
        bc(emit.count),
        bc(emit.sum_speed),
        bc(emit.sum_speed2),
        bc(emit.sum_lat),
        bc(emit.sum_lon),
        emit.valid.astype(jnp.uint32),
        bc(p95),
        bc(emit.anchor_speed),
        bc(emit.anchor_lat),
        bc(emit.anchor_lon),
    ], axis=1)
    head = jnp.zeros((1, body.shape[1]), jnp.uint32)
    head = head.at[0, 0].set(emit.n_emitted.reshape(()).astype(jnp.uint32))
    head = head.at[0, 1].set(emit.overflowed.reshape(()).astype(jnp.uint32))
    return jnp.concatenate([head, body], axis=0)


_STATS_RIDER_SLOT0 = 2  # first head-row slot available to ride_stats


def ride_stats(packed: jnp.ndarray, stats) -> jnp.ndarray:
    """Embed a NamedTuple of int32 scalars into the packed head row.

    The single definition of the stats-rider layout: fields land in head
    slots 2..2+len(stats), in field order, bitcast to uint32.  Decode with
    ``read_stats_rider`` using a host NamedTuple with the SAME fields in
    the same order.
    """
    n = len(stats)
    if _STATS_RIDER_SLOT0 + n > packed.shape[1]:
        raise ValueError(f"stats rider of {n} fields does not fit the "
                         f"{packed.shape[1]}-slot head row")
    svec = jax.lax.bitcast_convert_type(
        jnp.stack(list(stats)).astype(jnp.int32), jnp.uint32)
    return packed.at[0, _STATS_RIDER_SLOT0:_STATS_RIDER_SLOT0 + n].set(svec)


def read_stats_rider(packed_np, cls):
    """Host-side inverse of ``ride_stats``: decode ``cls`` (a NamedTuple
    type of ints, fields ordered as the device-side stats tuple) from a
    packed matrix's head row."""
    import numpy as np

    n = len(cls._fields)
    raw = np.asarray(packed_np)[0, _STATS_RIDER_SLOT0:_STATS_RIDER_SLOT0 + n]
    return cls(*[int(v) for v in raw.view(np.int32)])


def unpack_emit(packed) -> dict:
    """Host-side inverse of pack_emit: dict of numpy arrays + scalars."""
    import numpy as np

    p = np.asarray(packed)
    body = p[1:]
    f32 = lambda col: body[:, col].view(np.float32)
    return {
        "key_hi": body[:, 0],
        "key_lo": body[:, 1],
        "key_ws": body[:, 2].view(np.int32),
        "count": body[:, 3].view(np.int32),
        "sum_speed": f32(4),
        "sum_speed2": f32(5),
        "sum_lat": f32(6),
        "sum_lon": f32(7),
        "valid": body[:, 8] != 0,
        "p95": f32(9),
        "anchor_speed": f32(10),
        "anchor_lat": f32(11),
        "anchor_lon": f32(12),
        "n_emitted": int(p[0, 0]),
        "overflowed": bool(p[0, 1]),
    }


def aggregate_batch(
    state: TileState,
    lat_rad,
    lng_rad,
    speed_kmh,
    ts_s,
    valid,
    watermark_cutoff,
    params: AggParams,
):
    """Convenience: snap + window + merge in one call (used by stream/)."""
    hi, lo, ws = snap_and_window(lat_rad, lng_rad, ts_s, valid, params)
    lat_deg = lat_rad * (180.0 / jnp.pi)
    lon_deg = lng_rad * (180.0 / jnp.pi)
    return merge_batch(
        state, hi, lo, ws, speed_kmh, lat_deg, lon_deg, ts_s, valid,
        watermark_cutoff, params,
    )

def pull_packed_stack(packed, prefix: bool) -> list:
    """Device->host pull of a stacked packed-emit array ((P, E+1, L)
    uint32 — one (E+1, L) block per pair/batch) as a list of P host
    matrices.  THE single implementation of the transfer discipline
    (stream.runtime and bench.py both route here).

    ``prefix=False``: one full transfer.  ``prefix=True``: the P head
    rows first (they carry n_emitted + the stats rider), then one shared
    live-prefix bucket — max n_emitted across blocks rounded up to a
    power of two, so at most log2(E) slice shapes ever compile.  Live
    emit rows are a prefix by construction (pack_emit's nonzero() yields
    ascending indices with the fill at the tail) and rows inside the
    bucket past a block's own n_emitted carry valid=0, so every consumer
    (unpack_emit, packed_tile_docs, the C++ encoder) works unchanged.

    On remote-attached accelerators the D2H payload dominates the extra
    round trip as soon as emit capacity dwarfs the touched-group count —
    the streaming steady state.  On CPU the full pull is cheaper (an
    extra round trip with nothing to save).
    """
    import numpy as np

    if not prefix:
        b = np.asarray(packed)
        return [b[i] for i in range(b.shape[0])]
    heads = np.asarray(packed[:, 0, :])             # (P, L) tiny
    E = packed.shape[1] - 1
    n_max = int(heads[:, 0].astype(np.int64).max())
    bucket = 1
    while bucket < n_max and bucket < E:
        bucket <<= 1
    bucket = min(bucket, E)                          # overflow: n > E
    body = np.asarray(packed[:, 1:1 + bucket, :])
    return [np.concatenate([heads[i:i + 1], body[i]])
            for i in range(body.shape[0])]


def pull_emit_prefix(packed):
    """Live-prefix pull of ONE packed emit matrix ((E+1, L) uint32) —
    the single-block view of ``pull_packed_stack``."""
    return pull_packed_stack(packed[None], prefix=True)[0]


class EmitRing:
    """Fixed-capacity accumulator of DEVICE-RESIDENT packed emits.

    Each ``append`` parks one batch's stacked packed-emit matrix
    ((P, E+1, L) uint32, stats ridden in the head rows) on device; a
    ``flush_stacked`` concatenates every parked batch in ONE eager device
    op and crosses the device->host link with a single
    ``pull_packed_stack`` call — so K batches pay one pull's round trips
    instead of K (the per-batch pull over the ~200 KB/s tunnel dominated
    the fused hex_pyramid/multi_window pipelines, VERDICT r5 §3).  While
    entries sit in the ring the device runs ahead unforced: nothing
    synchronizes on batch k's fold until the flush that covers it.

    Entries must share one shape — the owner flushes before any slab /
    emit-capacity resize (``append`` refuses a mismatched shape loudly
    rather than corrupting the stack).  ``take`` hands the raw entries
    back un-pulled for callers with their own transfer discipline (the
    sharded path pulls addressable shards per entry).

    Per-mesh-shard rings (the partitioned mesh fast path keeps ONE ring
    per device) additionally distinguish LIVE entries (batches that fed
    the shard rows) from idle ones (empty dispatches parked only so
    their eviction emits and stats are never dropped): ``full`` triggers
    on the live count, so a hot shard's flush cadence is its own and an
    idle shard holds its (empty) entries until a forced flush — its
    device→host pull count stays at the idle-flush floor.  Idle entries
    still bound memory: past ``8 * capacity`` total parked entries the
    ring reads full regardless of liveness.
    """

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._entries: list = []      # (packed_device, tag) append order
        self._enter: list = []        # (monotonic enter, append seq, live)
        self._appends = 0             # lifetime appends (residency base)
        self.live_pending = 0         # parked entries appended live=True
        self.n_flushes = 0            # pulls issued (telemetry)
        # residency of the entries the LAST take()/flush_stacked()
        # drained, aligned with its return order: (seconds parked,
        # batches resident — appends from the entry's own, inclusive, to
        # the flush; the oldest entry of a K-deep flush reads K).  The
        # stream runtime feeds these into the
        # heatmap_emit_ring_residency_* histograms and the freshness
        # lineage (obs.lineage) right after each flush.
        # ``last_flush_live`` is the aligned per-entry live flag.
        self.last_flush_residency: list = []
        self.last_flush_live: list = []

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def full(self) -> bool:
        return (self.live_pending >= self.capacity
                or len(self._entries) >= 8 * self.capacity)

    @property
    def nbytes(self) -> int:
        """Bytes of packed emits currently parked on device — the ring
        slab's share of HBM (obs.runtimeinfo memory telemetry).  All
        entries share one shape, so this is len * entry-bytes.  Reads a
        local snapshot: the scrape thread races the step thread's
        take(), and a swap between the check and the index must not
        turn the gauge sample into an error."""
        entries = self._entries
        if not entries:
            return 0
        return len(entries) * int(entries[0][0].nbytes)

    def append(self, packed, tag=None, live: bool = True) -> bool:
        """Park one batch's packed emits; True when the ring is full
        (flush before the next append).  ``live=False`` marks an empty
        dispatch (no input rows for this shard): it parks — eviction
        emits and stats riding it must still be pulled eventually — but
        does not advance the flush trigger (per-mesh-shard flush
        independence)."""
        if self._entries and tuple(packed.shape) != tuple(
                self._entries[0][0].shape):
            raise ValueError(
                f"emit ring entries must share one shape "
                f"(got {tuple(packed.shape)} vs "
                f"{tuple(self._entries[0][0].shape)}); flush before a "
                f"slab/emit-capacity resize")
        self._appends += 1
        self._entries.append((packed, tag))
        self._enter.append((time.monotonic(), self._appends, live))
        if live:
            self.live_pending += 1
        return self.full

    def take(self) -> list:
        """Drain the raw (packed, tag) entries without pulling."""
        entries, self._entries = self._entries, []
        enters, self._enter = self._enter, []
        self.live_pending = 0
        if entries:
            self.n_flushes += 1
            now = time.monotonic()
            self.last_flush_residency = [
                (now - t, self._appends - seq + 1)
                for t, seq, _live in enters]
            # aligned liveness flags: residency TELEMETRY should only
            # describe real data batches — an idle mesh shard's empty
            # entries park ~8x longer than any live batch and would
            # dominate the histograms (the caller filters on this)
            self.last_flush_live = [live for _t, _s, live in enters]
        else:
            self.last_flush_residency = []
            self.last_flush_live = []
        return entries

    def flush_stacked(self, prefix: bool) -> list:
        """Pull every parked batch in one transfer.

        Returns [(bufs, tag)] in append order, where ``bufs`` is the
        per-pair list of host matrices ``pull_packed_stack`` would have
        produced for that batch alone — consumers (unpack_emit,
        stats_from_packed, packed_tile_docs) are unchanged.
        """
        entries = self.take()
        if not entries:
            return []
        if len(entries) == 1:
            packed, tag = entries[0]
            return [(pull_packed_stack(packed, prefix), tag)]
        import jax.numpy as jnp

        n_pairs = entries[0][0].shape[0]
        blocks = jnp.concatenate([p for p, _ in entries], axis=0)
        bufs = pull_packed_stack(blocks, prefix)
        return [(bufs[i * n_pairs:(i + 1) * n_pairs], tag)
                for i, (_, tag) in enumerate(entries)]
