"""Device-resident aggregation state for one (resolution, window-size) pair.

Layout: a fixed-capacity compact slab of (cell, windowStart) groups, kept
**sorted by key** with empty slots (key_hi == EMPTY_KEY_HI) at the tail.
Sortedness is the invariant that lets each micro-batch be folded in with one
merge-sort rather than hash probing (see step.merge_batch).

The 64-bit cell index rides as two uint32 lanes (TPU-friendly; see
hexgrid/device.py).  Aggregates mirror the reference's groupBy outputs —
count, avg(speedKmh), avg(lon), avg(lat) (reference: heatmap_stream.py:118-123)
— plus sum-of-squares and an optional per-cell speed histogram so the
extended stats configs (p95 speed, BASELINE.json config #5) come from the
same state.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

# Sentinel for empty slots.  Valid cell-index high words always have bit 31
# (the reserved H3 bit 63) clear, so 0xFFFFFFFF can never collide.
EMPTY_KEY_HI = jnp.uint32(0xFFFFFFFF)
EMPTY_KEY_LO = jnp.uint32(0xFFFFFFFF)
EMPTY_WS = jnp.int32(2**31 - 1)


class TileState(NamedTuple):
    """All arrays share leading dim = capacity C; hist is (C, B) (B may be 0).

    The four float accumulators hold RESIDUAL sums about fixed per-group
    anchors (``anchor_*``), not absolute sums: TPUs have no f64, and an
    absolute f32 Σlat over a million-event hot cell reaches ~4e7 where the
    f32 ulp is 4 — the representable sum itself is then microdegrees off.
    Residuals within one hex cell are tiny (and exact to compute, see
    step._apply_routing), so f32 holds them losslessly; consumers
    recombine ``anchor + resid/count`` in f64 host-side.  ``comp`` carries
    Kahan compensation for the residual sums so cross-batch folding error
    stays at per-batch rounding level instead of growing with count."""

    key_hi: jnp.ndarray    # uint32 — cell index bits 32..63
    key_lo: jnp.ndarray    # uint32 — cell index bits 0..31
    key_ws: jnp.ndarray    # int32  — window start, epoch seconds
    count: jnp.ndarray     # int32
    sum_speed: jnp.ndarray   # float32 — Σ (speedKmh - anchor_speed)
    sum_speed2: jnp.ndarray  # float32 — Σ (speedKmh - anchor_speed)²
    sum_lat: jnp.ndarray     # float32 — Σ (lat - anchor_lat) (degrees)
    sum_lon: jnp.ndarray     # float32 — Σ (lon - anchor_lon) (degrees)
    hist: jnp.ndarray        # int32 (C, B) — speed histogram for p95
    anchor_speed: jnp.ndarray  # float32 — fixed per-group speed anchor
    anchor_lat: jnp.ndarray    # float32 — fixed per-group lat anchor
    anchor_lon: jnp.ndarray    # float32 — fixed per-group lon anchor
    comp: jnp.ndarray          # float32 (C, 4) — Kahan compensation for
                               # (sum_speed, sum_speed2, sum_lat, sum_lon)

    @property
    def capacity(self) -> int:
        return self.key_hi.shape[0]

    @property
    def hist_bins(self) -> int:
        return self.hist.shape[1]


def init_state(capacity: int, hist_bins: int = 0) -> TileState:
    c = capacity
    return TileState(
        key_hi=jnp.full((c,), EMPTY_KEY_HI, jnp.uint32),
        key_lo=jnp.full((c,), EMPTY_KEY_LO, jnp.uint32),
        key_ws=jnp.full((c,), EMPTY_WS, jnp.int32),
        count=jnp.zeros((c,), jnp.int32),
        sum_speed=jnp.zeros((c,), jnp.float32),
        sum_speed2=jnp.zeros((c,), jnp.float32),
        sum_lat=jnp.zeros((c,), jnp.float32),
        sum_lon=jnp.zeros((c,), jnp.float32),
        hist=jnp.zeros((c, hist_bins), jnp.int32),
        anchor_speed=jnp.zeros((c,), jnp.float32),
        anchor_lat=jnp.zeros((c,), jnp.float32),
        anchor_lon=jnp.zeros((c,), jnp.float32),
        comp=jnp.zeros((c, 4), jnp.float32),
    )


def donate_state_argnums() -> tuple:
    """``(0,)`` off-CPU, ``()`` on CPU — the donate_argnums value for
    the jitted step programs that fold the state slabs in place.

    Donation is the memory-correct choice on accelerators (the slab is
    the dominant HBM tenant; without donation every step holds two).
    On this jaxlib's CPU client, however, donated step buffers + the
    async dispatch pipeline corrupt the heap (glibc "corrupted
    double-linked list" aborts mid-suite, reproducibly in the
    resume-then-step path) — and on CPU the donation saves only a
    host-RAM copy.  So the step programs donate exactly where it pays
    and is safe: any non-CPU backend."""
    import jax

    return () if jax.default_backend() == "cpu" else (0,)


_device_copy = None


def device_copy(state: TileState) -> TileState:
    """Fresh on-device copy of a state slab (new buffers, same sharding).

    The step programs donate their state argument, so a snapshot taken by
    reference would be invalidated by the very next step on real hardware.
    This copy dispatches asynchronously and costs one HBM->HBM pass, which
    is what lets checkpoints pull state off-device on a background thread
    while the step loop keeps running (VERDICT round-1 item 6).
    """
    global _device_copy
    if _device_copy is None:
        import jax

        _device_copy = jax.jit(
            lambda s: jax.tree_util.tree_map(jnp.copy, s))
    return _device_copy(state)


def to_host(snap: TileState) -> TileState:
    """Host-side numpy copy of a (fully replicated / single-device) state."""
    import numpy as np

    return TileState(*[np.asarray(leaf) for leaf in snap])


def resize_state(st: TileState, new_capacity: int,
                 n_shards: int = 1) -> TileState:
    """Host-side resize of a snapshot to a new per-shard capacity.

    Growth pads each shard block's tail with EMPTY rows — EMPTY sorts
    last under the fold's compressed key, so per-shard sortedness (the
    slab invariant) is preserved.  Shrinking is allowed only when every
    shard's live rows fit (live rows are a sorted prefix); otherwise
    raises, because dropping aggregates silently is never acceptable.
    """
    import numpy as np

    rows = st.key_hi.shape[0]
    if rows % n_shards:
        raise ValueError(f"{rows} rows not divisible by {n_shards} shards")
    old_cap = rows // n_shards
    if new_capacity == old_cap:
        return st
    key_hi = np.asarray(st.key_hi).reshape(n_shards, old_cap)
    if new_capacity < old_cap:
        live = (key_hi != np.uint32(EMPTY_KEY_HI)).sum(axis=1)
        if int(live.max(initial=0)) > new_capacity:
            raise ValueError(
                f"cannot shrink to {new_capacity}: a shard holds "
                f"{int(live.max())} live groups")
    fills = {
        "key_hi": np.uint32(EMPTY_KEY_HI),
        "key_lo": np.uint32(EMPTY_KEY_LO),
        "key_ws": np.int32(EMPTY_WS),
    }
    out = []
    for name, leaf in zip(TileState._fields, st):
        a = np.asarray(leaf)
        shard_shape = (n_shards, old_cap) + a.shape[1:]
        a = a.reshape(shard_shape)
        new = np.full((n_shards, new_capacity) + a.shape[2:],
                      fills.get(name, a.dtype.type(0)), a.dtype)
        keep = min(old_cap, new_capacity)
        new[:, :keep] = a[:, :keep]
        out.append(new.reshape((n_shards * new_capacity,) + a.shape[2:]))
    return TileState(*out)
