"""Single-device aggregator with the same host API as parallel.ShardedAggregator.

Used when one chip is enough (the bench's single-chip runs) — skips the
all_to_all exchange entirely; the state slab lives on the default device.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp


from heatmap_tpu.engine.state import (TileState, donate_state_argnums,
                                      init_state)
from heatmap_tpu.engine.step import (AggParams, aggregate_batch, pack_emit,
                                     ride_stats)


class SingleAggregator:
    n_shards = 1

    def __init__(self, params: AggParams, capacity: int, batch_size: int,
                 hist_bins: int = 0):
        self.params = params
        self.capacity_per_shard = capacity
        self.batch_size = batch_size
        self.state: TileState = init_state(capacity, hist_bins)

        def _step(state, lat, lng, speed, ts, valid, cutoff):
            return aggregate_batch(state, lat, lng, speed, ts, valid, cutoff,
                                   self.params)

        self._step = jax.jit(_step,
                     donate_argnums=donate_state_argnums())

        def _step_packed(state, lat, lng, speed, ts, valid, cutoff):
            state, emit, stats = aggregate_batch(
                state, lat, lng, speed, ts, valid, cutoff, self.params
            )
            return state, pack_emit(emit, self.params.speed_hist_max), stats

        self._step_packed = jax.jit(
            _step_packed, donate_argnums=donate_state_argnums())

        def _step_ride(state, lat, lng, speed, ts, valid, cutoff):
            state, emit, stats = aggregate_batch(
                state, lat, lng, speed, ts, valid, cutoff, self.params
            )
            return state, ride_stats(
                pack_emit(emit, self.params.speed_hist_max), stats)

        self._step_ride = jax.jit(
            _step_ride, donate_argnums=donate_state_argnums())

    def step(self, lat_rad, lng_rad, speed, ts, valid, watermark_cutoff):
        self.state, emit, stats = self._step(
            self.state,
            jnp.asarray(lat_rad), jnp.asarray(lng_rad), jnp.asarray(speed),
            jnp.asarray(ts), jnp.asarray(valid),
            jnp.int32(watermark_cutoff),
        )
        # align emit scalar shapes with the sharded aggregator's (D,) form
        emit = emit._replace(n_emitted=emit.n_emitted[None],
                             overflowed=emit.overflowed[None])
        return emit, stats

    def step_packed(self, lat_rad, lng_rad, speed, ts, valid, watermark_cutoff):
        """Single-transfer variant: returns (packed_emit_device, stats_device).

        The caller pulls the packed matrix with one device_get (see
        engine.step.pack_emit) — the low-overhead path for remote-attached
        devices; the bench hot loop uses it."""
        self.state, packed, stats = self._step_packed(
            self.state,
            jnp.asarray(lat_rad), jnp.asarray(lng_rad), jnp.asarray(speed),
            jnp.asarray(ts), jnp.asarray(valid),
            jnp.int32(watermark_cutoff),
        )
        return packed, stats

    def step_packed_ride(self, lat_rad, lng_rad, speed, ts, valid,
                         watermark_cutoff):
        """Like step_packed, but the StepStats ride the packed head row
        (engine.step.ride_stats) so the WHOLE batch output is one device
        array — the shape engine.step.EmitRing accumulates and
        ``stats_from_packed`` decodes (parity with MultiAggregator /
        ShardedAggregator).  Returns the (E+1, 13) packed matrix on
        device."""
        self.state, packed = self._step_ride(
            self.state,
            jnp.asarray(lat_rad), jnp.asarray(lng_rad), jnp.asarray(speed),
            jnp.asarray(ts), jnp.asarray(valid),
            jnp.int32(watermark_cutoff),
        )
        return packed

    def emit_to_host(self, emit) -> dict:
        """Emit leaves as host numpy (API parity with ShardedAggregator)."""
        import numpy as np

        e = jax.device_get(emit)
        return {
            "key_hi": e.key_hi, "key_lo": e.key_lo, "key_ws": e.key_ws,
            "count": e.count, "sum_speed": e.sum_speed,
            "sum_speed2": e.sum_speed2, "sum_lat": e.sum_lat,
            "sum_lon": e.sum_lon, "anchor_speed": e.anchor_speed,
            "anchor_lat": e.anchor_lat, "anchor_lon": e.anchor_lon,
            "valid": e.valid,
            "hist": np.asarray(e.hist) if e.hist.shape[1] else None,
        }

    # --- checkpoint interface (runtime._checkpoint / _maybe_resume) --------

    def snapshot(self) -> TileState:
        """Host-side copy of the state slab (synchronous; no device copy)."""
        from heatmap_tpu.engine.state import to_host

        return to_host(self.state)

    def device_snapshot(self) -> TileState:
        """On-device copy with fresh buffers (async dispatch) — safe to
        hold across later (buffer-donating) steps and pull off-thread."""
        from heatmap_tpu.engine.state import device_copy

        return device_copy(self.state)

    @staticmethod
    def to_host(snap: TileState) -> TileState:
        from heatmap_tpu.engine.state import to_host

        return to_host(snap)

    def restore(self, st: TileState) -> None:
        """Install a snapshot (shape-checked; raises on config mismatch)."""
        self._check_restore_shapes(st)
        self.state = TileState(*st)

    def _check_restore_shapes(self, st: TileState) -> None:
        want = (self.state.key_hi.shape, self.state.hist.shape)
        got = (st.key_hi.shape, st.hist.shape)
        if want != got:
            raise ValueError(f"state shape {got} != configured {want}")
