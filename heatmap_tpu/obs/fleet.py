"""Fleet observatory: federate per-member snapshots into fleet surfaces.

Every remaining scale-out direction (replicated serve fleet, sharded
runtime, shard fan-in — ROADMAP items 1 and 5) runs N processes, and the
PR 1/3/5 observability stack is process-local: registries, lineage,
/healthz, and the flight recorder all stop at the process boundary.
GeoFlink and LMStream (PAPERS.md) both treat cluster-wide latency and
throughput accounting as the PREREQUISITE for partitioned scaling
decisions — so the fleet view ships before anything shards.

Members publish full snapshots next to the supervisor channel
(``obs/xproc.py`` ``publish_member_snapshot``: registry exposition
text + freshness summary + /healthz verdict + compact lineage tail).
:class:`FleetAggregator` merges them into three surfaces served by any
process holding the channel path (``serve/api.py``):

- ``/fleet/metrics`` — every member's series re-emitted with a
  ``proc="<tag>"`` label, plus fleet rollups: counters SUMMED across
  members (``heatmap_fleet_<name>``), watermark gauges MAXED, and
  fleet-level interpolated quantiles from the merged histogram buckets
  (``heatmap_fleet_event_age_p50_s`` …).  Legacy freshness-only child
  files keep surfacing as the unchanged ``heatmap_child_*`` gauges.
- ``/fleet/healthz`` — aggregate SLO verdict: any member degraded/down
  degrades/downs the fleet, and a STALE or VANISHED member (snapshot
  older than ``HEATMAP_FLEET_MAX_AGE_S``, corrupt, clock-skewed, or
  deleted after having been seen) degrades the fleet NAMING the member
  — a dead shard must never read as a healthy fleet.
- ``/fleet/freshness`` — the cross-process event-age decomposition:
  per-batch lineage records are stitched BY LINEAGE ID across members
  (a runtime shard contributes poll→fold→ring→sink stages, the member
  applying the materialized view contributes ``view_apply``), and the
  merged stages telescope conservation-exactly against the final
  stamp, the same invariant PR 3 pinned in-process.

All reads are hardened (``members_from``): a torn member file or a
skewed clock is skipped and counted (``heatmap_fleet_stale_members``),
never raised.
"""

from __future__ import annotations

import re
import threading
import time

from heatmap_tpu.obs.lineage import STAGES
from heatmap_tpu.obs.registry import _escape_label, _fmt
from heatmap_tpu.obs.xproc import (
    FRESHNESS_FIELDS,
    SupervisorChannel,
    child_freshness_from,
    members_from,
    read_episode,
)

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# gauge families that SUM across members (rates/depths are additive even
# though they are point-in-time); every other gauge stays per-member
# unless its name says watermark (maxed — a fleet high-water is the
# worst member's high-water)
_SUM_GAUGES = frozenset({
    "heatmap_events_per_sec", "heatmap_sink_queue_depth",
    "heatmap_emit_ring_pending", "heatmap_serve_sse_clients",
})

# The fleet's OWN metric families (everything else at /fleet/metrics is
# a member's series re-labeled, or a ``heatmap_fleet_<name>`` rollup of
# one).  This table is the single source for the exposition HELP/TYPE
# lines AND the tools/check_metrics_docs.py docs gate — every row must
# have an ARCHITECTURE.md table row.
FAMILIES = (
    ("heatmap_fleet_members", "gauge",
     "member snapshots currently fresh on the channel"),
    ("heatmap_fleet_stale_members", "gauge",
     "member snapshots skipped this scrape: stale past "
     "HEATMAP_FLEET_MAX_AGE_S, torn/corrupt, clock-skewed, or vanished "
     "after having been seen"),
    ("heatmap_fleet_member_up", "gauge",
     "1 per fresh member (with role=), 0 per skipped member"),
    ("heatmap_fleet_member_age_seconds", "gauge",
     "age of each member's latest snapshot publish"),
    ("heatmap_fleet_member_event_age_p50_s", "gauge",
     "each member's recent end-to-end event-age p50, from its "
     "published freshness summary"),
    ("heatmap_fleet_member_event_age_p99_s", "gauge",
     "each member's recent end-to-end event-age p99, from its "
     "published freshness summary"),
    ("heatmap_fleet_member_delivered_age_p50_s", "gauge",
     "each member's recent delivered-age p50 (event occurrence to "
     "subscriber socket write), from its published delivery block"),
    ("heatmap_fleet_member_delivered_age_p99_s", "gauge",
     "each member's recent delivered-age p99, from its published "
     "delivery block"),
    ("heatmap_fleet_event_age_p50_s", "gauge",
     "fleet-level interpolated event-age p50 over the members' MERGED "
     "cumulative histogram buckets (per-member p50s do not average)"),
    ("heatmap_fleet_event_age_p99_s", "gauge",
     "fleet-level interpolated event-age p99 over the merged buckets"),
    ("heatmap_fleet_batch_latency_p50_s", "gauge",
     "fleet-level interpolated batch-latency p50 over the merged "
     "buckets"),
)
_FAMILY_META = {name: (mtype, help_) for name, mtype, help_ in FAMILIES}


def parse_exposition(text: str):
    """Minimal Prometheus text parse: (types {name: type}, samples
    [(series, label_block, value)]).  Unparseable lines are skipped —
    one member's garbage must not break the federation."""
    types: dict = {}
    samples: list = []
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) == 4:
                types[parts[2]] = parts[3]
            continue
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            v = float(m.group(3))
        except ValueError:
            continue
        samples.append((m.group(1), m.group(2) or "", v))
    return types, samples


def _family_of(series: str, types: dict) -> str:
    """Histogram sample names fold back to their family name."""
    for suffix in ("_bucket", "_sum", "_count"):
        base = series[: -len(suffix)] if series.endswith(suffix) else None
        if base and types.get(base) == "histogram":
            return base
    return series


def interp_quantile(bucket_cums: dict, q: float) -> float | None:
    """Interpolated quantile over merged cumulative buckets
    ({le_float: cumulative_count}); None on an empty histogram.  The
    open-ended +Inf bucket reports the last finite bound (the honest
    floor — same rule as tools/obs_top.py)."""
    bounds = sorted(bucket_cums)
    if not bounds:
        return None
    total = bucket_cums[bounds[-1]]
    if total <= 0:
        return None
    target = q * total
    lo = 0.0
    prev_cum = 0.0
    for le in bounds:
        cum = max(prev_cum, bucket_cums[le])
        if cum >= target and cum > prev_cum:
            if le == float("inf"):
                return lo
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + frac * (le - lo)
        prev_cum = cum
        if le != float("inf"):
            lo = le
    return lo


def child_freshness_lines(channel_path: str | None) -> list:
    """Legacy per-child freshness summaries -> the UNCHANGED
    ``heatmap_child_<key>{child=}`` gauges (the PR 3 wire surface; old
    freshness-only children keep reporting next to the new member
    snapshots)."""
    kids = child_freshness_from(channel_path)
    if not kids:
        return []
    lines = []
    for k in FRESHNESS_FIELDS:
        samples = [
            (tag, d[k]) for tag, d in sorted(kids.items())
            if isinstance(d.get(k), (int, float))]
        if not samples:
            continue
        lines.append(f"# TYPE heatmap_child_{k} gauge")
        for tag, v in samples:
            lines.append(
                f'heatmap_child_{k}{{child="{_escape_label(tag)}"}} '
                f"{_fmt(v)}")
    return lines


class FleetAggregator:
    """Merges the channel's member snapshots into the fleet surfaces.

    One instance per serving process: it remembers which member tags it
    has seen, so a member whose snapshot file VANISHES (deleted, lost
    volume) degrades /fleet/healthz instead of silently shrinking the
    fleet."""

    def __init__(self, channel_path: str, max_age_s: float | None = None,
                 clock=time.time):
        self.path = channel_path
        self.max_age_s = max_age_s
        self.clock = clock
        self._lock = threading.Lock()
        self._seen: set = set()
        # per-(member, series, labels) monotonic-counter state: a member
        # restart resets its cumulative counters to zero, and a naive
        # fleet sum would DROP by the lost total — poisoning every rate
        # computed off the rollup.  (last_raw, carried_base): the rollup
        # reports base + raw, and a reset folds the pre-restart total
        # into the base so the fleet sum never goes backwards.
        self._ctr_state: dict = {}

    def _monotonic(self, tag: str, series: str, labels: str,
                   v: float) -> float:
        """Reset-aware cumulative value for one member counter series:
        identity while the counter grows, resumes from the reset point
        (prior total carried forward) after a member restart."""
        k = (tag, series, labels)
        with self._lock:
            prev, base = self._ctr_state.get(k, (v, 0.0))
            if v < prev:  # member restarted: counter came back at ~0
                base += prev
            self._ctr_state[k] = (v, base)
            if len(self._ctr_state) > 65536:  # bounded against churn
                self._ctr_state.pop(next(iter(self._ctr_state)))
        return base + v

    # ------------------------------------------------------------ collect
    def collect(self) -> tuple[dict, dict]:
        """({tag: snapshot}, {tag: reason-not-counted}) with vanished
        members folded into the second dict.  A member that published a
        departure tombstone (clean close, ``left=True``) appears in
        NEITHER: it left on purpose, so it must not degrade the fleet
        as stale — and it is forgotten here, so it cannot resurface as
        "vanished" either."""
        members, skipped = members_from(self.path,
                                        max_age_s=self.max_age_s)
        left = [tag for tag, why in skipped.items() if why == "left"]
        for tag in left:
            del skipped[tag]
        with self._lock:
            for tag in left:
                self._seen.discard(tag)
            for tag in list(self._seen)[: max(0, len(self._seen) - 256)]:
                self._seen.discard(tag)  # bounded against tag churn
            self._seen.update(members)
            self._seen.update(skipped)
            for tag in self._seen - set(members) - set(skipped):
                skipped[tag] = "vanished"
        return members, skipped

    # ------------------------------------------------------------ metrics
    def metrics_text(self) -> str:
        """The federation exposition: fleet gauges, per-member series
        with an injected ``proc`` label, rollups, and the legacy
        ``heatmap_child_*`` gauges."""
        members, skipped = self.collect()
        out: list = []
        typed: set = set()

        def own(name: str) -> None:
            """HELP/TYPE lines for one of the fleet's own families
            (FAMILIES), once per exposition."""
            if name not in typed:
                typed.add(name)
                mtype, help_ = _FAMILY_META[name]
                out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {mtype}")

        own("heatmap_fleet_members")
        out.append(f"heatmap_fleet_members {len(members)}")
        own("heatmap_fleet_stale_members")
        out.append(f"heatmap_fleet_stale_members {len(skipped)}")
        counter_sums: dict = {}     # (family, labels) -> sum
        gauge_maxes: dict = {}      # (family, labels) -> max
        gauge_sums: dict = {}       # (family, labels) -> sum
        age_buckets: dict = {}      # le -> cum (event_age, bound=mean)
        latency_buckets: dict = {}  # le -> cum (batch_latency)
        up_lines: list = []
        age_lines: list = []
        fresh_lines: dict = {
            "heatmap_fleet_member_event_age_p50_s": [],
            "heatmap_fleet_member_event_age_p99_s": [],
            "heatmap_fleet_member_delivered_age_p50_s": [],
            "heatmap_fleet_member_delivered_age_p99_s": [],
        }
        # per-member series regrouped BY FAMILY: the exposition format
        # requires one contiguous block per metric name, and with N
        # members every member contributes samples to the same families
        member_fams: dict = {}      # fam -> {"type": t, "lines": [...]}
        for tag in sorted(members):
            snap = members[tag]
            types, samples = parse_exposition(
                str(snap.get("metrics_text", "")))
            up_lbl = f'proc="{_escape_label(tag)}"'
            role = _escape_label(str(snap.get("role", "?")))
            up_lines.append(f'heatmap_fleet_member_up{{{up_lbl},'
                            f'role="{role}"}} 1')
            upd = snap.get("updated_unix", 0.0)
            age_lines.append(
                f"heatmap_fleet_member_age_seconds{{{up_lbl}}} "
                f"{_fmt(max(0.0, round(self.clock() - upd, 3)))}")
            # per-member freshness gauges from the published summary —
            # the rows obs_top --fleet renders without histogram math
            fresh = snap.get("freshness") or {}
            for key, fam in (("event_age_p50_s",
                              "heatmap_fleet_member_event_age_p50_s"),
                             ("event_age_p99_s",
                              "heatmap_fleet_member_event_age_p99_s")):
                v = fresh.get(key)
                if isinstance(v, (int, float)):
                    fresh_lines[fam].append(
                        f"{fam}{{{up_lbl}}} {_fmt(v)}")
            # per-member delivered-age gauges from the published
            # delivery block — same shape as the freshness pair
            delv = snap.get("delivery") or {}
            for key, fam in (
                    ("age_p50_s",
                     "heatmap_fleet_member_delivered_age_p50_s"),
                    ("age_p99_s",
                     "heatmap_fleet_member_delivered_age_p99_s")):
                v = delv.get(key)
                if isinstance(v, (int, float)):
                    fresh_lines[fam].append(
                        f"{fam}{{{up_lbl}}} {_fmt(v)}")
            for series, labels, v in samples:
                fam = _family_of(series, types)
                ftype = types.get(fam, "untyped")
                lbl = up_lbl + ("," + labels if labels else "")
                group = member_fams.setdefault(
                    fam, {"type": ftype, "lines": []})
                group["lines"].append(f"{series}{{{lbl}}} {_fmt(v)}")
                # ---- rollups ----------------------------------------
                key = (fam, labels)
                if ftype == "counter":
                    counter_sums[key] = (counter_sums.get(key, 0.0)
                                         + self._monotonic(
                                             tag, series, labels, v))
                elif ftype == "gauge":
                    if fam in _SUM_GAUGES:
                        gauge_sums[key] = gauge_sums.get(key, 0.0) + v
                    elif "watermark" in fam:
                        gauge_maxes[key] = max(
                            gauge_maxes.get(key, float("-inf")), v)
                elif ftype == "histogram" and series == fam + "_bucket":
                    pairs = dict(_LABEL_RE.findall(labels))
                    le_raw = pairs.pop("le", None)
                    if le_raw is None:
                        continue
                    le = (float("inf") if le_raw == "+Inf"
                          else float(le_raw))
                    if (fam == "heatmap_event_age_seconds"
                            and pairs.get("bound") == "mean"):
                        age_buckets[le] = age_buckets.get(le, 0.0) + v
                    elif fam == "heatmap_batch_latency_seconds":
                        latency_buckets[le] = (
                            latency_buckets.get(le, 0.0) + v)
        for tag in sorted(skipped):
            up_lines.append(f'heatmap_fleet_member_up{{proc='
                            f'"{_escape_label(tag)}",role="?"}} 0')
        if up_lines:
            own("heatmap_fleet_member_up")
            out.extend(up_lines)
        if age_lines:
            own("heatmap_fleet_member_age_seconds")
            out.extend(age_lines)
        for fam, lines in fresh_lines.items():
            if lines:
                own(fam)
                out.extend(lines)
        for fam, group in member_fams.items():
            if group["type"] != "untyped" and fam not in typed:
                typed.add(fam)
                out.append(f"# TYPE {fam} {group['type']}")
            out.extend(group["lines"])
        # fleet rollups: counters summed, watermarks maxed, additive
        # gauges summed — each under its own heatmap_fleet_<name>
        for (fam, labels), v in sorted(counter_sums.items()):
            self._rollup(out, typed, fam, labels, v, "counter")
        for (fam, labels), v in sorted(gauge_sums.items()):
            self._rollup(out, typed, fam, labels, v, "gauge")
        for (fam, labels), v in sorted(gauge_maxes.items()):
            self._rollup(out, typed, fam, labels, v, "gauge")
        # fleet-level interpolated quantiles over the MERGED buckets —
        # the per-member p50s do not average into a fleet p50; the
        # summed cumulative histograms do interpolate into one
        for name, buckets, qs in (
                ("heatmap_fleet_event_age", age_buckets,
                 ((0.5, "p50"), (0.99, "p99"))),
                ("heatmap_fleet_batch_latency", latency_buckets,
                 ((0.5, "p50"),))):
            for q, qname in qs:
                val = interp_quantile(buckets, q)
                if val is None:
                    continue
                own(f"{name}_{qname}_s")
                out.append(f"{name}_{qname}_s {_fmt(round(val, 6))}")
        out.extend(child_freshness_lines(self.path))
        return "\n".join(out) + "\n"

    @staticmethod
    def _rollup(out: list, typed: set, fam: str, labels: str, v: float,
                mtype: str) -> None:
        name = "heatmap_fleet_" + fam.removeprefix("heatmap_")
        if name not in typed:
            typed.add(name)
            out.append(f"# TYPE {name} {mtype}")
        suffix = "{" + labels + "}" if labels else ""
        out.append(f"{name}{suffix} {_fmt(v)}")

    # ------------------------------------------------------------ healthz
    def healthz(self) -> tuple[dict, bool]:
        """(payload, down): the aggregate fleet SLO verdict.  Any
        member degraded → fleet degraded; any member down → fleet down;
        a stale/corrupt/skewed/vanished member degrades NAMING it."""
        members, skipped = self.collect()
        checks: dict = {}
        degraded = down = False
        for tag, reason in sorted(skipped.items()):
            checks[f"member_{tag}"] = {"value": reason, "ok": False}
            degraded = True
        for tag in sorted(members):
            hz = members[tag].get("healthz") or {}
            status = hz.get("status", "ok")
            ok = status == "ok"
            failing = [k for k, c in (hz.get("checks") or {}).items()
                       if isinstance(c, dict) and not c.get("ok", True)]
            checks[f"member_{tag}"] = {
                "value": status, "ok": ok,
                **({"failing": failing} if failing else {})}
            degraded |= not ok
            down |= status == "down"
        chan = SupervisorChannel.metrics_from(self.path)
        if chan.get("gave_up"):
            checks["supervisor"] = {"value": "gave_up", "ok": False}
            down = True
        payload = {
            "ok": not down,
            "status": ("down" if down
                       else "degraded" if degraded else "ok"),
            "checks": checks,
            "members": sorted(members),
            "stale_members": sorted(skipped),
        }
        ep = read_episode(self.path)
        if ep:
            payload["episode"] = ep
        return payload, down

    # ---------------------------------------------------------- freshness
    def freshness(self, n: int = 32) -> dict:
        """The cross-process event-age decomposition: every member's
        compact lineage contributions stitched by lineage id.  Each
        merged record carries the union of stage contributions, the
        total age to the LAST stamp any member reported, and the
        conservation residual |age - sum(stages)| — exactly 0 when the
        stamps telescope (the PR 3 invariant, now across processes)."""
        members, skipped = self.collect()
        by_lid: dict = {}
        for tag in sorted(members):
            for rec in members[tag].get("lineage") or []:
                if not isinstance(rec, dict):
                    continue
                lid = rec.get("lid")
                stages = rec.get("stages")
                if not lid or not isinstance(stages, dict):
                    continue
                agg = by_lid.setdefault(lid, {
                    "lid": lid, "procs": [], "stages": {},
                    "ev_mean_ts": None, "t_last": None,
                    "n_events": rec.get("n_events")})
                agg["procs"].append(tag)
                for k, v in stages.items():
                    if isinstance(v, (int, float)):
                        agg["stages"][k] = v
                ts = rec.get("ev_mean_ts")
                if isinstance(ts, (int, float)):
                    agg["ev_mean_ts"] = (ts if agg["ev_mean_ts"] is None
                                         else min(agg["ev_mean_ts"], ts))
                tl = rec.get("t_last")
                if isinstance(tl, (int, float)):
                    agg["t_last"] = (tl if agg["t_last"] is None
                                     else max(agg["t_last"], tl))
        records = []
        for agg in by_lid.values():
            if agg["ev_mean_ts"] is None or agg["t_last"] is None:
                continue
            agg["age_s"] = agg["t_last"] - agg["ev_mean_ts"]
            agg["residual_s"] = agg["age_s"] - sum(agg["stages"].values())
            records.append(agg)
        records.sort(key=lambda r: r["t_last"], reverse=True)
        records = records[: max(0, int(n))]
        summary: dict = {}
        for stage in STAGES:
            vals = sorted(r["stages"][stage] for r in records
                          if stage in r["stages"])
            if vals:
                summary[f"{stage}_p50_s"] = round(
                    vals[min(len(vals) - 1, len(vals) // 2)], 6)
        if records:
            summary["max_abs_residual_s"] = round(
                max(abs(r["residual_s"]) for r in records), 6)
        return {
            "records": records,
            "stage_order": list(STAGES),
            "summary": summary,
            "members": sorted(members),
            "stale_members": sorted(skipped),
        }

    # ----------------------------------------------------------- delivery
    def delivery(self) -> tuple[dict, bool]:
        """``/fleet/delivery``: every member's delivery-lineage block
        (obs.delivery ``member_block``: delivered-age quantiles,
        per-stage p50s, worst stage, residual bound) rolled up, with
        the WORST replica named by delivered-age p50 — the row an
        operator pages on.  A stale/vanished member degrades the
        surface NAMING it (second return True → the endpoint serves
        503): a SIGKILLed replica must never read as a healthy delivery
        fleet, and the active episode (obs.xproc broadcast) rides along
        so the degradation correlates with the incident's flight
        recorder dumps."""
        from heatmap_tpu.obs.delivery import (
            CROSS_HOST_STAGES,
            DELIVERY_STAGES,
        )

        members, skipped = self.collect()
        per: dict = {}
        degraded = bool(skipped)
        worst: tuple | None = None  # (age_p50_s, tag)
        reporting = 0
        for tag, reason in sorted(skipped.items()):
            per[tag] = {"skipped": reason}
        for tag in sorted(members):
            block = members[tag].get("delivery")
            if not isinstance(block, dict) or not block.get("count"):
                # a member without subscribers (or with the knob off)
                # is absent, not degraded — delivery is per-replica
                per[tag] = {"count": 0}
                continue
            per[tag] = block
            reporting += 1
            v = block.get("age_p50_s")
            if isinstance(v, (int, float)) and (worst is None
                                                or v > worst[0]):
                worst = (float(v), tag)
        payload = {
            "ok": not degraded,
            "members": per,
            "member_tags": sorted(members),
            "stale_members": sorted(skipped),
            "reporting": reporting,
            "stage_order": list(DELIVERY_STAGES),
            "cross_host": list(CROSS_HOST_STAGES),
        }
        if worst is not None:
            payload["worst"] = {
                "proc": worst[1],
                "age_p50_s": round(worst[0], 6),
                "worst_stage": (per[worst[1]].get("worst_stage")
                                if isinstance(per[worst[1]], dict)
                                else None),
            }
        ep = read_episode(self.path)
        if ep:
            payload["episode"] = ep
        return payload, degraded

    # -------------------------------------------------------------- audit
    def audit(self) -> dict:
        """``/fleet/audit``: member integrity ledgers stitched
        cross-process (summed counts re-checked against the same
        conservation identities) + the per-window shard-digest combine
        against the merged-view digest — see :func:`fleet_audit`."""
        members, skipped = self.collect()
        out = fleet_audit(members)
        out["member_tags"] = sorted(members)
        out["stale_members"] = sorted(skipped)
        return out

    def quality(self) -> dict:
        """``/fleet/quality``: member inference-quality blocks stitched
        cross-process (scorecard ledgers summed + the conservation
        identity re-checked, calibration coverage update-weighted) with
        the worst shard named — see :func:`fleet_quality`."""
        members, skipped = self.collect()
        out = fleet_quality(members)
        out["member_tags"] = sorted(members)
        out["stale_members"] = sorted(skipped)
        return out


def _hex_digest(v) -> int | None:
    try:
        return int(str(v), 16)
    except (TypeError, ValueError):
        return None


def _member_audit_summary(blk: dict) -> dict:
    verify = blk.get("verify") or {}
    res = blk.get("residuals") or {}
    worst = None
    numeric = {b: r for b, r in res.items()
               if isinstance(r, (int, float))}
    if numeric:
        b = max(numeric, key=lambda k: abs(numeric[k]))
        if numeric[b]:
            worst = {"boundary": b, "residual": numeric[b]}
    return {
        "ledger": blk.get("ledger") or {},
        "residuals": res,
        "worst_boundary": worst,
        "verify": verify,
        "repl": blk.get("repl") or {},
    }


def fleet_audit(members: dict) -> dict:
    """The cross-process integrity stitch behind ``/fleet/audit``
    (obs/audit.py is the per-member half): member conservation ledgers
    are SUMMED and re-checked against the same boundary identities
    (`residuals_from_counts` — the fleet's books must balance exactly
    as each member's do), and every member's per-shard window digests
    are XOR-combined per (grid, windowStart) against the merged-view
    owner's published view digest (disjoint cell spaces -> the combine
    must be exact).  A window whose combine mismatches names the
    member set that contributed — the production form of the 1-vs-N
    differential test."""
    from heatmap_tpu.obs.audit import combine_digests, \
        residuals_from_counts

    per_member: dict = {}
    totals: dict = {}
    view_digests: dict = {}   # (grid, ws) -> (hex, owner tag)
    shard_digests: dict = {}  # (grid, ws) -> [(tag, shard, int)]
    mismatches = 0
    has_view = False
    for tag in sorted(members):
        blk = members[tag].get("audit")
        if not isinstance(blk, dict):
            continue
        per_member[tag] = _member_audit_summary(blk)
        for stage, v in (blk.get("ledger") or {}).items():
            if isinstance(v, (int, float)):
                totals[stage] = totals.get(stage, 0) + int(v)
        verify = blk.get("verify") or {}
        if isinstance(verify.get("mismatches"), (int, float)):
            mismatches += int(verify["mismatches"])
        digests = blk.get("digests") or {}
        view = digests.get("view")
        if isinstance(view, dict) and view:
            has_view = True
            for grid, per_ws in view.items():
                for ws, d in (per_ws or {}).items():
                    view_digests[(grid, ws)] = (
                        (d or {}).get("digest"), tag)
        for label, table in (digests.get("shard") or {}).items():
            for grid, per_ws in (table or {}).items():
                for ws, d in (per_ws or {}).items():
                    h = _hex_digest((d or {}).get("digest"))
                    if h is not None:
                        shard_digests.setdefault(
                            (grid, ws), []).append((tag, label, h))
    # per-window combine verdicts, for every window the merged view
    # holds: XOR over every contributing shard must equal the view —
    # a shard whose merge was skipped (or double-applied) breaks it
    combine: list = []
    combine_mismatches = 0
    if not shard_digests:
        view_digests = {}  # no emitting shards on the channel: nothing
        #                    to combine (serve-only fleets)
    for (grid, ws), (view_hex, owner) in sorted(view_digests.items()):
        want = _hex_digest(view_hex)
        contrib = shard_digests.get((grid, ws), [])
        if not contrib:
            # no shard emitted into this window THIS boot (a restart's
            # store-seeded window, or a pre-boot window) — unverifiable,
            # NOT a mismatch: flagging it would false-alarm on every
            # restart against a durable store.  A SKIPPED shard merge
            # is still caught: its surviving peers' contributions exist
            # and the XOR below comes up short.
            combine.append({
                "grid": grid, "ws": int(ws), "view": view_hex,
                "ok": None, "skipped": "no emitting shard this boot",
                "view_owner": owner, "shards": []})
            continue
        got = combine_digests(h for _t, _l, h in contrib)
        ok = want is not None and got == want
        if not ok:
            combine_mismatches += 1
        combine.append({
            "grid": grid, "ws": int(ws), "view": view_hex,
            "combined": format(got, "016x"), "ok": ok,
            "view_owner": owner,
            "shards": sorted(f"{t}/{lbl}" for t, lbl, _h in contrib),
        })
    residuals = residuals_from_counts(totals, has_view=has_view)
    worst = None
    if residuals:
        b = max(residuals, key=lambda k: abs(residuals[k]))
        if residuals[b]:
            worst = {"boundary": b, "residual": residuals[b]}
    return {
        "members": per_member,
        "ledger": totals,
        "residuals": residuals,
        "worst_boundary": worst,
        "digest_mismatches": mismatches,
        "combine": combine,
        "combine_mismatches": combine_mismatches,
        "ok": (mismatches == 0 and combine_mismatches == 0),
    }


def fleet_quality(members: dict) -> dict:
    """The cross-process inference-quality stitch behind
    ``/fleet/quality`` (obs/quality.py is the per-member half):
    scorecard ledgers are plain-summed and the summed conservation
    identity re-checked (registered == scored + expired_unscorable +
    pending must hold for the fleet exactly as for each member),
    calibration coverage is update-weighted across shards, and the
    WORST shard is named — worst calibration drift (band error) first,
    worst live skill as the tiebreak — so a fleet-level drift page
    starts with the shard to look at."""
    per_member: dict = {}
    ledger = {"registered": 0, "scored": 0, "expired_unscorable": 0,
              "pending": 0}
    upd_total = 0
    inside_total = 0
    anom: dict = {}
    worst = None          # (band_err desc, skill asc) -> naming block
    worst_key = None
    for tag in sorted(members):
        blk = members[tag].get("quality")
        if not isinstance(blk, dict):
            continue
        cards = blk.get("scorecards") or {}
        nis = blk.get("nis") or {}
        skill = blk.get("skill") or {}
        per_member[tag] = {
            "scorecards": cards,
            "nis": nis,
            "skill": skill,
            "anomaly_rate": blk.get("anomaly_rate") or {},
            "table": blk.get("table") or {},
        }
        for k in ledger:
            v = cards.get(k)
            if isinstance(v, (int, float)):
                ledger[k] += int(v)
        upd = nis.get("updates")
        cov = nis.get("coverage")
        if isinstance(upd, (int, float)) and isinstance(
                cov, (int, float)):
            upd_total += int(upd)
            inside_total += int(round(cov * upd))
        for r, v in (blk.get("anomaly_rate") or {}).items():
            if isinstance(v, (int, float)):
                anom[r] = round(anom.get(r, 0.0) + v, 4)
        band_err = nis.get("band_error")
        band_err = float(band_err) if isinstance(
            band_err, (int, float)) else 0.0
        skills = [v for v in skill.values()
                  if isinstance(v, (int, float))]
        min_skill = min(skills) if skills else None
        key = (-band_err, min_skill if min_skill is not None
               else float("inf"))
        if worst_key is None or key < worst_key:
            worst_key = key
            worst = {"tag": tag, "band_error": band_err,
                     "min_skill": min_skill}
            if skills:
                gh = min((k for k, v in skill.items()
                          if isinstance(v, (int, float))),
                         key=lambda k: skill[k])
                grid, _, h = gh.partition("|")
                worst.update({"grid": grid, "h": h})
    ident_ok = (ledger["registered"] == ledger["scored"]
                + ledger["expired_unscorable"] + ledger["pending"])
    return {
        "members": per_member,
        "scorecards": {**ledger, "ok": ident_ok},
        "nis": {
            "updates": upd_total,
            "coverage": (round(inside_total / upd_total, 4)
                         if upd_total else None),
        },
        "anomaly_rate": anom,
        "worst_shard": worst,
        "ok": ident_ok,
    }


def fleet_stamp(rate: float | None = None,
                role: str = "runtime") -> dict:
    """The ``fleet`` artifact block bench.py / tools/bench_serve.py
    stamp: how many members were live on the supervisor channel during
    the run (1 = standalone) and the headline normalized per member —
    so when PRs 7+ shard the runtime, their artifacts compare
    like-for-like against today's single-process baselines instead of
    conflating fleet width with per-member speed.

    Only members of ``role`` count toward the divisor: the headline is
    produced by the runtime shards (or, for bench_serve, the serve
    workers) — the supervisor and other sidecar members on the same
    channel do no data-path work, and dividing by them would corrupt
    the per-member baseline the stamp exists to protect."""
    import os

    from heatmap_tpu.obs.xproc import ENV_CHANNEL

    members, _skipped = members_from(os.environ.get(ENV_CHANNEL))
    workers = sorted(t for t, d in members.items()
                     if d.get("role") == role)
    n = max(1, len(workers))
    out: dict = {"members": n}
    if workers:
        out["member_tags"] = workers
    if isinstance(rate, (int, float)):
        out["per_member_rate"] = round(rate / n, 1)
    return {"fleet": out}


def repl_stamp() -> dict:
    """The ``repl`` artifact block bench.py / tools/bench_serve.py /
    tools/e2e_rate.py stamp when a REPLICATED serve fleet is attached
    to the channel: how many members are following a replication feed
    (their snapshots expose ``heatmap_repl_seq_lag``) and the worst
    seq lag among them.  {} when none — a standalone round's artifact
    stays byte-compatible with pre-replication rounds.

    Like the ``shards`` stamp (ISSUE 7), this is refusal provenance:
    tools/check_bench_regress.py rejects serve-artifact pairs whose
    replica counts differ, so an N-replica aggregate can never mask a
    single-replica regression."""
    import os

    from heatmap_tpu.obs.xproc import ENV_CHANNEL

    members, _skipped = members_from(os.environ.get(ENV_CHANNEL))
    lags = []
    for _tag, snap in sorted(members.items()):
        _types, samples = parse_exposition(
            str(snap.get("metrics_text", "")))
        for series, _labels, v in samples:
            if series == "heatmap_repl_seq_lag":
                lags.append(v)
    if not lags:
        return {}
    return {"repl": {"replicas": len(lags),
                     "max_seq_lag": int(max(lags))}}


def compact_lineage(records: list) -> list:
    """Closed lineage records -> the compact cross-process form a
    member snapshot publishes: lid, event-time anchor, stage
    contributions, and the member's LAST stamp (view apply when the
    member applied the view, else the sink-commit ack)."""
    out = []
    for r in records:
        lid = r.get("lid")
        stages = r.get("stages")
        if not lid or not isinstance(stages, dict):
            continue
        t_last = r.get("t_view", r.get("t_sink"))
        if not isinstance(t_last, (int, float)):
            continue
        out.append({
            "lid": lid,
            "ev_mean_ts": r.get("ev_mean_ts"),
            "n_events": r.get("n_events"),
            "stages": {k: v for k, v in stages.items()
                       if isinstance(v, (int, float))},
            "t_last": t_last,
        })
    return out
