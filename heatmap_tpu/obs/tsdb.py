"""tsdb — self-hosted telemetry history (the fleet's memory).

Every series the observability substrate exposes is scrape-or-lose:
/metrics answers "now", /healthz is an instant threshold, and the only
post-hoc artifact is a flight-recorder dump with no surrounding
timeline.  This module gives the system a memory of its OWN telemetry,
the same retrospective move the space-time history tier (ISSUE 15)
made for tile data:

- :class:`TsdbRecorder` — a sampler thread scrapes the local registry
  exposition every ``HEATMAP_TSDB_SCRAPE_S`` into fixed-step in-memory
  rings (gauges last-value, counters monotonic totals with read-side
  reset detection, histograms as cumulative merged-bucket snapshots —
  uniformly: every exposition sample is one (t, value) point), records
  the member's /healthz verdict alongside, and persists append-only
  block files under ``HEATMAP_TSDB_DIR/<member-tag>/`` on the
  obs/xproc atomic-rename discipline (tmp + rename, ``updated_unix``
  staleness meta, ``.tmp`` skipped by readers) with bounded retention
  and a downsampled older tier.
- :class:`TsdbReader` — the cross-process read side: any member (or a
  survivor after a SIGKILL) can reassemble any member's historical
  series, healthz transitions, and recorded events from the retained
  blocks alone.
- :func:`member_timeline` / :func:`fleet_timeline` — the retrospective
  incident surfaces behind ``/debug/timeline`` and ``/fleet/timeline``:
  healthz transitions, SLO alerts, governor adjustments, audit
  mismatches, shed/lagged bursts, retraces, and flight-recorder
  episodes merged into one ordered timeline; the fleet form NAMES
  which member degraded first.

Everything is gated by ``HEATMAP_TSDB=1``; knob-off, nothing here is
imported on the hot path and no families register (tests pin the
exposition byte-identical).  The recorder self-reports its scrape cost
(``heatmap_tsdb_scrape_seconds``) so its overhead is bounded by a
metric assertion, not a promise.
"""

from __future__ import annotations

import collections
import glob
import json
import logging
import os
import threading
import time
from typing import Callable, Iterable, Mapping

log = logging.getLogger(__name__)

ENV_TSDB = "HEATMAP_TSDB"
ENV_DIR = "HEATMAP_TSDB_DIR"
ENV_SCRAPE = "HEATMAP_TSDB_SCRAPE_S"
ENV_RETAIN = "HEATMAP_TSDB_RETAIN_S"
ENV_HOT = "HEATMAP_TSDB_HOT_S"
ENV_FLUSH = "HEATMAP_TSDB_FLUSH_S"
ENV_RING = "HEATMAP_TSDB_RING"

_HZ_STATUS = {"ok": 0, "degraded": 1, "down": 2}
_HZ_NAMES = {v: k for k, v in _HZ_STATUS.items()}

# counter families whose increases become timeline events, with the
# event kind they surface as (reset-aware: a restarted member's counter
# restarting at zero is resumed from the reset point, never a negative)
EVENT_COUNTERS = (
    ("heatmap_govern_adjust_total", "govern_adjust"),
    ("heatmap_audit_digest_mismatch_total", "audit_mismatch"),
    ("heatmap_serve_shed_total", "shed"),
    ("heatmap_sse_lagged_total", "lagged"),
    ("heatmap_retrace_after_warmup_total", "retrace"),
)


def tsdb_enabled(env: Mapping[str, str] | None = None) -> bool:
    e = os.environ if env is None else env
    return e.get(ENV_TSDB, "") not in ("", "0", "false")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def series_key(name: str, labels: Mapping[str, str] | None) -> str:
    """Canonical ring key for one exposition sample: the series name
    with its labels re-rendered in sorted order, so the same sample
    always lands in the same ring regardless of emission order."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def counter_increases(points: Iterable[tuple]) -> list:
    """Reset-aware per-interval increases of a monotonic-total series:
    ``new < previous`` means the writer restarted and the new total IS
    the increase since the reset point (the satellite fix obs_top and
    the fleet aggregator share)."""
    out = []
    prev = None
    for t, v in points:
        if prev is not None:
            d = v - prev if v >= prev else v
            if d > 0:
                out.append((t, d))
        prev = v
    return out


class TsdbRecorder:
    """In-process metrics history recorder for ONE fleet member.

    ``scrape_fn() -> exposition text`` is the member's own /metrics
    body (full registry + flat counters — exactly what the member
    snapshot publishes), ``healthz_fn() -> payload`` its /healthz
    verdict.  Construction registers the self-accounting families in
    ``registry`` (only ever called knob-on, so knob-off exposition is
    untouched); ``start()`` runs the sampler thread; listeners (the
    SLO engine) run after every ingest with the scrape timestamp —
    same thread, same injected clock, so burn-rate math is
    synthetic-clock testable tick by tick."""

    def __init__(self, scrape_fn: Callable[[], str], *, tag: str,
                 dir_path: str | None = None,
                 healthz_fn: Callable[[], dict] | None = None,
                 registry=None, scrape_s: float | None = None,
                 retain_s: float | None = None,
                 hot_s: float | None = None,
                 flush_s: float | None = None,
                 ring: int | None = None,
                 clock: Callable[[], float] = time.time):
        self.scrape_fn = scrape_fn
        self.healthz_fn = healthz_fn
        self.tag = str(tag)
        self.dir = dir_path or None
        self.clock = clock
        self.scrape_s = float(scrape_s if scrape_s is not None
                              else _env_f(ENV_SCRAPE, 5.0))
        self.retain_s = float(retain_s if retain_s is not None
                              else _env_f(ENV_RETAIN, 3 * 86400.0))
        self.hot_s = float(hot_s if hot_s is not None
                           else _env_f(ENV_HOT, 3600.0))
        self.flush_s = float(flush_s if flush_s is not None
                             else _env_f(ENV_FLUSH, 60.0))
        self._ring_n = int(ring if ring is not None
                           else _env_f(ENV_RING, 2048))
        # coarse tier step: ~10 scrapes per retained point, never finer
        # than 30 s — old enough to be cold, coarse enough to be cheap
        self.coarse_s = max(30.0, self.scrape_s * 10.0)
        self._lock = threading.Lock()
        self._rings: dict[str, collections.deque] = {}
        self._parsed: dict[str, tuple] = {}     # key -> (name, labels)
        self._types: dict[str, str] = {}        # family -> type
        self._hz: collections.deque = collections.deque(
            maxlen=self._ring_n)
        self._events: collections.deque = collections.deque(maxlen=512)
        self._pending: list = []                # scrapes since last flush
        self._pending_events: list = []
        self._listeners: list = []
        self._last_flush = None
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if registry is not None:
            self._m_scrape = registry.histogram(
                "heatmap_tsdb_scrape_seconds",
                "wall time of one telemetry-history scrape (parse the "
                "local exposition + ingest rings + due block flush) — "
                "the recorder's self-reported overhead, asserted under "
                "budget in-suite",
                buckets=(0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1,
                         0.25, 1.0))
            self._m_scrapes = registry.counter(
                "heatmap_tsdb_scrapes_total",
                "telemetry-history scrapes taken since boot")
            self._m_series = registry.gauge(
                "heatmap_tsdb_series",
                "distinct series currently held in the telemetry-"
                "history in-memory rings", fn=lambda: len(self._rings))
            self._m_blocks = registry.counter(
                "heatmap_tsdb_blocks_written_total",
                "telemetry-history block files persisted under "
                "HEATMAP_TSDB_DIR (raw + downsampled tiers)")
            self._m_pruned = registry.counter(
                "heatmap_tsdb_pruned_blocks_total",
                "telemetry-history block files removed by retention "
                "(HEATMAP_TSDB_RETAIN_S) or merged into the "
                "downsampled tier")
            self._m_events = registry.counter(
                "heatmap_tsdb_events_total",
                "discrete incident events (SLO alerts/resolves, ...) "
                "recorded into the telemetry history")
        else:
            self._m_scrape = self._m_scrapes = self._m_series = None
            self._m_blocks = self._m_pruned = self._m_events = None

    # ------------------------------------------------------- listeners
    def add_listener(self, fn: Callable[[float], None]) -> None:
        """``fn(t)`` runs after each ingest, on the sampler thread."""
        self._listeners.append(fn)

    # --------------------------------------------------------- scraping
    def scrape_once(self) -> float:
        """One scrape tick: parse the exposition, ingest every sample
        into its ring, record the healthz verdict, notify listeners,
        flush when due.  Returns the tick timestamp.  Never raises —
        telemetry history must not take its member down."""
        t0_cost = time.perf_counter()
        t = float(self.clock())
        try:
            self._ingest(t)
        except Exception:  # noqa: BLE001 - recorder never kills the host
            log.warning("tsdb scrape failed", exc_info=True)
        for fn in self._listeners:
            try:
                fn(t)
            except Exception:  # noqa: BLE001
                log.warning("tsdb listener failed", exc_info=True)
        try:
            if self._flush_due(t):
                self.flush(now=t)
        except Exception:  # noqa: BLE001
            log.warning("tsdb flush failed", exc_info=True)
        if self._m_scrape is not None:
            self._m_scrape.observe(time.perf_counter() - t0_cost)
            self._m_scrapes.inc()
        return t

    def _ingest(self, t: float) -> None:
        from heatmap_tpu.obs.fleet import _LABEL_RE, parse_exposition

        types, samples = parse_exposition(self.scrape_fn())
        point = {}
        with self._lock:
            self._types.update(types)
            for name, labels, v in samples:
                # labels is the raw label block ("k=\"v\",...") — our
                # own registry emits it in stable order, so it is a
                # stable ring-key suffix as-is
                key = f"{name}{{{labels}}}" if labels else name
                ring = self._rings.get(key)
                if ring is None:
                    ring = self._rings[key] = collections.deque(
                        maxlen=self._ring_n)
                    self._parsed[key] = (
                        name, dict(_LABEL_RE.findall(labels or "")))
                ring.append((t, v))
                point[key] = v
        hz = None
        if self.healthz_fn is not None:
            try:
                payload = self.healthz_fn() or {}
                status = _HZ_STATUS.get(str(payload.get("status")), 1)
                failing = sorted(
                    n for n, c in (payload.get("checks") or {}).items()
                    if isinstance(c, dict) and c.get("ok") is False)
                hz = (t, status, failing)
                with self._lock:
                    self._hz.append(hz)
            except Exception:  # noqa: BLE001 - verdict is best-effort
                log.warning("tsdb healthz sample failed", exc_info=True)
        self._pending.append((t, point, hz))

    def record_event(self, ev: dict) -> None:
        """Append a discrete incident event (SLO alert, ...) to the
        history.  ``t`` defaults to the recorder clock; callers that
        need durability NOW (an alert just fired — exactly when the
        process may die next) follow with :meth:`flush`."""
        ev = dict(ev)
        ev.setdefault("t", float(self.clock()))
        ev.setdefault("member", self.tag)
        with self._lock:
            self._events.append(ev)
        self._pending_events.append(ev)
        if self._m_events is not None:
            self._m_events.inc()

    # ------------------------------------------------------ persistence
    def _flush_due(self, now: float) -> bool:
        if self.dir is None or not self._pending:
            return False
        if self._last_flush is None:
            self._last_flush = now
            return False
        return now - self._last_flush >= self.flush_s

    def flush(self, now: float | None = None) -> str | None:
        """Persist pending scrapes as one append-only block file
        (atomic tmp + rename), refresh the member meta, then apply
        downsampling + retention.  No-op without a directory."""
        now = float(self.clock()) if now is None else now
        pending, events = self._pending, self._pending_events
        self._pending, self._pending_events = [], []
        self._last_flush = now
        if self.dir is None or not (pending or events):
            return None
        from heatmap_tpu.obs.xproc import atomic_write_json

        mdir = os.path.join(self.dir, self.tag)
        os.makedirs(mdir, exist_ok=True)
        series: dict[str, list] = {}
        hz = []
        for t, point, hz_s in pending:
            for key, v in point.items():
                series.setdefault(key, []).append([round(t, 3), v])
            if hz_s is not None:
                hz.append([round(hz_s[0], 3), hz_s[1], hz_s[2]])
        ts = ([p[0] for p in pending]
              + [float(e.get("t", now)) for e in events])
        t0, t1 = (min(ts), max(ts)) if ts else (now, now)
        self._seq += 1
        block = {
            "tag": self.tag, "schema": 1, "tier": 0,
            "t0": round(t0, 3), "t1": round(t1, 3),
            "scrape_s": self.scrape_s,
            "types": dict(self._types),
            "series": series, "hz": hz, "events": events,
        }
        path = os.path.join(mdir, f"block-{int(t0 * 1000):015d}"
                                  f"-{self._seq:06d}.json")
        atomic_write_json(path, block)
        atomic_write_json(os.path.join(mdir, "meta.json"), {
            "tag": self.tag, "schema": 1,
            "scrape_s": self.scrape_s,
            "updated_unix": round(float(self.clock()), 3),
        })
        if self._m_blocks is not None:
            self._m_blocks.inc()
        try:
            self._maintain(now)
        except Exception:  # noqa: BLE001 - retention is best-effort
            log.warning("tsdb retention failed", exc_info=True)
        return path

    def _maintain(self, now: float) -> None:
        """Downsample raw blocks past the hot window into the coarse
        tier (last sample per ``coarse_s`` stride; healthz transitions
        only; every event kept), then drop ANY block past retention."""
        from heatmap_tpu.obs.xproc import atomic_write_json

        mdir = os.path.join(self.dir, self.tag)
        raws = sorted(glob.glob(os.path.join(glob.escape(mdir),
                                             "block-*.json")))
        cold = []
        for p in raws:
            blk = _read_block(p)
            if blk is not None and blk.get("t1", now) < now - self.hot_s:
                cold.append((p, blk))
        if cold:
            merged: dict[str, list] = {}
            types: dict[str, str] = {}
            hz, events = [], []
            for _p, blk in cold:
                types.update(blk.get("types") or {})
                for key, pts in (blk.get("series") or {}).items():
                    merged.setdefault(key, []).extend(pts)
                hz.extend(blk.get("hz") or [])
                events.extend(blk.get("events") or [])
            series = {key: _downsample(sorted(pts), self.coarse_s)
                      for key, pts in merged.items()}
            hz.sort()
            t0 = min(blk["t0"] for _p, blk in cold)
            t1 = max(blk["t1"] for _p, blk in cold)
            self._seq += 1
            atomic_write_json(
                os.path.join(mdir, f"tier1-{int(t0 * 1000):015d}"
                                   f"-{self._seq:06d}.json"),
                {"tag": self.tag, "schema": 1, "tier": 1,
                 "t0": t0, "t1": t1, "scrape_s": self.coarse_s,
                 "types": types, "series": series,
                 "hz": _hz_transitions(hz), "events": events})
            if self._m_blocks is not None:
                self._m_blocks.inc()
            for p, _blk in cold:
                try:
                    os.remove(p)
                except OSError:
                    pass
                if self._m_pruned is not None:
                    self._m_pruned.inc()
        for p in glob.glob(os.path.join(glob.escape(mdir),
                                        "tier1-*.json")):
            blk = _read_block(p)
            if blk is not None and blk.get("t1", now) < now - self.retain_s:
                try:
                    os.remove(p)
                except OSError:
                    pass
                if self._m_pruned is not None:
                    self._m_pruned.inc()

    # -------------------------------------------------------- ring reads
    def window(self, key: str, since: float) -> list:
        """Recent points of one series from the in-memory ring."""
        with self._lock:
            ring = self._rings.get(key)
            return [(t, v) for t, v in (ring or ()) if t > since]

    def latest(self, key: str):
        with self._lock:
            ring = self._rings.get(key)
            return ring[-1] if ring else None

    def match(self, name: str,
              labels: Mapping[str, str] | None = None) -> list:
        """Ring keys whose base name matches ``name`` and whose labels
        include every (k, v) in ``labels``."""
        want = dict(labels or {})
        with self._lock:
            out = []
            for key, (base, lbls) in self._parsed.items():
                if base != name:
                    continue
                if all(lbls.get(k) == v for k, v in want.items()):
                    out.append(key)
            return out

    def parsed(self, key: str) -> tuple:
        with self._lock:
            return self._parsed.get(key, (key, {}))

    # --------------------------------------------------------- lifecycle
    def start(self) -> "TsdbRecorder":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="tsdb-recorder", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.scrape_s):
            self.scrape_once()

    def stop(self) -> None:
        """Stop the sampler and force a final flush so the last
        pre-shutdown window survives for the retrospective surfaces."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        try:
            self.flush()
        except Exception:  # noqa: BLE001
            log.warning("tsdb final flush failed", exc_info=True)


def _downsample(points: list, step: float) -> list:
    """Last sample per ``step``-wide stride: preserves gauges' level
    and counters' monotonic totals (any subsample of a cumulative
    series still yields exact increases at coarser resolution)."""
    out: dict[int, list] = {}
    for p in points:
        out[int(p[0] // step)] = p
    return [out[k] for k in sorted(out)]


def _hz_transitions(hz: list) -> list:
    out = []
    prev = None
    for e in hz:
        if prev is None or e[1] != prev:
            out.append(e)
            prev = e[1]
    return out


def _read_block(path: str) -> dict | None:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


class TsdbReader:
    """Cross-process read side over a ``HEATMAP_TSDB_DIR``: every
    member's retained blocks, with the same never-raise contract as
    every xproc channel read (a corrupt or in-rename block is skipped,
    never fatal)."""

    def __init__(self, dir_path: str):
        self.dir = dir_path

    def members(self) -> list:
        out = []
        try:
            for name in sorted(os.listdir(self.dir)):
                if os.path.isfile(os.path.join(self.dir, name,
                                               "meta.json")):
                    out.append(name)
        except OSError:
            pass
        return out

    def meta(self, tag: str) -> dict | None:
        return _read_block(os.path.join(self.dir, tag, "meta.json"))

    def blocks(self, tag: str, since: float | None = None,
               until: float | None = None) -> list:
        mdir = os.path.join(self.dir, tag)
        paths = sorted(
            glob.glob(os.path.join(glob.escape(mdir), "tier1-*.json"))
            + glob.glob(os.path.join(glob.escape(mdir), "block-*.json")),
            key=lambda p: os.path.basename(p).split("-", 1)[1])
        out = []
        for p in paths:
            blk = _read_block(p)
            if blk is None:
                continue
            if since is not None and blk.get("t1", 0) < since:
                continue
            if until is not None and blk.get("t0", 0) > until:
                continue
            out.append(blk)
        return out

    def series(self, tag: str, names: Iterable[str] | None = None,
               since: float | None = None,
               until: float | None = None) -> dict:
        """``{series_key: [(t, v), ...]}`` merged across blocks, sorted
        by time.  ``names`` filters on the BASE family name (the part
        before any label braces)."""
        want = set(names) if names is not None else None
        merged: dict[str, list] = {}
        for blk in self.blocks(tag, since=since, until=until):
            for key, pts in (blk.get("series") or {}).items():
                if want is not None and key.split("{", 1)[0] not in want:
                    continue
                dst = merged.setdefault(key, [])
                for t, v in pts:
                    if since is not None and t <= since:
                        continue
                    if until is not None and t > until:
                        continue
                    dst.append((t, v))
        for pts in merged.values():
            pts.sort()
        return merged

    def healthz(self, tag: str, since: float | None = None) -> list:
        out = []
        for blk in self.blocks(tag, since=since):
            for e in blk.get("hz") or []:
                if len(e) >= 2 and (since is None or e[0] > since):
                    out.append((e[0], e[1],
                                list(e[2]) if len(e) > 2 else []))
        out.sort(key=lambda e: e[0])
        return out

    def events(self, tag: str, since: float | None = None) -> list:
        out = []
        for blk in self.blocks(tag, since=since):
            for ev in blk.get("events") or []:
                if isinstance(ev, dict) and (
                        since is None or ev.get("t", 0) > since):
                    out.append(ev)
        out.sort(key=lambda ev: ev.get("t", 0))
        return out


# ------------------------------------------------------------ timelines
def _flightrec_entries(flightrec_dir: str | None,
                       since: float | None) -> list:
    if not flightrec_dir:
        return []
    out = []
    for p in sorted(glob.glob(os.path.join(glob.escape(flightrec_dir),
                                           "flightrec-*.json"))):
        d = _read_block(p)
        if d is None:
            continue
        t = d.get("t_wall")
        if not isinstance(t, (int, float)):
            continue
        if since is not None and t <= since:
            continue
        out.append({"t": t, "kind": "flight_record",
                    "reason": d.get("reason"),
                    "episode": d.get("episode_id"),
                    "file": os.path.basename(p)})
    return out


def member_timeline(reader: TsdbReader, tag: str,
                    since: float | None = None,
                    flightrec_dir: str | None = None) -> list:
    """One member's ordered incident timeline, reconstructed from its
    retained blocks alone: healthz transitions, event-counter bursts
    (governor adjustments, audit mismatches, shed/lagged, retraces),
    recorded SLO alerts, and flight-recorder episodes."""
    entries = []
    prev = None
    for t, status, failing in reader.healthz(tag):
        if prev is not None and status != prev:
            if since is None or t > since:
                entries.append({
                    "t": t, "kind": "healthz", "member": tag,
                    "from": _HZ_NAMES.get(prev, str(prev)),
                    "to": _HZ_NAMES.get(status, str(status)),
                    "failing": failing})
        prev = status
    series = reader.series(tag, names=[n for n, _k in EVENT_COUNTERS],
                           since=None)
    kinds = dict(EVENT_COUNTERS)
    for key, pts in series.items():
        kind = kinds.get(key.split("{", 1)[0])
        if kind is None:
            continue
        for t, d in counter_increases(pts):
            if since is None or t > since:
                entries.append({"t": t, "kind": kind, "member": tag,
                                "series": key, "n": d})
    for ev in reader.events(tag, since=since):
        e = dict(ev)
        e.setdefault("kind", "event")
        e.setdefault("member", tag)
        entries.append(e)
    entries.extend(_flightrec_entries(flightrec_dir, since))
    entries.sort(key=lambda e: e.get("t", 0))
    return entries


def fleet_timeline(reader: TsdbReader, since: float | None = None,
                   flightrec_dir: str | None = None) -> dict:
    """Every member's timeline stitched into one, naming which member
    degraded FIRST (the earliest healthz transition away from ok —
    usable even after that member was SIGKILLed, because it reads the
    victim's retained blocks, not its sockets)."""
    members = reader.members()
    entries = []
    for tag in members:
        entries.extend(member_timeline(reader, tag, since=since))
    entries.extend(_flightrec_entries(flightrec_dir, since))
    entries.sort(key=lambda e: e.get("t", 0))
    first = None
    for e in entries:
        if e.get("kind") == "healthz" and e.get("to") != "ok":
            first = {"member": e.get("member"), "t": e.get("t"),
                     "to": e.get("to"), "failing": e.get("failing")}
            break
    return {"members": members, "entries": entries,
            "first_degraded": first}
