"""Delivery observatory — conservation-exact read-path lineage.

The PR 3 lineage telescopes event age from poll to sink ack ON THE
WRITER; this module extends the same discipline across the read tier,
so "delivered freshness" — the age of the newest event a subscriber's
socket has actually received — decomposes exactly:

    delivered_age == event_age + publish_queue + feed_transit
                     + replica_apply + fanout_queue + socket_write

Stamp points (one stamp per boundary, stages are adjacent differences,
so the identity telescopes with residual exactly 0 by construction —
the synthetic-clock cross-process test in tests/test_delivery.py pins
that no leg is lost, double-counted, or rounded):

- ``event_age``      age already accumulated when the writer's view
                     hook enqueued the mutation (the PR 3 lineage's
                     newest committed batch age; 0 when unknown);
- ``publish_queue``  hook enqueue → segment-log publish (writer clock);
- ``feed_transit``   publish → follower receipt of the record batch.
                     THE CROSS-HOST LEG: a writer-wall vs replica-wall
                     difference, reported separately (PR 8 skew
                     discipline) and never folded into a same-clock
                     percentile — with skewed clocks it absorbs the
                     skew and may even go negative;
- ``replica_apply``  receipt → ``replica_apply`` returned (local);
- ``fanout_queue``   apply → the subscriber generator began the socket
                     write of a frame carrying that seq (local; the
                     per-channel encode stamp rides the sample for
                     diagnosis but is not its own stage);
- ``socket_write``   write begin → the blocking WSGI write returned
                     (local).

The writer-side feed stamp is knob-gated (``HEATMAP_DELIVERY=1``):
with the knob off the feed records are byte-identical to an
uninstrumented build, and no frame is ever tagged.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from heatmap_tpu.obs.registry import DEFAULT_LAG_BUCKETS

#: stage order of the telescoping decomposition (worst-stage reporting,
#: /debug/delivery payloads, obs_top rows)
DELIVERY_STAGES = ("event_age", "publish_queue", "feed_transit",
                   "replica_apply", "fanout_queue", "socket_write")

#: legs whose endpoints live on DIFFERENT hosts' wall clocks — reported
#: separately, never mixed into a same-clock sum (PR 8 skew discipline)
CROSS_HOST_STAGES = ("feed_transit",)

ENV_DELIVERY = "HEATMAP_DELIVERY"
ENV_SLO_DELIVERED_P50_MS = "HEATMAP_SLO_DELIVERED_P50_MS"


def delivery_enabled(env=None) -> bool:
    """The writer-side publish-stamp knob (``HEATMAP_DELIVERY=1``)."""
    e = os.environ if env is None else env
    return str(e.get(ENV_DELIVERY, "")).strip().lower() in (
        "1", "true", "yes", "on")


def _q(sorted_vals: list, q: float) -> float:
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


class DeliveryTracker:
    """Per-replica delivery-lineage state: upstream stamps keyed by
    view seq (installed by the follower as records apply), completed
    end-to-end samples (installed by the SSE subscriber generators as
    socket writes return), and the ``heatmap_delivered_age_seconds``
    histogram per measurement bound.

    One shared injectable ``clock`` stamps every local boundary, so the
    decomposition telescopes exactly — the same conservation rule as
    obs.lineage."""

    def __init__(self, capacity: int = 512, clock=time.time,
                 registry=None):
        self.clock = clock
        self._lock = threading.Lock()
        self._cap = max(16, int(capacity))
        self._recs: collections.OrderedDict = collections.OrderedDict()
        self._samples: collections.deque = collections.deque(
            maxlen=self._cap)
        # newest upstream stamps, for the stalled-feed view: when the
        # writer goes quiet the NEXT record's transit keeps growing
        # even though no completed sample moves
        self._last_pub: float | None = None
        self._last_rx: float | None = None
        self._h_age = self._g_stage = None
        if registry is not None:
            self._h_age = registry.histogram(
                "heatmap_delivered_age_seconds",
                "age of the newest event a read-path consumer has "
                "received, per measurement bound: apply (replica view "
                "updated), encode (SSE frame encoded once per channel)"
                ", socket (a subscriber's blocking socket write "
                "returned) — the delivered-freshness decomposition "
                "behind /debug/delivery",
                labels=("bound",), buckets=DEFAULT_LAG_BUCKETS)
            self._g_stage = registry.gauge(
                "heatmap_delivery_stage_seconds",
                "recent mean of each delivery-lineage stage "
                "(event_age/publish_queue/feed_transit/replica_apply/"
                "fanout_queue/socket_write); feed_transit is the "
                "cross-host leg and is reported on its own clock pair",
                labels=("stage",))

    # ------------------------------------------------- follower side
    def record_applied(self, seq: int, pt, rx: float,
                       ap: float) -> None:
        """Install one applied record's upstream stamps.  ``pt`` is the
        feed record's knob-gated writer-clock stamp ``[eq, pub, ea]``
        (hook-enqueue time, publish time, event age at enqueue); ``rx``
        / ``ap`` are this process's receipt-of-batch and apply-returned
        stamps from the shared tracker clock."""
        if (not isinstance(pt, (list, tuple)) or len(pt) != 3
                or not all(isinstance(v, (int, float)) for v in pt)):
            return
        eq, pub, ea = float(pt[0]), float(pt[1]), float(pt[2])
        rec = {"seq": int(seq), "eq": eq, "pub": pub, "ea": ea,
               "rx": float(rx), "ap": float(ap)}
        with self._lock:
            self._recs[int(seq)] = rec
            while len(self._recs) > self._cap:
                self._recs.popitem(last=False)
            self._last_pub = pub
            self._last_rx = float(rx)
        if self._h_age is not None:
            age = (ea + (pub - eq) + (rx - pub) + (ap - rx))
            self._h_age.labels(bound="apply").observe(max(0.0, age))

    def _lookup(self, seq: int) -> dict | None:
        """The record that advanced the view to ``seq`` — or, when
        frames coalesce several seqs, the newest stamped record at or
        below it (the frame's newest content is what ages)."""
        rec = self._recs.get(int(seq))
        if rec is not None:
            return rec
        best = None
        for s, r in self._recs.items():
            if s <= seq and (best is None or s > best["seq"]):
                best = r
        return best

    # ---------------------------------------------------- serve side
    def encoded(self, seq: int) -> dict | None:
        """One per-channel encode stamp for the frame at view ``seq``;
        returns the frame's sidecar meta (ridden to each subscriber via
        ``Channel.broadcast(frame, meta=...)``) or None when no
        upstream stamps cover the seq — then the frame is broadcast
        plain and stays byte-identical to an uninstrumented run."""
        with self._lock:
            rec = self._lookup(int(seq))
            if rec is None:
                return None
            rec = dict(rec)
        enc = self.clock()
        if self._h_age is not None:
            age = (rec["ea"] + (rec["pub"] - rec["eq"])
                   + (rec["rx"] - rec["pub"]) + (enc - rec["rx"]))
            self._h_age.labels(bound="encode").observe(max(0.0, age))
        return {"rec": rec, "enc": enc}

    def delivered(self, meta: dict, wb: float, we: float) -> None:
        """Complete one subscriber's end-to-end sample: ``wb``/``we``
        bracket the tagged frame's socket write.  Both serve cores
        call this with the same contract — the thread core around the
        blocking ``send()`` in its subscriber generator, the epoll
        core from ``wb`` = the loop staging the frame to ``we`` = the
        loop completing the (possibly multi-``send``, offset-resumed)
        drain — so fanout_queue + socket_write still telescope and
        the residual stays identically 0 on either core."""
        rec = meta.get("rec")
        if not isinstance(rec, dict):
            return
        stages = {
            "event_age": rec["ea"],
            "publish_queue": rec["pub"] - rec["eq"],
            "feed_transit": rec["rx"] - rec["pub"],
            "replica_apply": rec["ap"] - rec["rx"],
            "fanout_queue": wb - rec["ap"],
            "socket_write": we - wb,
        }
        # the independent end-to-end recomputation, grouped by clock
        # domain (writer leg + cross leg + local leg from FIRST and
        # LAST stamps only): residual != 0 would mean a leg was lost
        # or double-counted, exactly like the PR 3 invariant
        age = (rec["ea"] + (rec["pub"] - rec["eq"])
               + (rec["rx"] - rec["pub"]) + (we - rec["rx"]))
        sample = {
            "seq": rec["seq"],
            "stages": stages,
            "age_s": age,
            "residual_s": age - sum(stages.values()),
            "enc": meta.get("enc"),
            "t": we,
        }
        with self._lock:
            self._samples.append(sample)
        if self._h_age is not None:
            self._h_age.labels(bound="socket").observe(max(0.0, age))
        if self._g_stage is not None:
            with self._lock:
                tail = list(self._samples)[-64:]
            for st in DELIVERY_STAGES:
                vals = [s["stages"][st] for s in tail]
                if vals:
                    self._g_stage.labels(stage=st).set(
                        round(sum(vals) / len(vals), 6))

    # ------------------------------------------------------ surfaces
    def summary(self) -> dict:
        """The compact rollup: completed-sample count, delivered-age
        p50/p99, per-stage p50s, the worst (slowest) stage, and the
        max |residual|."""
        with self._lock:
            samples = list(self._samples)
            last_pub, last_rx = self._last_pub, self._last_rx
        out: dict = {"count": len(samples)}
        if samples:
            ages = sorted(s["age_s"] for s in samples)
            out["age_p50_s"] = round(_q(ages, 0.5), 6)
            out["age_p99_s"] = round(_q(ages, 0.99), 6)
            stages: dict = {}
            for st in DELIVERY_STAGES:
                vals = sorted(s["stages"][st] for s in samples)
                stages[st] = round(_q(vals, 0.5), 6)
            out["stages_p50_s"] = stages
            out["worst_stage"] = max(stages, key=lambda k: stages[k])
            out["max_abs_residual_s"] = round(
                max(abs(s["residual_s"]) for s in samples), 9)
        if last_pub is not None:
            # the stalled-feed view: how long since the newest applied
            # record was PUBLISHED (cross-clock, like feed_transit
            # itself) — rises while a wedged writer publishes nothing,
            # even though no completed sample moves
            out["feed_transit_current_s"] = round(
                max(0.0, self.clock() - last_pub), 6)
        if last_rx is not None:
            out["since_last_receipt_s"] = round(
                max(0.0, self.clock() - last_rx), 6)
        return out

    def snapshot(self, n: int = 32) -> dict:
        """The ``/debug/delivery`` payload."""
        with self._lock:
            recent = list(self._samples)[-max(0, int(n)):][::-1]
        return {
            "stage_order": list(DELIVERY_STAGES),
            "cross_host": list(CROSS_HOST_STAGES),
            "summary": self.summary(),
            "recent": recent,
        }

    def member_block(self) -> dict | None:
        """The fleet member snapshot's ``delivery`` block (compact —
        published every HEATMAP_FLEET_PUBLISH_S; /fleet/delivery
        stitches it)."""
        s = self.summary()
        if not s.get("count") and "feed_transit_current_s" not in s:
            return None
        return s
