"""Integrity observatory: event-conservation ledger + content digests.

Every structural guarantee the system rests on — 1-vs-N shard
byte-identity (sharded runtime), mesh-vs-single-device byte-identity
(mesh fast path), writer-vs-replica byte-interchangeability (replicated
serve fleet) — is pinned by offline differential tests; in production
nothing detects a silently diverged shard, a corrupted repl segment, or
a double-applied window.  This module extends the conservation-exact
discipline the freshness lineage applies to *time* (obs.lineage: stages
telescope, residual == 0) to *content*, as two observe-only halves
gated by ``HEATMAP_AUDIT=1`` (zero data-path mutation either way):

**Event conservation ledger** (:class:`AuditState` + the counters the
runtime stamps at every pipeline boundary):

    polled == folded + dropped{reason: invalid, late, out_of_shard,
                               oversample, exchange}
    docs_emitted == docs_committed == docs_view_applied
    view seq == repl feed seq == replica applied seq   (per replica)

Residuals are computed per boundary.  A pipeline in flight legitimately
holds a transient residual (prefetched batches, the device emit ring,
the writer queue), but a healthy residual shrinks at every flush; a
LEAK never shrinks.  :meth:`AuditState.healthz_checks` therefore
degrades /healthz NAMING the boundary only when a non-zero residual has
not decreased (or returned to zero) for ``HEATMAP_AUDIT_SETTLE_S``
(default 10 s) — an idle-but-unbalanced book, or a monotonically
growing one, is the incident; a deep-but-draining pipeline is not.

**Per-window content digests** (:class:`DigestTable`): each tile doc
hashes to a stable 64-bit value (:func:`doc_hash` — salt-free blake2b
over the canonicalized doc, so every process agrees), and a (grid,
windowStart) window's digest is the XOR of its live cells' hashes.
XOR makes the digest order-independent (upsert order, shard-merge
order, replica apply order all commute) and incrementally maintainable
(upsert = ``old_hash ^ new_hash``), with the empty window as the
identity (0) and eviction retiring the window's digest entirely.
Because shard cell spaces are disjoint, per-shard digests COMBINE by
the same XOR to the merged-view digest (:func:`combine_digests`) —
the fan-in invariant /fleet/audit checks continuously.

The writer-side ``TileMatView`` maintains a digest table under its own
lock and publishes the post-apply digest of every touched (grid,
windowStart) inside the repl delta-log record (``"dg"``); every replica
recomputes from its OWN applied state and verifies per seq advance
(:meth:`AuditState.verify_record`).  A mismatch bumps
``heatmap_audit_digest_mismatch_total``, degrades /healthz naming the
(grid, window, seq), and dumps the flight recorder under ONE correlated
fleet episode (obs.xproc.ensure_episode — the PR 6 correlation rules).
Verification covers the grid's LATEST window only: non-latest windows
may legitimately diverge across replicas (local TTL clocks evict them
independently), and latest is the only serving-visible window anyway.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

ENV_AUDIT = "HEATMAP_AUDIT"
ENV_AUDIT_SETTLE = "HEATMAP_AUDIT_SETTLE_S"

# Ledger stages, in pipeline order (events, then docs, then records).
# ``dropped_<reason>`` children appear next to them per drop reason
# (stream.metrics.DROP_REASONS — the closed set).
LEDGER_STAGES = (
    "polled",          # rows polled from the source (incl. parse drops)
    "dispatched",      # rows entering the device fold
    "folded",          # rows aggregated (primary pair n_valid)
    "docs_emitted",    # tile docs pulled off the device, handed to sink
    "docs_committed",  # tile docs durably applied by the store
    "docs_view_applied",  # tile docs applied to the materialized view
    "repl_applied",    # replication records applied (replica side)
)

# Count-based boundaries: (name, upstream stage, downstream stages).
# feed_fold additionally subtracts every dropped_<reason> stage — the
# ISSUE's headline identity.  sink_view is only evaluated when a
# materialized view is attached (shard runtimes may have none).
BOUNDARIES = ("feed_fold", "emit_sink", "sink_view", "view_repl",
              "repl_replica")


def audit_enabled(env=None) -> bool:
    e = os.environ if env is None else env
    return e.get(ENV_AUDIT, "0") not in ("0", "false", "")


def audit_settle_s(default: float = 10.0) -> float:
    raw = os.environ.get(ENV_AUDIT_SETTLE, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        log.warning("%s=%r is not a number; using %s", ENV_AUDIT_SETTLE,
                    raw, default)
        return default


# ----------------------------------------------------------------- hash
def _canon(v) -> str:
    if isinstance(v, _dt.datetime):
        return v.isoformat()
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k}:{_canon(v[k])}" for k in sorted(v)) + "}"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_canon(x) for x in v) + "]"
    return repr(v)


def doc_hash(doc: dict) -> int:
    """Stable 64-bit content hash of one tile doc: salt-free blake2b
    over the canonicalized (sorted-key, ISO-datetime, repr-float) doc,
    so every process, shard, and replica derives the same value from
    the same content — Python's salted ``hash`` is exactly what this
    must NOT be."""
    parts = [f"{k}={_canon(doc[k])}" for k in sorted(doc)]
    h = hashlib.blake2b("|".join(parts).encode("utf-8"), digest_size=8)
    return int.from_bytes(h.digest(), "big")


def combine_digests(digests) -> int:
    """XOR-combine per-shard window digests (disjoint cell spaces) into
    the merged-view digest; the empty iterable is the identity 0."""
    out = 0
    for d in digests:
        out ^= int(d)
    return out


# ---------------------------------------------------------------- table
class DigestTable:
    """Per-(grid, windowStart) order-independent content digests.

    digest(grid, ws) == XOR of doc_hash(doc) over the window's live
    cells; maintained incrementally (upsert = old ^ new) under one
    lock.  ``staleAt`` rides along per window so :meth:`snapshot` can
    prune windows the view-side TTL would have retired — keeping a
    shard's published digests combinable against a lazily-evicting
    merged view."""

    def __init__(self):
        self._lock = threading.Lock()
        # grid -> ws -> {"cells": {cid: hash}, "digest": int,
        #               "stale": float | None}
        self._g: dict[str, dict[int, dict]] = {}

    def update(self, grid: str, ws: int, cid: str,
               old_doc: dict | None, new_doc: dict | None) -> None:
        """One cell's doc changed: fold the hash delta into the window
        digest.  ``new_doc=None`` removes the cell."""
        if not grid:
            return
        with self._lock:
            wins = self._g.setdefault(grid, {})
            w = wins.get(ws)
            if w is None:
                w = wins[ws] = {"cells": {}, "digest": 0, "stale": None}
            d = w["digest"]
            prev = w["cells"].pop(cid, None)
            if prev is not None:
                d ^= prev
            elif old_doc is not None:
                d ^= doc_hash(old_doc)
            if new_doc is not None:
                h = doc_hash(new_doc)
                w["cells"][cid] = h
                d ^= h
                stale = new_doc.get("staleAt")
                if isinstance(stale, _dt.datetime):
                    w["stale"] = stale.timestamp()
            w["digest"] = d
            if not w["cells"]:
                del wins[ws]

    def apply_doc(self, doc: dict) -> None:
        grid = doc.get("grid")
        ws_dt = doc.get("windowStart")
        if not grid or not isinstance(ws_dt, _dt.datetime):
            return
        self.update(grid, int(ws_dt.timestamp()), doc.get("cellId"),
                    None, doc)

    def apply_docs(self, docs) -> None:
        for d in docs:
            self.apply_doc(d)

    def drop_window(self, grid: str, ws: int) -> None:
        with self._lock:
            wins = self._g.get(grid)
            if wins is not None:
                wins.pop(ws, None)
                if not wins:
                    self._g.pop(grid, None)

    def prune(self, now: float) -> int:
        """Drop every window whose ``staleAt`` has passed — the
        emit-shard tables' eviction (the VIEW's table is pruned by the
        view's own evictions; these tables have no such driver, and an
        unpruned table would grow one cell-hash map per window
        forever).  Returns windows dropped."""
        n = 0
        with self._lock:
            for grid in list(self._g):
                wins = self._g[grid]
                for ws in [w for w, rec in wins.items()
                           if rec["stale"] is not None
                           and rec["stale"] <= now]:
                    del wins[ws]
                    n += 1
                if not wins:
                    del self._g[grid]
        return n

    def clear(self) -> None:
        with self._lock:
            self._g.clear()

    def digest(self, grid: str, ws: int) -> int | None:
        with self._lock:
            w = (self._g.get(grid) or {}).get(ws)
            return None if w is None else w["digest"]

    def windows(self, grid: str) -> list:
        with self._lock:
            return sorted(self._g.get(grid) or ())

    def snapshot(self, now: float | None = None,
                 max_windows: int = 32) -> dict:
        """{grid: {str(ws): {"digest": hex16, "cells": n}}} — newest
        ``max_windows`` windows per grid, windows stale at ``now``
        pruned (so a shard's published digests stay combinable against
        the merged view's lazy TTL eviction)."""
        out: dict = {}
        with self._lock:
            for grid, wins in self._g.items():
                live = {ws: w for ws, w in wins.items()
                        if now is None or w["stale"] is None
                        or w["stale"] > now}
                for ws in sorted(live)[-max_windows:]:
                    w = live[ws]
                    out.setdefault(grid, {})[str(ws)] = {
                        "digest": format(w["digest"], "016x"),
                        "cells": len(w["cells"]),
                    }
        return out


# ---------------------------------------------------------------- ledger
def residuals_from_counts(counts: dict, has_view: bool = True) -> dict:
    """Count-based boundary residuals from a ledger/stage dict — shared
    by the local snapshot and the fleet stitch (obs.fleet sums member
    ledgers, then applies the same identities)."""
    c = counts.get
    dropped = sum(v for k, v in counts.items()
                  if k.startswith("dropped_"))
    out = {"feed_fold": c("polled", 0) - c("folded", 0) - dropped,
           "emit_sink": c("docs_emitted", 0) - c("docs_committed", 0)}
    if has_view:
        out["sink_view"] = (c("docs_committed", 0)
                            - c("docs_view_applied", 0))
    return out


class AuditState:
    """One process's integrity-observatory state: the conservation
    ledger, per-shard digest tables, replica digest verification, the
    ``heatmap_audit_*`` metric families, and the /healthz checks.
    Observe-only by construction — nothing here is on the data path's
    failure surface (every hook call is counted arithmetic)."""

    def __init__(self, registry=None, tag: str = "local",
                 settle_s: float | None = None, clock=time.monotonic,
                 channel_path=None, flightrec=None):
        self.tag = str(tag)
        self.clock = clock
        self.settle_s = (audit_settle_s() if settle_s is None
                         else float(settle_s))
        # channel/flightrec feed the correlated-episode dump on a digest
        # mismatch; both default from env lazily (a serve worker builds
        # this before its recorder exists)
        self._channel_path = channel_path
        self.flightrec = flightrec
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._tables: dict[object, DigestTable] = {}
        self.has_view = False
        self.view = None
        self.repl_pub = None
        self.follower = None
        self.verified = 0
        self.mismatches = 0
        self.last_verified_seq = 0
        self.last_mismatch: dict | None = None
        # per-boundary leak tracker: last |residual| and the last time
        # it was zero or decreased (the "draining" evidence)
        self._track: dict[str, list] = {}
        self._dumped_episodes: set = set()
        self._prune_last = time.monotonic()  # shard-table sweep limiter
        self._scrape_memo: tuple | None = None  # (mono_ts, residuals)
        self._c_stage = self._g_residual = None
        self._c_verified = self._c_mismatch = self._g_last_seq = None
        if registry is not None:
            self._c_stage = registry.counter(
                "heatmap_audit_stage_total",
                "events/docs/records counted at each pipeline boundary "
                "by the conservation ledger (HEATMAP_AUDIT=1; stages "
                "telescope — see /debug/audit for the residuals)",
                labels=("stage",))
            for s in LEDGER_STAGES:
                self._c_stage.labels(stage=s)
            self._g_residual = registry.gauge(
                "heatmap_audit_residual",
                "conservation-ledger residual per pipeline boundary "
                "(upstream minus downstream counts; transiently nonzero "
                "while batches are in flight, 0 at quiescence — a "
                "residual that stops draining degrades /healthz naming "
                "the boundary)", labels=("boundary",))
            for b in ("feed_fold", "emit_sink"):
                self._g_residual.labels(boundary=b).fn = (
                    lambda bb=b: self._scrape_residuals().get(bb, 0))
            self._c_verified = registry.counter(
                "heatmap_audit_digests_verified_total",
                "per-window content digests recomputed from this "
                "replica's own applied state that matched the writer's "
                "published digest")
            self._c_mismatch = registry.counter(
                "heatmap_audit_digest_mismatch_total",
                "published-vs-recomputed window digest mismatches — a "
                "diverged replica, corrupted repl record, or "
                "double-applied window; any nonzero degrades /healthz "
                "naming the (grid, window, seq)")
            self._g_last_seq = registry.gauge(
                "heatmap_audit_last_verified_seq",
                "newest view seq whose published window digest this "
                "replica verified against its own state")

    # ------------------------------------------------------------ wiring
    def attach(self, view=None, repl_pub=None, follower=None) -> None:
        """Late-bound live refs: the materialized view (seq + digest
        table), the repl publisher (feed head seq), the replica
        follower (applied seq) — the record/seq boundaries are computed
        from these at read time instead of double-counted."""
        if view is not None:
            self.view = view
            self.has_view = True
        if repl_pub is not None:
            self.repl_pub = repl_pub
        if follower is not None:
            self.follower = follower
        if self._g_residual is not None:
            for b, want in (("sink_view", self.has_view),
                            ("view_repl", self.repl_pub is not None),
                            ("repl_replica", self.follower is not None)):
                if want:
                    self._g_residual.labels(boundary=b).fn = (
                        lambda bb=b:
                        self._scrape_residuals().get(bb, 0))

    @property
    def channel_path(self):
        if self._channel_path is not None:
            return self._channel_path
        from heatmap_tpu.obs import ENV_CHANNEL

        return os.environ.get(ENV_CHANNEL)

    # ------------------------------------------------------------ ledger
    def add(self, stage: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            self._counts[stage] = self._counts.get(stage, 0) + int(n)
        if self._c_stage is not None:
            self._c_stage.labels(stage=stage).inc(n)
        self._maybe_prune()

    def _maybe_prune(self) -> None:
        """Rate-limited (60 s) stale-window sweep over the emit-shard
        digest tables — without it a 24/7 audited run retains every
        expired window's cell-hash map forever (the view's table is
        pruned by the view's own evictions; these have no other
        driver)."""
        now = time.monotonic()
        with self._lock:
            if now - self._prune_last < 60.0:
                return
            self._prune_last = now
            tables = list(self._tables.values())
        wall = time.time()
        for t in tables:
            t.prune(wall)

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def shard_table(self, shard=None) -> DigestTable:
        """The digest table of one emit shard: ``None`` = the process's
        single fold, an int = one partitioned-mesh device.  Published
        per shard so /fleet/audit can XOR-combine them against the
        merged-view digest (disjoint cell spaces)."""
        key = "self" if shard is None else str(shard)
        with self._lock:
            t = self._tables.get(key)
            if t is None:
                t = self._tables[key] = DigestTable()
            return t

    def _scrape_residuals(self) -> dict:
        """residuals() behind a short memo for the per-boundary gauge
        callbacks: one /metrics scrape evaluates up to 5 children, and
        without the memo each would re-take the ledger/view locks for
        values from the same instant — the memo also makes the
        published boundary values mutually consistent."""
        now = time.monotonic()
        memo = self._scrape_memo
        if memo is not None and now - memo[0] < 0.25:
            return memo[1]
        res = self.residuals()
        self._scrape_memo = (now, res)
        return res

    def residuals(self) -> dict:
        out = residuals_from_counts(self.counts(),
                                    has_view=self.has_view)
        view, pub, fol = self.view, self.repl_pub, self.follower
        if view is not None and pub is not None:
            out["view_repl"] = max(
                0, int(view.seq) - int(getattr(pub, "_last_seq", 0)))
        if fol is not None:
            out["repl_replica"] = int(fol.seq_lag())
        return out

    # ---------------------------------------------------------- settling
    def evaluate(self, now: float | None = None) -> dict:
        """Residuals + leak tracking in one pass: a boundary whose
        |residual| hit zero or decreased is 'draining' (its timer
        resets); one that stayed nonzero without ever decreasing for
        ``settle_s`` is LEAKING.  Returns {boundary: residual}."""
        now = self.clock() if now is None else now
        res = self.residuals()
        with self._lock:
            for b, r in res.items():
                t = self._track.get(b)
                if t is None:
                    self._track[b] = [abs(r), now]
                    continue
                if abs(r) == 0 or abs(r) < t[0]:
                    t[1] = now
                t[0] = abs(r)
        return res

    def leaking(self, now: float | None = None) -> dict:
        """{boundary: residual} for boundaries in the leak state."""
        now = self.clock() if now is None else now
        res = self.evaluate(now)
        out = {}
        with self._lock:
            for b, r in res.items():
                t = self._track.get(b)
                if (r != 0 and t is not None
                        and now - t[1] >= self.settle_s):
                    out[b] = r
        return out

    def worst_boundary(self) -> tuple[str, int] | None:
        res = self.residuals()
        if not res:
            return None
        b = max(res, key=lambda k: abs(res[k]))
        return (b, res[b]) if res[b] else None

    # ------------------------------------------------------------ digests
    def verify_record(self, view, rec: dict) -> None:
        """Replica-side digest verification, per applied feed record:
        the writer published its post-apply digest for every touched
        (grid, windowStart) (``rec["dg"]``); recompute from THIS
        replica's applied state and compare.  Latest-window only —
        non-latest windows evict on local TTL clocks and may
        legitimately differ."""
        dg = rec.get("dg")
        if not isinstance(dg, dict):
            return
        seq = int(rec.get("seq", 0))
        for grid, per_ws in dg.items():
            if not isinstance(per_ws, dict):
                continue
            latest = view.latest_ws_of(grid)
            for ws_s, expect in per_ws.items():
                try:
                    ws, want = int(ws_s), int(expect, 16)
                except (TypeError, ValueError):
                    continue
                if latest is None or ws != latest:
                    continue
                have = view.audit_digest(grid, ws) or 0
                if have == want:
                    self.note_verified(seq)
                else:
                    self.note_digest_mismatch(grid, ws, seq, have=have,
                                              want=want)

    def note_verified(self, seq: int) -> None:
        with self._lock:
            self.verified += 1
            self.last_verified_seq = max(self.last_verified_seq,
                                         int(seq))
        if self._c_verified is not None:
            self._c_verified.inc()
        if self._g_last_seq is not None:
            self._g_last_seq.set(self.last_verified_seq)

    def note_digest_mismatch(self, grid: str, ws: int, seq: int,
                             have: int = 0, want: int = 0) -> None:
        """Content divergence detected: count it, remember the (grid,
        window, seq) for /healthz, and dump the flight recorder under
        ONE correlated fleet episode (the first mismatch of an incident
        claims/joins the episode; later mismatches under the same
        episode don't re-dump)."""
        with self._lock:
            self.mismatches += 1
            self.last_mismatch = {"grid": grid, "ws": int(ws),
                                  "seq": int(seq),
                                  "have": format(have, "016x"),
                                  "want": format(want, "016x")}
        if self._c_mismatch is not None:
            self._c_mismatch.inc()
        log.error("AUDIT digest mismatch: grid=%s window=%d seq=%d "
                  "(have %016x, want %016x)", grid, ws, seq, have, want)
        self._dump_mismatch(grid, ws, seq)

    def _dump_mismatch(self, grid: str, ws: int, seq: int) -> None:
        rec = self.flightrec
        if rec is None:
            from heatmap_tpu.obs.flightrec import from_env

            rec = from_env()
        reason = (f"audit digest mismatch: grid={grid} window={ws} "
                  f"seq={seq}")
        episode: dict = {}
        chan = self.channel_path
        if chan:
            from heatmap_tpu.obs.xproc import ensure_episode

            episode = ensure_episode(chan, self.tag, reason)
        # dump once per incident: the fleet episode id when a channel
        # is attached; channel-less, per diverged (grid, window) — a
        # NEW window diverging days later is a new incident and must
        # still leave a flight record
        eid = episode.get("episode_id") or ""
        key = eid or f"local:{grid}:{int(ws)}"
        with self._lock:
            if key in self._dumped_episodes:
                return
            while len(self._dumped_episodes) >= 64:
                self._dumped_episodes.pop()
            self._dumped_episodes.add(key)
        if rec is None:
            return
        try:
            snap = rec.spawn()
            snap.add_source("audit", lambda: self.snapshot())
            if episode:
                snap.add_source("episode", lambda e=dict(episode): e)
            snap.dump(reason + (f" (episode {eid})" if eid else ""),
                      episode_id=eid or None)
        except Exception:  # noqa: BLE001 - telemetry never takes us down
            log.warning("audit mismatch flight-record dump failed",
                        exc_info=True)

    # ----------------------------------------------------------- surfaces
    def healthz_checks(self, now: float | None = None
                       ) -> tuple[dict, bool]:
        """({check: ...}, degraded) for /healthz: a leaking boundary
        degrades NAMING it; any digest mismatch degrades naming the
        (grid, window, seq)."""
        checks: dict = {}
        degraded = False
        leaks = self.leaking(now)
        if leaks:
            worst = max(leaks, key=lambda k: abs(leaks[k]))
            checks["audit_residual"] = {
                "value": "; ".join(f"{b}={r:+d}"
                                   for b, r in sorted(leaks.items())),
                "boundary": worst, "ok": False}
            degraded = True
        else:
            checks["audit_residual"] = {"value": "conserved", "ok": True}
        with self._lock:
            mm, last = self.mismatches, dict(self.last_mismatch or {})
        if mm:
            checks["audit_digest"] = {
                "value": (f"{mm} mismatch(es); last grid={last.get('grid')}"
                          f" window={last.get('ws')} seq={last.get('seq')}"),
                "ok": False, **last}
            degraded = True
        else:
            checks["audit_digest"] = {"value": "verified", "ok": True}
        return checks, degraded

    def member_block(self, now_wall: float | None = None) -> dict:
        """The compact audit block a fleet member snapshot publishes
        (obs.xproc) — what /fleet/audit stitches: ledger counts,
        residuals, per-shard + view digests, verification state, and
        the repl seq anchors."""
        now_wall = time.time() if now_wall is None else now_wall
        with self._lock:
            tables = dict(self._tables)
            verify = {"verified": self.verified,
                      "mismatches": self.mismatches,
                      "last_verified_seq": self.last_verified_seq}
            if self.last_mismatch:
                verify["last_mismatch"] = dict(self.last_mismatch)
        view, pub, fol = self.view, self.repl_pub, self.follower
        out = {
            "tag": self.tag,
            "ledger": self.counts(),
            "residuals": self.residuals(),
            "digests": {
                "shard": {label: t.snapshot(now=now_wall)
                          for label, t in sorted(tables.items())},
            },
            "verify": verify,
        }
        if view is not None and (tables or pub is not None):
            # only an EMITTING member (or the feed publisher) owns the
            # merged-view digests the fleet combine targets; a replica's
            # view digests are its verification input, not a combine
            # anchor — publishing them would make a lagging replica
            # read as a shard-merge mismatch
            vt = getattr(view, "audit_table", None)
            if vt is not None:
                out["digests"]["view"] = vt.snapshot(now=now_wall)
        repl: dict = {}
        if view is not None:
            repl["view_seq"] = int(view.seq)
        if pub is not None:
            repl["feed_seq"] = int(getattr(pub, "_last_seq", 0))
        if fol is not None:
            repl["applied_seq"] = int(fol.applied)
            repl["feed_head_seq"] = int(fol._last_seq_seen)
        if repl:
            out["repl"] = repl
        return out

    def snapshot(self) -> dict:
        """The /debug/audit payload: the member block plus the settled
        verdicts an operator asks for first."""
        out = self.member_block()
        out["leaking"] = self.leaking()
        worst = self.worst_boundary()
        out["worst_boundary"] = (
            {"boundary": worst[0], "residual": worst[1]}
            if worst else None)
        out["settle_s"] = self.settle_s
        return out

    def bench_stamp(self) -> dict:
        """The ``audit`` block bench.py / tools/e2e_rate.py stamp into
        artifacts; tools/check_bench_regress.py REFUSES artifacts whose
        stamp carries a non-zero residual or any digest mismatch."""
        res = self.residuals()
        return {
            "enabled": True,
            "max_residual": (max((abs(r) for r in res.values()),
                                 default=0)),
            "digests_verified": self.verified,
            "mismatches": self.mismatches,
        }
