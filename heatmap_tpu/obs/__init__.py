"""obs — pipeline-wide observability substrate.

Eight pieces, all dependency-free:

- :mod:`registry` — counters / gauges / fixed-bucket histograms with
  Prometheus text exposition (``Registry.expose_text``);
- :mod:`tracebuf` — bounded ring of structured per-micro-batch trace
  records (``/trace/recent``; optional size-rotated JSONL export via
  ``HEATMAP_TRACE_JSONL`` / ``HEATMAP_TRACE_JSONL_MAX_BYTES``);
- :mod:`lineage` — per-batch freshness lineage (event ts → sink-commit
  ack, staged through poll/prefetch/fold/ring/sink), the substrate of
  ``heatmap_event_age_seconds`` and ``/debug/freshness``;
- :mod:`delivery` — read-path delivery lineage (publish enqueue →
  feed transit → replica apply → fan-out → subscriber socket write),
  the substrate of ``heatmap_delivered_age_seconds``,
  ``/debug/delivery`` and ``/fleet/delivery`` (``HEATMAP_DELIVERY``);
- :mod:`flightrec` — crash-time state dump (trace tail, lineage tail,
  metrics snapshot, config) to ``HEATMAP_FLIGHTREC_DIR``;
- :mod:`runtimeinfo` — compile/retrace tracking on the jitted entry
  points, device memory watermarks, and the SLO watchdog that
  auto-captures an enriched flight record when /healthz degrades;
- :mod:`prof` — the always-available sampling Python stack profiler
  behind ``/debug/stacks`` (``HEATMAP_STACKPROF_HZ``);
- :mod:`xproc` — the file-backed supervisor→child metrics channel
  (``HEATMAP_SUPERVISOR_CHANNEL``), so the child's ``/metrics`` reports
  its parent supervisor's restart counters and they survive restarts;
  plus the per-child freshness summary files next to it.

stream.metrics.Metrics builds on the registry and keeps its historical
``snapshot()`` JSON keys — served at ``/metrics.json`` — while
``/metrics`` serves the scrape-able exposition.  Metric names and SLO
knobs are documented in ARCHITECTURE.md §Observability.
"""

from heatmap_tpu.obs.delivery import (  # noqa: F401
    DELIVERY_STAGES,
    DeliveryTracker,
    delivery_enabled,
)
from heatmap_tpu.obs.flightrec import FlightRecorder  # noqa: F401
from heatmap_tpu.obs.lineage import LineageTracker  # noqa: F401
from heatmap_tpu.obs.prof import StackSampler, get_sampler  # noqa: F401
from heatmap_tpu.obs.registry import (  # noqa: F401
    DEFAULT_LAG_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Registry,
    render_flat_counters,
)
from heatmap_tpu.obs.tracebuf import TraceRing  # noqa: F401
from heatmap_tpu.obs.xproc import ENV_CHANNEL, SupervisorChannel  # noqa: F401
