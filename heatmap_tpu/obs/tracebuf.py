"""Structured per-micro-batch trace records in a bounded ring buffer.

Each record is one micro-batch: batch id (epoch), wall time, span
timings, event counts, and loss flags (overflow / late drops).  The ring
is what /trace/recent serves (newest first) — enough history to see what
the pipeline was doing around an incident without a profiler attach.

Optional JSONL export: set ``HEATMAP_TRACE_JSONL=/path/file.jsonl`` and
every record is also appended as one JSON line (flushed per batch; at
micro-batch cadence this is noise).  Export errors are logged once and
never take the pipeline down.

The export is size-bounded: once the file exceeds
``HEATMAP_TRACE_JSONL_MAX_BYTES`` (default 64 MiB) it rotates to a
single ``.1`` rollover (replacing any previous one), so a long-running
stream holds at most ~2x the limit on disk instead of filling it.
``0`` disables rotation.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

ENV_JSONL = "HEATMAP_TRACE_JSONL"
ENV_JSONL_MAX = "HEATMAP_TRACE_JSONL_MAX_BYTES"
DEFAULT_JSONL_MAX = 64 << 20


class TraceRing:
    def __init__(self, capacity: int = 256, jsonl_path: str | None = None,
                 env=None, jsonl_max_bytes: int | None = None):
        e = os.environ if env is None else env
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._jsonl_path = (jsonl_path if jsonl_path is not None
                            else e.get(ENV_JSONL) or None)
        if jsonl_max_bytes is not None:
            self._jsonl_max = int(jsonl_max_bytes)
        else:
            try:
                self._jsonl_max = int(
                    e.get(ENV_JSONL_MAX, DEFAULT_JSONL_MAX))
            except ValueError:
                log.warning("%s=%r is not an integer; using %d",
                            ENV_JSONL_MAX, e.get(ENV_JSONL_MAX),
                            DEFAULT_JSONL_MAX)
                self._jsonl_max = DEFAULT_JSONL_MAX
        self._jsonl_bytes = 0
        self._jsonl_fh = None
        self._jsonl_dead = False

    def record(self, epoch: int, latency_s: float, spans: dict,
               n_events: int = 0, n_late: int = 0,
               overflow_groups: int = 0, late_dropped: int = 0,
               **extra) -> dict:
        rec = {
            "seq": 0,  # filled under the lock
            "epoch": int(epoch),
            "t_wall": round(time.time(), 3),
            "latency_ms": round(latency_s * 1e3, 3),
            "spans_ms": {k: round(v * 1e3, 3) for k, v in spans.items()},
            "n_events": int(n_events),
            "n_late": int(n_late),
            "overflow_groups": int(overflow_groups),
            "late_dropped": int(late_dropped),
        }
        rec.update(extra)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
        self._export(rec)
        return rec

    def recent(self, n: int = 50) -> list:
        with self._lock:
            items = list(self._ring)
        return items[::-1][: max(0, int(n))]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def _export(self, rec: dict) -> None:
        if self._jsonl_path is None or self._jsonl_dead:
            return
        try:
            if self._jsonl_fh is None:
                self._jsonl_fh = open(self._jsonl_path, "a",
                                      encoding="utf-8")
                try:
                    self._jsonl_bytes = os.path.getsize(self._jsonl_path)
                except OSError:
                    self._jsonl_bytes = 0
            line = json.dumps(rec, separators=(",", ":")) + "\n"
            self._jsonl_fh.write(line)
            self._jsonl_fh.flush()
            # default json is ASCII (ensure_ascii), so chars == bytes
            self._jsonl_bytes += len(line)
            if 0 < self._jsonl_max <= self._jsonl_bytes:
                # size rotation: keep exactly one .1 rollover so the
                # export can never fill the disk on a long-running
                # stream (a rotation failure latches the export dead,
                # same as any other export error)
                self._jsonl_fh.close()
                self._jsonl_fh = None
                os.replace(self._jsonl_path, self._jsonl_path + ".1")
                self._jsonl_bytes = 0
        except OSError as e:
            self._jsonl_dead = True  # log once; never crash the pipeline
            log.warning("trace JSONL export to %s disabled: %s",
                        self._jsonl_path, e)

    def close(self) -> None:
        if self._jsonl_fh is not None:
            try:
                self._jsonl_fh.close()
            except OSError:
                pass
            self._jsonl_fh = None
