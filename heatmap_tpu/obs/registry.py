"""Dependency-free metrics registry with Prometheus text exposition.

The repo's headline claims are quantitative (PAPER.md: ≥5M events/sec,
<500 ms p50 micro-batch latency), so the telemetry substrate must speak
the format the standard tooling scrapes.  This module is the whole
substrate: counters, gauges (optionally callback-backed, evaluated at
collect time), and fixed-bucket histograms, each with optional labels,
plus ``Registry.expose_text()`` producing the Prometheus text exposition
format (``# HELP``/``# TYPE`` + ``_bucket``/``_sum``/``_count`` series).

Design points, chosen for the streaming hot path:

- **No dependencies.**  The container may not have prometheus_client;
  the format is simple enough to emit directly.
- **Per-instance registries.**  A registry belongs to whoever creates it
  (one per MicroBatchRuntime via stream.metrics.Metrics) — no global
  mutable state, so concurrent runtimes in one process (tests!) never
  share counters.  Registration is idempotent per registry: asking for
  an existing (name, type, labels) family returns it.
- **Histograms are cumulative** (Prometheus semantics) *and* keep a
  small bounded window of recent raw samples so ``quantile(q)`` answers
  "recent p50" exactly — that is what /healthz SLOs and the back-compat
  ``snapshot()`` keys need, and what a cumulative histogram alone
  cannot give without PromQL.
- **Locked, but cheap.**  One registry-wide lock; every operation under
  it is a few arithmetic ops.  The step loop observes ~6 values per
  batch — noise next to a device dispatch.
"""

from __future__ import annotations

import bisect
import collections
import math
import threading
from typing import Callable, Iterable, Mapping, Sequence

# Latency-shaped default buckets (seconds): spans 100 µs .. 30 s, dense
# around the paper's 500 ms p50 budget.
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# Freshness / lag-shaped buckets (seconds): 100 ms .. 1 h (replay of old
# captures shows the replay lag, which can be large and is the honest
# answer — see stream.runtime.flush_pending).
DEFAULT_LAG_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0, 900.0, 3600.0,
)


def _fmt(v: float) -> str:
    """Prometheus sample-value rendering: integers without a decimal
    point, floats via repr (shortest round-trip), inf/nan spelled the
    way the exposition format requires."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_suffix(labelnames: Sequence[str], labelvalues: Sequence[str],
                   extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(v)}"'
             for n, v in zip(labelnames, labelvalues)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    """Monotonic counter (one labelset child of a family)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += n


class Gauge:
    """Settable value; ``fn`` makes it callback-backed (read at collect
    time — e.g. a queue depth that lives in someone else's object)."""

    __slots__ = ("_lock", "_value", "fn")

    def __init__(self, lock: threading.Lock,
                 fn: Callable[[], float] | None = None):
        self._lock = lock
        self._value = 0.0
        self.fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:  # a dead callback must not break /metrics
                return float("nan")
        return self._value


class Histogram:
    """Fixed-bucket cumulative histogram + a bounded recent-sample
    window for exact recent quantiles (``quantile``), which the
    Prometheus series intentionally don't provide client-side."""

    __slots__ = ("_lock", "bounds", "bucket_counts", "sum", "count",
                 "samples")

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                 window: int = 512):
        self._lock = lock
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +Inf last
        self.sum = 0.0
        self.count = 0
        self.samples: collections.deque = collections.deque(maxlen=window)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.bucket_counts[bisect.bisect_left(self.bounds, v)] += 1
            self.sum += v
            self.count += 1
            self.samples.append(v)

    # drop-in for the old stream.metrics.Percentiles surface
    add = observe

    def quantile(self, q: float) -> float:
        """Exact quantile over the recent window (same pick rule as the
        pre-obs Percentiles deque: index int(q*n), clamped)."""
        with self._lock:
            s = sorted(self.samples)
        if not s:
            return 0.0
        return s[min(len(s) - 1, int(q * len(s)))]


class _Family:
    """One metric name: help, type, labelnames, children by labelvalues."""

    def __init__(self, name: str, help_: str, mtype: str,
                 labelnames: Sequence[str], make_child, lock):
        self.name = name
        self.help = help_
        self.type = mtype
        self.labelnames = tuple(labelnames)
        self._make_child = make_child
        self._lock = lock
        self.children: dict[tuple, object] = {}
        if not self.labelnames:
            self.children[()] = make_child()

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kw)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kw[n]) for n in self.labelnames)
        # insertion under the registry lock: the scrape thread iterates
        # children while the step loop lazily creates labelsets
        with self._lock:
            child = self.children.get(key)
            if child is None:
                child = self.children[key] = self._make_child()
        return child

    # unlabeled families proxy the single child so callers can write
    # registry.counter(...).inc() without .labels()
    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; use .labels()")
        return self.children[()]

    def inc(self, n: float = 1):
        self._solo().inc(n)

    def set(self, v: float):
        self._solo().set(v)

    def observe(self, v: float):
        self._solo().observe(v)

    add = observe

    def quantile(self, q: float) -> float:
        return self._solo().quantile(q)

    @property
    def value(self):
        return self._solo().value

    @property
    def count(self):
        return self._solo().count

    @property
    def sum(self):
        return self._solo().sum

    @property
    def samples(self):
        return self._solo().samples


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _register(self, name: str, help_: str, mtype: str,
                  labelnames: Sequence[str], make_child) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != mtype or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name} re-registered as {mtype}"
                        f"{tuple(labelnames)} (was {fam.type}"
                        f"{fam.labelnames})")
                return fam
            fam = _Family(name, help_, mtype, labelnames, make_child,
                          self._lock)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._register(name, help_, "counter", labels,
                              lambda: Counter(self._lock))

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = (),
              fn: Callable[[], float] | None = None) -> _Family:
        return self._register(name, help_, "gauge", labels,
                              lambda: Gauge(self._lock, fn=fn))

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                  window: int = 512) -> _Family:
        return self._register(
            name, help_, "histogram", labels,
            lambda: Histogram(self._lock, buckets=buckets, window=window))

    # ------------------------------------------------------- exposition
    def expose_text(self, extra: Iterable[str] = ()) -> str:
        """Prometheus text exposition format (0.0.4).  ``extra`` lines
        (already formatted) are appended — the serve layer uses this to
        merge ad-hoc counter dicts and the supervisor channel."""
        out: list[str] = []
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.type}")
            with self._lock:  # lazy labels() insertions race this walk
                children = sorted(fam.children.items())
            for lv, child in children:
                if fam.type == "histogram":
                    with self._lock:
                        counts = list(child.bucket_counts)
                        s, c = child.sum, child.count
                    cum = 0
                    for bound, n in zip(child.bounds, counts):
                        cum += n
                        suff = _labels_suffix(fam.labelnames, lv,
                                              f'le="{_fmt(bound)}"')
                        out.append(f"{fam.name}_bucket{suff} {cum}")
                    cum += counts[-1]
                    suff = _labels_suffix(fam.labelnames, lv, 'le="+Inf"')
                    out.append(f"{fam.name}_bucket{suff} {cum}")
                    plain = _labels_suffix(fam.labelnames, lv)
                    out.append(f"{fam.name}_sum{plain} {_fmt(s)}")
                    out.append(f"{fam.name}_count{plain} {c}")
                else:
                    suff = _labels_suffix(fam.labelnames, lv)
                    out.append(f"{fam.name}{suff} {_fmt(child.value)}")
        out.extend(extra)
        return "\n".join(out) + "\n"


def render_flat_counters(pairs: Mapping[str, float], prefix: str = "",
                         gauge_names: frozenset = frozenset()) -> list[str]:
    """Ad-hoc name->value dicts (stream.metrics counters, writer
    counters, source counters) rendered as exposition lines.  Names in
    ``gauge_names`` type as gauges; everything else as counters with a
    ``_total`` suffix (the Prometheus naming convention)."""
    out = []
    for name, v in sorted(pairs.items()):
        if not isinstance(v, (int, float)):
            continue
        base = prefix + "".join(
            ch if (ch.isalnum() or ch == "_") else "_" for ch in name)
        if name in gauge_names:
            out.append(f"# TYPE {base} gauge")
            out.append(f"{base} {_fmt(v)}")
        else:
            out.append(f"# TYPE {base}_total counter")
            out.append(f"{base}_total {_fmt(v)}")
    return out
