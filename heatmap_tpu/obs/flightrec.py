"""Flight recorder — crash-time state dump for post-mortem diagnosis.

When a long-running stream dies — injected exception, SIGTERM from an
orchestrator, a poisoned sink — the /metrics and /trace/recent
endpoints die with it, and the operator is left with an exit code.  The
flight recorder closes that gap: on abnormal runtime exit it dumps the
trace-ring tail, the freshness-lineage tail, the metrics snapshot, and
the resolved config to a timestamped ``flightrec-*.json`` under
``HEATMAP_FLIGHTREC_DIR``, so the last seconds before the incident are
diagnosable offline.

Contract (tests/test_lineage.py):

- armed only when ``HEATMAP_FLIGHTREC_DIR`` is set (the config knob);
- a NORMAL close writes nothing unless ``HEATMAP_FLIGHTREC_ALWAYS=1``;
- one dump per recorder (the first reason wins — a SIGTERM that unwinds
  into close() must not write twice);
- sources are callables evaluated at dump time, each guarded: a broken
  source contributes its error string instead of killing the dump;
- the file is written atomically (tmp + rename), so a half-written
  record is impossible even when the process is dying.

Wiring: the runtime dumps from ``close()`` (it knows fatal/poisoned/
unwinding); ``stream/__main__.py`` converts SIGTERM into a SystemExit
so that close() runs (and registers an atexit backstop for exits that
bypass it); the supervisor dumps its OWN view (channel state, failure
reason) when a child dies, via :func:`dump_snapshot`.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time

from heatmap_tpu.obs.lineage import json_safe

log = logging.getLogger(__name__)

ENV_DIR = "HEATMAP_FLIGHTREC_DIR"
ENV_ALWAYS = "HEATMAP_FLIGHTREC_ALWAYS"

# process-wide dump counter: several recorders (runtime + supervisor, or
# repeated child failures) in one second must not collide on a filename
_DUMP_SEQ = itertools.count(1)


class FlightRecorder:
    # dumps retained per directory: a supervised stream that flaps for
    # weeks writes one record per failure, and an unbounded directory
    # is the disk-filling failure mode the trace JSONL rotation exists
    # to prevent — after each dump the oldest files beyond this cap are
    # pruned
    RETAIN = 16

    def __init__(self, dir_path: str):
        self.dir = dir_path
        self._sources: dict = {}
        self._lock = threading.Lock()
        self._dumped: str | None = None  # path of the dump, once written
        self._disarmed = False

    def add_source(self, name: str, fn) -> None:
        """Register ``fn() -> JSON-serializable`` evaluated at dump time."""
        self._sources[name] = fn

    def disarm(self) -> None:
        """A clean close: the atexit backstop must not dump after this."""
        self._disarmed = True

    def spawn(self) -> "FlightRecorder":
        """A fresh recorder sharing this one's directory and sources —
        the SLO watchdog's repeated auto-captures need the once-only
        dump contract PER EPISODE, not per process lifetime."""
        rec = FlightRecorder(self.dir)
        rec._sources = dict(self._sources)
        return rec

    @property
    def dumped(self) -> str | None:
        return self._dumped

    def dump(self, reason: str, episode_id: str | None = None) -> str | None:
        """Write the flight record; returns its path, or None when this
        recorder already dumped / was disarmed / cannot write.  Never
        raises — the recorder runs on dying codepaths.

        ``episode_id`` is the fleet correlation id (obs.xproc episode
        broadcast): every member's dump for one incident carries the
        same id top-level, so post-mortem tooling can collect the dump
        SET for an episode with one grep instead of mtime archaeology."""
        with self._lock:
            if self._dumped is not None or self._disarmed:
                return None
            self._dumped = ""  # claim before the (slow) source walk
        payload = {
            "reason": str(reason)[:500],
            "t_wall": round(time.time(), 3),
            "pid": os.getpid(),
        }
        if episode_id:
            payload["episode_id"] = str(episode_id)
        for name, fn in self._sources.items():
            try:
                payload[name] = json_safe(fn())
            except Exception as e:  # noqa: BLE001 - partial dump > no dump
                payload[name] = f"<source failed: {type(e).__name__}: {e}>"
        stamp = time.strftime("%Y%m%d-%H%M%S")
        fname = (f"flightrec-{stamp}-{os.getpid()}"
                 f"-{next(_DUMP_SEQ)}.json")
        path = os.path.join(self.dir, fname)
        try:
            os.makedirs(self.dir, exist_ok=True)
            from heatmap_tpu.obs.xproc import atomic_write_json

            atomic_write_json(path, payload)
        except (OSError, TypeError, ValueError) as e:
            log.warning("flight record write to %s failed: %s", path, e)
            with self._lock:
                self._dumped = None  # release the claim: the atexit
                # backstop (or a later close) may retry on a dying disk
            return None
        self._dumped = path
        log.error("flight record written: %s (%s)", path, reason)
        self._prune()
        return path

    def _prune(self) -> None:
        """Keep the newest RETAIN flightrec-*.json in the directory."""
        import glob

        try:
            files = sorted(
                glob.glob(os.path.join(glob.escape(self.dir),
                                       "flightrec-*.json")),
                key=os.path.getmtime)
            for p in files[: max(0, len(files) - self.RETAIN)]:
                os.remove(p)
        except OSError:  # retention is best-effort on a dying codepath
            pass


def from_env(env=None) -> FlightRecorder | None:
    """A recorder for ``HEATMAP_FLIGHTREC_DIR``, or None when unset."""
    e = os.environ if env is None else env
    d = e.get(ENV_DIR, "")
    return FlightRecorder(d) if d else None


def dump_snapshot(dir_path: str, reason: str, sources: dict,
                  episode_id: str | None = None) -> str | None:
    """One-shot dump of already-materialized values (the supervisor's
    child-failure hook: it has no live runtime to source from)."""
    rec = FlightRecorder(dir_path)
    for name, value in sources.items():
        rec.add_source(name, lambda v=value: v)
    return rec.dump(reason, episode_id=episode_id)
