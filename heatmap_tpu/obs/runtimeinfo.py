"""Runtime introspection: compile/retrace tracking, device memory
telemetry, and the SLO-triggered auto-capture watchdog.

PRs 1 and 3 made the DATA PATH observable; the accelerator runtime
underneath it stayed a black box.  The two failure modes this module
exists for:

- **silent retraces**: a jitted step re-specializes (shape drift, a
  policy knob flipped mid-run, an accidental weak-type change) and the
  pipeline silently eats seconds of XLA compile per occurrence.  The
  spans show an unexplained ``device``/``pull`` spike; nothing says
  "that was a compile".  LMStream (PAPERS.md) attributes exactly this
  class of micro-batch stall to runtime effects the stream layer can't
  see.
- **HBM creep**: live buffer bytes ratchet up (a leaked reference, ring
  depth growth, a slab resize) until an OOM kills the run with no
  record of the high-water trajectory.

``CompileTracker`` wraps the jitted entry points (engine.multi /
parallel.sharded step functions) and detects compiles by probing the
jit cache size around each call — no global monkeypatching, and the
probe is two attribute reads per step.  A compile observed after a
function's warmup (``HEATMAP_WARMUP_BATCHES`` calls, default 4) is a
RETRACE-AFTER-WARMUP: always legitimate work (slab growth) or a bug
(shape flap), and either way an SLO-relevant event — /healthz degrades
while one is recent (``HEATMAP_SLO_RETRACES`` over the trailing
``HEATMAP_SLO_RETRACE_WINDOW_S``).

``MemoryMonitor`` samples per-device ``memory_stats()`` where the
backend provides it (TPU/GPU) and falls back to summing
``jax.live_arrays()`` bytes (CPU — the tests' backend), keeping a
process-lifetime watermark; ``HEATMAP_SLO_MEM_BYTES`` (default 0 =
disabled) turns the watermark into a /healthz budget.

``SloWatchdog`` closes the loop: a daemon thread re-evaluates the
/healthz verdict every ``HEATMAP_SLO_WATCHDOG_S`` (default 10) and, on
the transition into degraded/down, writes an ENRICHED flight-recorder
dump (trace tail, lineage tail, metrics, config, run state — plus
compile counts, memory watermarks, and the stack-sample tail), so the
incident is diagnosable even when nobody was watching /healthz.  One
dump per episode, ``HEATMAP_SLO_CAPTURE_COOLDOWN_S`` (default 300)
between dumps.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

ENV_WARMUP = "HEATMAP_WARMUP_BATCHES"
ENV_SLO_RETRACES = "HEATMAP_SLO_RETRACES"
ENV_RETRACE_WINDOW = "HEATMAP_SLO_RETRACE_WINDOW_S"
ENV_SLO_MEM = "HEATMAP_SLO_MEM_BYTES"
ENV_WATCHDOG_S = "HEATMAP_SLO_WATCHDOG_S"
ENV_COOLDOWN_S = "HEATMAP_SLO_CAPTURE_COOLDOWN_S"

# compile wall-time buckets: a CPU retrace of the fused fold runs
# 0.1-10 s; TPU compiles reach minutes
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %s", name, raw, default)
        return default


class _FnState:
    __slots__ = ("calls", "compiles", "cache_size", "last_compile_s",
                 "last_retrace_wall")

    def __init__(self):
        self.calls = 0
        self.compiles = 0
        self.cache_size = 0
        self.last_compile_s = 0.0
        self.last_retrace_wall: float | None = None


class CompileTracker:
    """Per-function compile counts / compile seconds / retrace-after-
    warmup detection for jitted entry points, by cache-size probing."""

    def __init__(self, registry, warmup: int | None = None):
        self.warmup = (max(1, int(_env_float(ENV_WARMUP, 4)))
                       if warmup is None else max(1, int(warmup)))
        self._lock = threading.Lock()
        self._fns: dict[str, _FnState] = {}
        # bounded trail of retrace wall times (the /healthz trailing-
        # window check and the snapshot both read it)
        self._retraces: collections.deque = collections.deque(maxlen=256)
        self._c_compiles = registry.counter(
            "heatmap_compile_total",
            "jit cache entries added (traces + XLA compiles) per wrapped "
            "step function", labels=("fn",))
        self._h_compile_s = registry.histogram(
            "heatmap_compile_seconds",
            "wall seconds of the step call that triggered a compile "
            "(trace + compile + first execute)", labels=("fn",),
            buckets=COMPILE_BUCKETS)
        self._c_retrace = registry.counter(
            "heatmap_retrace_after_warmup_total",
            "compiles observed after a step function's warmup "
            "(HEATMAP_WARMUP_BATCHES calls) — slab-growth retraces and "
            "shape/type flaps; each degrades /healthz while recent",
            labels=("fn",))

    @staticmethod
    def _cache_size(fn) -> int | None:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 - probe must never break a step
            return None

    def wrap(self, name: str, fn):
        """Wrap a jitted callable; the wrapper is transparent apart from
        the cache probe + wall clock around each call."""
        st = self._fns.setdefault(name, _FnState())
        st.cache_size = self._cache_size(fn) or 0

        def wrapped(*args, **kwargs):
            t0 = time.monotonic()
            out = fn(*args, **kwargs)
            size = self._cache_size(fn)
            with self._lock:
                st.calls += 1
                if size is not None and size > st.cache_size:
                    n_new = size - st.cache_size
                    st.cache_size = size
                    st.compiles += n_new
                    st.last_compile_s = time.monotonic() - t0
                    self._c_compiles.labels(fn=name).inc(n_new)
                    self._h_compile_s.labels(fn=name).observe(
                        st.last_compile_s)
                    if st.calls > self.warmup:
                        now = time.time()
                        st.last_retrace_wall = now
                        self._retraces.append(now)
                        self._c_retrace.labels(fn=name).inc(n_new)
                        log.warning(
                            "post-warmup retrace of %s (call %d, +%d "
                            "cache entr%s, %.2fs)", name, st.calls,
                            n_new, "y" if n_new == 1 else "ies",
                            st.last_compile_s)
            return out

        wrapped._inner = fn  # tests / debugging reach the jitted fn
        return wrapped

    # ------------------------------------------------------------ reads
    @property
    def retraces_total(self) -> int:
        """Lifetime post-warmup retrace count — the cheap accessor the
        governor's per-step guardrail polls (snapshot() builds the full
        per-function dict and is too heavy for a step-loop check)."""
        with self._lock:
            return len(self._retraces)

    def retraces_recent(self, window_s: float) -> int:
        cut = time.time() - window_s
        with self._lock:
            return sum(1 for t in self._retraces if t >= cut)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "warmup_calls": self.warmup,
                "retraces_after_warmup": len(self._retraces),
                "functions": {
                    name: {
                        "calls": st.calls,
                        "compiles": st.compiles,
                        "last_compile_s": round(st.last_compile_s, 4),
                        "last_retrace_wall": st.last_retrace_wall,
                    } for name, st in self._fns.items()
                },
            }


class MemoryMonitor:
    """Device memory telemetry sampled on the runtime loop.

    Where the backend reports ``memory_stats()`` (TPU/GPU) the
    per-device bytes-in-use / limit / peak land in labeled gauges; on
    backends that don't (CPU) the live-buffer fallback — the summed
    ``nbytes`` of ``jax.live_arrays()`` — carries the same watermark
    contract, so the /healthz budget and the acceptance tests work
    without a real TPU."""

    def __init__(self, registry, ring_bytes_fn=None):
        self._lock = threading.Lock()
        self._device_peak: dict[str, float] = {}
        self._live_peak = 0.0
        self._last_sample = 0.0
        self._g_in_use = registry.gauge(
            "heatmap_device_bytes_in_use",
            "allocator bytes in use per device (backend memory_stats; "
            "absent on backends that don't report it)",
            labels=("device",))
        self._g_limit = registry.gauge(
            "heatmap_device_bytes_limit",
            "allocator byte limit per device (backend memory_stats)",
            labels=("device",))
        self._g_peak = registry.gauge(
            "heatmap_device_hbm_watermark_bytes",
            "high-water of device bytes in use since process start "
            "(max of sampled in-use and the allocator's own peak)",
            labels=("device",))
        self._g_live = registry.gauge(
            "heatmap_live_buffer_bytes",
            "summed nbytes of all live jax arrays in this process "
            "(the device-agnostic fallback the CPU backend gets)")
        self._g_live_peak = registry.gauge(
            "heatmap_live_buffer_watermark_bytes",
            "high-water of live jax array bytes since process start")
        self._g_ring = registry.gauge(
            "heatmap_emit_ring_slab_bytes",
            "bytes of packed emit batches parked on device in the emit "
            "ring (EmitRing slab accounting)",
            fn=ring_bytes_fn)

    def sample(self, min_interval_s: float = 0.0) -> bool:
        """One telemetry sample; rate-limited when ``min_interval_s`` is
        set (the runtime loop calls this per step with 1.0)."""
        now = time.monotonic()
        with self._lock:
            if min_interval_s and now - self._last_sample < min_interval_s:
                return False
            self._last_sample = now
        import jax

        try:
            live = float(sum(a.nbytes for a in jax.live_arrays()))
        except Exception:  # noqa: BLE001 - telemetry never kills a step
            live = 0.0
        with self._lock:
            self._live_peak = max(self._live_peak, live)
            self._g_live.set(live)
            self._g_live_peak.set(self._live_peak)
        try:
            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 - a dying client must not turn
            return True    # telemetry into the step's failure
        for dev in devices:
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001
                stats = None
            if not stats:
                continue
            label = str(getattr(dev, "id", dev))
            in_use = float(stats.get("bytes_in_use", 0))
            peak = float(stats.get("peak_bytes_in_use", in_use))
            with self._lock:
                self._device_peak[label] = max(
                    self._device_peak.get(label, 0.0), in_use, peak)
                self._g_in_use.labels(device=label).set(in_use)
                if "bytes_limit" in stats:
                    self._g_limit.labels(device=label).set(
                        float(stats["bytes_limit"]))
                self._g_peak.labels(device=label).set(
                    self._device_peak[label])
        return True

    @property
    def watermark_bytes(self) -> float:
        """The high-water the /healthz budget compares against: max of
        the per-device peaks, falling back to the live-buffer peak."""
        with self._lock:
            if self._device_peak:
                return max(self._device_peak.values())
            return self._live_peak

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "live_buffer_bytes_peak": self._live_peak,
                "device_peak_bytes": dict(self._device_peak),
                "watermark_bytes": (max(self._device_peak.values())
                                    if self._device_peak
                                    else self._live_peak),
            }


class RuntimeIntrospection:
    """The runtime's introspection bundle: compile tracker + memory
    monitor, one snapshot for the flight recorder."""

    def __init__(self, registry, ring_bytes_fn=None,
                 warmup: int | None = None):
        self.compile = CompileTracker(registry, warmup=warmup)
        self.memory = MemoryMonitor(registry, ring_bytes_fn=ring_bytes_fn)

    def snapshot(self) -> dict:
        return {"compile": self.compile.snapshot(),
                "memory": self.memory.snapshot()}


# ------------------------------------------------------------ healthz
def healthz_checks(runtime) -> tuple[dict, bool]:
    """The runtime-introspection /healthz checks (serve.api merges them
    into the payload): recent post-warmup retraces over budget, and the
    memory watermark over ``HEATMAP_SLO_MEM_BYTES`` when set."""
    checks: dict = {}
    degraded = False
    ri = getattr(runtime, "runtimeinfo", None)
    if ri is None:
        return checks, degraded
    window = _env_float(ENV_RETRACE_WINDOW, 600.0)
    budget = _env_float(ENV_SLO_RETRACES, 0.0)
    recent = ri.compile.retraces_recent(window)
    if recent or budget:
        ok = recent <= budget
        checks["retrace_after_warmup"] = {
            "value": recent, "budget": budget,
            "window_s": window, "ok": ok}
        degraded |= not ok
    mem_budget = _env_float(ENV_SLO_MEM, 0.0)
    if mem_budget > 0:
        wm = ri.memory.watermark_bytes
        ok = wm <= mem_budget
        checks["memory_watermark_bytes"] = {
            "value": wm, "budget": mem_budget, "ok": ok}
        degraded |= not ok
    return checks, degraded


class SloWatchdog:
    """Re-evaluates the /healthz verdict off the request path and
    auto-captures an enriched flight-recorder dump when it degrades.

    **Fleet mode** (a supervisor channel is attached): the first member
    whose verdict transitions into degraded claims ONE episode id on
    the channel (``obs.xproc.broadcast_episode``); every other member's
    watchdog sees the broadcast on its next tick and writes its OWN
    correlated dump under the same id — one incident, one dump set,
    even for members whose local /healthz never budged.  A member
    degrading while an episode is already open JOINS it instead of
    minting a second id.  ``runtime`` may be ``None`` for serve-only /
    sidecar members (the /healthz evaluation then covers the channel
    SLOs only); pass ``flightrec`` explicitly in that case."""

    def __init__(self, runtime, interval_s: float | None = None,
                 cooldown_s: float | None = None, *,
                 channel_path: str | None = None, tag: str | None = None,
                 flightrec=None):
        from heatmap_tpu.obs.xproc import ENV_CHANNEL, ENV_FLEET_TAG

        self.runtime = runtime
        self.interval_s = (_env_float(ENV_WATCHDOG_S, 10.0)
                           if interval_s is None else float(interval_s))
        self.cooldown_s = (_env_float(ENV_COOLDOWN_S, 300.0)
                           if cooldown_s is None else float(cooldown_s))
        self.channel_path = (os.environ.get(ENV_CHANNEL)
                             if channel_path is None else channel_path
                             ) or None
        self.tag = (tag or os.environ.get(ENV_FLEET_TAG)
                    or f"pid{os.getpid()}")
        self._flightrec = flightrec
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._was_bad = False
        self._last_dump = -float("inf")
        # episodes broadcast before this process existed are not ours
        # to correlate: a restarted member's dump would describe healthy
        # post-restart boot state, pure noise in the incident's dump set
        self._boot_unix = time.time()
        # episode ids this member already captured (its own broadcasts
        # included, so the follow path never double-dumps); bounded
        self._episodes_done: collections.deque = collections.deque(
            maxlen=64)
        self.n_captures = 0

    @property
    def flightrec(self):
        return (self._flightrec if self._flightrec is not None
                else getattr(self.runtime, "flightrec", None))

    def start(self) -> bool:
        if self.interval_s <= 0 or self._thread is not None:
            return False
        self._thread = threading.Thread(
            target=self._loop, name="slo-watchdog", daemon=True)
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 - the watchdog never kills
                log.exception("SLO watchdog check failed")

    def check_once(self) -> str | None:
        """One evaluation; returns the dump path when a capture fired.
        One capture per degradation EPISODE — but the episode is only
        claimed once a dump actually lands: a degradation beginning
        inside the cooldown window (or while the disk refuses the
        write) keeps retrying on later ticks instead of silently
        consuming its one transition.  Recovery to ok re-arms.  In
        fleet mode a FOREIGN episode broadcast triggers a correlated
        dump first, even when local /healthz is ok."""
        from heatmap_tpu.serve.api import healthz_payload

        payload, down = healthz_payload(self.runtime)
        bad = down or payload.get("status") == "degraded"
        now = time.monotonic()
        path = self._follow_fleet_episode(payload, now)
        if not bad:
            if self._was_bad and self.channel_path:
                # recovery closes the episode THIS member claimed so the
                # next incident mints a fresh id instead of joining (and
                # being dump-suppressed by) a finished one; an episode
                # some other member originated is left for its owner
                from heatmap_tpu.obs.xproc import clear_episode

                clear_episode(self.channel_path, origin=self.tag)
            self._was_bad = False
            return path
        if self._was_bad or path is not None:
            # already captured — either earlier in this episode or just
            # now under the fleet id (which covers this degradation)
            self._was_bad = True
            return path
        if now - self._last_dump < self.cooldown_s:
            return None
        rec = self.flightrec
        if rec is None:
            return None
        failing = [k for k, c in payload.get("checks", {}).items()
                   if isinstance(c, dict) and not c.get("ok", True)]
        reason = "slo degraded: " + (", ".join(failing)
                                     or payload.get("status", "?"))
        episode = {}
        if self.channel_path:
            from heatmap_tpu.obs.xproc import ensure_episode

            episode = ensure_episode(self.channel_path, self.tag, reason)
            eid = episode.get("episode_id")
            if eid:
                self._episodes_done.append(eid)
                reason = f"{reason} (episode {eid})"
        path = self._dump(rec, payload, reason, episode)
        if path is not None:
            self._was_bad = True
            self._last_dump = now
            self.n_captures += 1
        return path

    def _follow_fleet_episode(self, payload: dict, now: float):
        """Correlated capture for an episode ANOTHER member opened: one
        dump per episode id, under the shared id."""
        if not self.channel_path:
            return None
        from heatmap_tpu.obs.xproc import read_episode

        ep = read_episode(self.channel_path)
        eid = ep.get("episode_id")
        if (not eid or eid in self._episodes_done
                or ep.get("origin") == self.tag):
            return None
        upd = ep.get("updated_unix")
        # compare at the broadcast stamp's OWN precision: updated_unix
        # is round(time.time(), 3), which can round DOWN up to half a
        # millisecond — against a full-precision boot stamp, a
        # broadcast issued microseconds AFTER boot would classify as
        # pre-boot and be skipped forever; a same-millisecond tie goes
        # to dumping (one extra correlated dump beats a silently
        # missing one)
        if isinstance(upd, (int, float)) \
                and upd < round(self._boot_unix, 3):
            # broadcast predates this process (we restarted into an
            # in-flight incident): our dump would describe post-boot
            # state that never saw the incident — skip, once
            self._episodes_done.append(eid)
            return None
        if now - self._last_dump < self.cooldown_s:
            return None
        rec = self.flightrec
        if rec is None:
            # no recorder will ever land this dump — mark done so the
            # tick loop doesn't re-walk the file forever
            self._episodes_done.append(eid)
            return None
        path = self._dump(
            rec, payload,
            f"fleet episode {eid} from {ep.get('origin', '?')}: "
            f"{ep.get('reason', '')}", ep)
        if path is not None:
            self._episodes_done.append(eid)
            self._last_dump = now
            self.n_captures += 1
        return path

    @staticmethod
    def _dump(rec, payload: dict, reason: str, episode: dict):
        snap = rec.spawn()
        snap.add_source("healthz", lambda p=payload: p)
        if episode:
            snap.add_source("episode", lambda e=dict(episode): e)
        return snap.dump(reason, episode_id=episode.get("episode_id"))
