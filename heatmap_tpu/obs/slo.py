"""slo — declarative SLOs with error budgets and burn-rate alerts.

The instant thresholds in /healthz answer "is this value over budget
RIGHT NOW" — they can neither tell a momentary blip from a sustained
burn nor say how much incident budget the day has already spent.  This
module implements the standard SRE answer on top of the telemetry
history (:mod:`obs.tsdb`):

- :class:`SloSpec` — a declarative objective over an EXISTING metric
  family (emit freshness p50, delivered-age p99, serve loop p99, repl
  lag, audit mismatches, post-warmup retraces); each scrape tick
  classifies one sample good/bad against the spec's threshold.
- :class:`SloEngine` — rolling error-budget accounting (bad seconds
  consumed out of ``budget_frac x budget_window_s`` allowed) and
  multi-window multi-burn-rate alerting: a rule fires only when BOTH
  its short window (fast detection) and its long window (confirmation,
  kills one-tick blips) burn faster than its threshold multiple of the
  budget rate — the Google SRE workbook construction, scaled from the
  canonical 30-day windows to ``HEATMAP_SLO_BUDGET_WINDOW_S``.

A firing alert claims/joins ONE fleet episode (obs.xproc — the PR 6
correlation discipline), records a durable event into the tsdb (the
flush happens at fire time, exactly when the process may die next),
enriches the flight-recorder dump with the budget ledger and the
offending series' recent window, and surfaces in /healthz as a
degradation that distinguishes "error budget burning fast" from
"momentary blip — within budget" (a warn, never a degradation).
Recovery resolves the alert and releases an episode this engine
claimed.

Everything rides the recorder's injected clock, so tests script the
error rate and pin the firing tick exactly.
"""

from __future__ import annotations

import logging
import math
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Mapping

log = logging.getLogger(__name__)

ENV_BUDGET_FRAC = "HEATMAP_SLO_BUDGET_FRAC"
ENV_BUDGET_WINDOW = "HEATMAP_SLO_BUDGET_WINDOW_S"
ENV_SERVE_P99_MS = "HEATMAP_SLO_SERVE_P99_MS"
ENV_DELIVERED_P99_MS = "HEATMAP_SLO_DELIVERED_P99_MS"


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclass(frozen=True)
class SloSpec:
    """One objective.  ``kind``:

    - ``gauge`` — the latest sample is bad when ``> threshold``;
    - ``counter`` — the reset-aware increase since the previous tick
      is bad when ``> threshold`` (0 = any increase is bad);
    - ``quantile`` — the interpolated quantile of the histogram's
      traffic SINCE the previous tick (cumulative-bucket diff) is bad
      when ``> threshold``; a tick with no traffic contributes no
      sample (no data is neither good nor bad).

    ``op`` flips the badness direction for objectives where LOWER is
    worse (the quality observatory's forecast-skill floor): ``"gt"``
    (default) marks a sample bad when it exceeds the threshold,
    ``"lt"`` when it falls below.  For multi-series gauges the
    aggregate follows the direction too — worst case is the max for
    ``gt``, the min for ``lt``.
    """

    name: str
    kind: str
    series: str
    threshold: float
    q: float = 0.5
    labels: tuple = ()
    op: str = "gt"

    def label_map(self) -> dict:
        return dict(self.labels)


@dataclass(frozen=True)
class BurnRule:
    """One multi-window burn-rate rule: fires when BOTH windows burn
    at >= ``burn`` times the budget rate."""

    name: str
    short_s: float
    long_s: float
    burn: float
    severity: str = "page"


def default_specs(env: Mapping[str, str] | None = None) -> tuple:
    """The declarative registry over today's families.  Thresholds
    reuse the /healthz SLO knobs where one exists, so the instant
    check and the budgeted check disagree only about duration, never
    about the objective."""
    e = os.environ if env is None else env

    def f(name, default):
        try:
            return float(e.get(name, default))
        except (TypeError, ValueError):
            return default

    return (
        SloSpec("freshness_p50", "quantile", "heatmap_event_age_seconds",
                f("HEATMAP_SLO_FRESHNESS_P50_MS", 10000.0) / 1000.0,
                q=0.5),
        SloSpec("delivered_p99", "quantile",
                "heatmap_delivered_age_seconds",
                f(ENV_DELIVERED_P99_MS, 5000.0) / 1000.0, q=0.99),
        SloSpec("serve_p99", "quantile",
                "heatmap_serve_loop_iteration_seconds",
                f(ENV_SERVE_P99_MS, 250.0) / 1000.0, q=0.99),
        SloSpec("repl_lag", "gauge", "heatmap_repl_lag_seconds",
                f("HEATMAP_SLO_REPL_LAG_S", 10.0)),
        SloSpec("audit_mismatch", "counter",
                "heatmap_audit_digest_mismatch_total", 0.0),
        SloSpec("retraces", "counter",
                "heatmap_retrace_after_warmup_total", 0.0),
        # quality-drift objectives (obs.quality, HEATMAP_QUALITY=1):
        # inert when the observatory is off — the series never exist,
        # so no tick produces a sample.  Skill is the first
        # lower-is-worse objective (op="lt": a forecast WORSE than the
        # configured floor burns budget); band error is a distance
        # (0 inside the band), so any positive sample is bad.
        SloSpec("forecast_skill", "gauge",
                "heatmap_quality_forecast_skill",
                f("HEATMAP_SLO_FORECAST_SKILL", 0.0), op="lt"),
        SloSpec("nis_band", "gauge",
                "heatmap_quality_nis_band_error", 0.0),
    )


def default_rules(budget_window_s: float,
                  scrape_s: float) -> tuple:
    """The canonical 30-day page/ticket window pairs (5m+1h @ 14.4x,
    30m+6h @ 6x) scaled linearly to the configured budget window, and
    clamped so a window always spans >= 2 scrape ticks."""
    lo = 2.0 * scrape_s

    def w(canon_s: float) -> float:
        return max(lo, canon_s * budget_window_s / (30.0 * 86400.0))

    return (
        BurnRule("fast", w(300.0), w(3600.0), 14.4, "page"),
        BurnRule("slow", w(1800.0), w(21600.0), 6.0, "ticket"),
    )


@dataclass
class _SpecState:
    samples: deque = field(default_factory=deque)   # (t, bad01)
    prev_totals: dict = field(default_factory=dict)  # counter kind
    prev_buckets: dict = field(default_factory=dict)  # quantile kind
    last_t: float = 0.0
    last_value: float | None = None
    last_bad: bool = False
    firing: str | None = None        # rule name while an alert is open
    severity: str | None = None
    episode: str | None = None
    episode_claimed: bool = False
    alerts_total: int = 0
    worst_burn: float = 0.0


class SloEngine:
    """Burn-rate evaluation driven by a :class:`TsdbRecorder`'s scrape
    ticks (``recorder.add_listener``): same thread, same clock."""

    def __init__(self, recorder, *, registry=None, tag: str = "",
                 specs=None, rules=None,
                 budget_frac: float | None = None,
                 budget_window_s: float | None = None,
                 channel_path: str | None = None, flightrec=None):
        self.rec = recorder
        self.tag = str(tag or recorder.tag)
        self.budget_frac = float(
            budget_frac if budget_frac is not None
            else _env_f(ENV_BUDGET_FRAC, 0.01))
        self.budget_window_s = float(
            budget_window_s if budget_window_s is not None
            else _env_f(ENV_BUDGET_WINDOW, 86400.0))
        self.specs = tuple(specs if specs is not None
                           else default_specs())
        self.rules = tuple(rules if rules is not None
                           else default_rules(self.budget_window_s,
                                              recorder.scrape_s))
        self.channel_path = channel_path
        self.flightrec = flightrec
        maxn = max(8, int(math.ceil(
            self.budget_window_s / max(recorder.scrape_s, 1e-6))) + 1)
        self._state = {s.name: _SpecState(
            samples=deque(maxlen=min(maxn, 200_000)))
            for s in self.specs}
        if flightrec is not None:
            flightrec.add_source("slo", self.snapshot)
        if registry is not None:
            self._m_bad = registry.counter(
                "heatmap_slo_bad_samples_total",
                "scrape ticks classified bad against the SLO's "
                "threshold (the error-budget spend unit)",
                labels=("slo",))
            self._m_alerts = registry.counter(
                "heatmap_slo_alerts_total",
                "burn-rate alerts fired (both windows of a rule over "
                "its threshold multiple of the budget rate)",
                labels=("slo", "severity"))
            self._m_firing = registry.gauge(
                "heatmap_slo_alert_firing",
                "1 while a burn-rate alert is open for the SLO "
                "(resolves when no rule's window pair trips)",
                labels=("slo",))
            self._m_burn = registry.gauge(
                "heatmap_slo_burn_rate",
                "current burn-rate multiple over the fastest rule's "
                "short window (1.0 = exactly the budget rate)",
                labels=("slo",))
            self._m_budget = registry.gauge(
                "heatmap_slo_budget_remaining_frac",
                "fraction of the rolling HEATMAP_SLO_BUDGET_WINDOW_S "
                "error budget not yet consumed", labels=("slo",))
        else:
            self._m_bad = self._m_alerts = self._m_firing = None
            self._m_burn = self._m_budget = None
        recorder.add_listener(self.evaluate)

    # ------------------------------------------------------ observation
    def _observe(self, spec: SloSpec, st: _SpecState, t: float):
        """(value, has_sample) for this tick from the recorder rings."""
        keys = self.rec.match(spec.series, spec.label_map())
        if spec.kind == "gauge":
            vals = []
            for k in keys:
                p = self.rec.latest(k)
                if p is not None and p[0] >= t - self.rec.scrape_s * 1.5:
                    vals.append(p[1])
            if not vals:
                return (None, False)
            # worst case across series follows the badness direction
            return (min(vals) if spec.op == "lt" else max(vals), True)
        if spec.kind == "counter":
            total_inc = 0.0
            seen = False
            for k in keys:
                p = self.rec.latest(k)
                if p is None:
                    continue
                seen = True
                prev = st.prev_totals.get(k)
                cur = p[1]
                if prev is not None:
                    total_inc += cur - prev if cur >= prev else cur
                st.prev_totals[k] = cur
            return (total_inc, seen)
        # quantile: diff the cumulative buckets of the histogram's
        # _bucket series since the previous tick; reset-aware (a bucket
        # going backwards means the writer restarted — the new
        # cumulative IS the window)
        cums: dict = {}
        bucket_keys = self.rec.match(spec.series + "_bucket",
                                     spec.label_map())
        any_traffic = False
        for k in bucket_keys:
            p = self.rec.latest(k)
            if p is None:
                continue
            _name, lbls = self.rec.parsed(k)
            le = lbls.get("le")
            if le is None:
                continue
            try:
                bound = float(le.replace("+Inf", "inf"))
            except ValueError:
                continue
            cur = p[1]
            prev = st.prev_buckets.get(k, 0.0)
            if cur < prev:
                prev = 0.0
            st.prev_buckets[k] = cur
            d = cur - prev
            cums[bound] = cums.get(bound, 0.0) + d
            if d > 0:
                any_traffic = True
        if not any_traffic:
            return (None, False)
        from heatmap_tpu.obs.fleet import interp_quantile

        v = interp_quantile(cums, spec.q)
        return (v, v is not None)

    @staticmethod
    def _bad_frac(samples: deque, now: float, window: float) -> float:
        n = bad = 0
        for t, b in reversed(samples):
            if t <= now - window:
                break
            n += 1
            bad += b
        return bad / n if n else 0.0

    # ------------------------------------------------------- evaluation
    def evaluate(self, t: float) -> None:
        for spec in self.specs:
            st = self._state[spec.name]
            try:
                self._eval_spec(spec, st, t)
            except Exception:  # noqa: BLE001 - never kill the sampler
                log.warning("slo eval failed for %s", spec.name,
                            exc_info=True)
        self._persist()

    def _eval_spec(self, spec: SloSpec, st: _SpecState,
                   t: float) -> None:
        value, has = self._observe(spec, st, t)
        if not has:
            return
        bad = (value < spec.threshold if spec.op == "lt"
               else value > spec.threshold)
        st.samples.append((t, 1 if bad else 0))
        st.last_t, st.last_value, st.last_bad = t, value, bad
        if bad and self._m_bad is not None:
            self._m_bad.labels(slo=spec.name).inc()
        tripped = None
        burn_now = 0.0
        for rule in self.rules:
            bs = self._bad_frac(st.samples, t, rule.short_s) \
                / self.budget_frac
            bl = self._bad_frac(st.samples, t, rule.long_s) \
                / self.budget_frac
            burn_now = max(burn_now, min(bs, bl))
            st.worst_burn = max(st.worst_burn, min(bs, bl))
            if tripped is None and bs >= rule.burn and bl >= rule.burn:
                tripped = (rule, bs, bl)
        if self._m_burn is not None:
            self._m_burn.labels(slo=spec.name).set(round(burn_now, 4))
            self._m_budget.labels(slo=spec.name).set(
                round(self.budget(spec.name)["remaining_frac"], 4))
        if tripped is not None and st.firing is None:
            self._fire(spec, st, t, *tripped)
        elif tripped is None and st.firing is not None:
            self._resolve(spec, st, t)
        if self._m_firing is not None:
            self._m_firing.labels(slo=spec.name).set(
                1 if st.firing else 0)

    # ------------------------------------------------------ transitions
    def _fire(self, spec: SloSpec, st: _SpecState, t: float,
              rule: BurnRule, burn_short: float,
              burn_long: float) -> None:
        st.firing, st.severity = rule.name, rule.severity
        st.alerts_total += 1
        if self._m_alerts is not None:
            self._m_alerts.labels(slo=spec.name,
                                  severity=rule.severity).inc()
        eid = None
        if self.channel_path:
            from heatmap_tpu.obs.xproc import ensure_episode

            ep = ensure_episode(self.channel_path, self.tag,
                                f"slo burn: {spec.name} "
                                f"{burn_short:.1f}x/{burn_long:.1f}x")
            eid = ep.get("episode_id") or None
            st.episode = eid
            st.episode_claimed = bool(
                eid and ep.get("origin") == self.tag)
        ev = {"t": t, "kind": "slo_alert", "slo": spec.name,
              "rule": rule.name, "severity": rule.severity,
              "burn_short": round(burn_short, 3),
              "burn_long": round(burn_long, 3),
              "value": st.last_value,
              "threshold": spec.threshold,
              "budget": self.budget(spec.name)}
        if eid:
            ev["episode"] = eid
        self.rec.record_event(ev)
        self.rec.flush()        # durable NOW — this is the incident
        if self.flightrec is not None:
            # per-episode once-only dump, enriched by the "slo" source
            # registered at construction (budget ledger + offending
            # series window)
            self.flightrec.spawn().dump(
                f"slo-burn:{spec.name}:{rule.name}", episode_id=eid)

    def _resolve(self, spec: SloSpec, st: _SpecState,
                 t: float) -> None:
        ev = {"t": t, "kind": "slo_resolve", "slo": spec.name,
              "rule": st.firing, "budget": self.budget(spec.name)}
        if st.episode:
            ev["episode"] = st.episode
        self.rec.record_event(ev)
        self.rec.flush()
        if st.episode_claimed and self.channel_path:
            from heatmap_tpu.obs.xproc import clear_episode

            clear_episode(self.channel_path, origin=self.tag)
        st.firing = st.severity = None
        st.episode, st.episode_claimed = None, False

    # --------------------------------------------------------- surfaces
    def budget(self, name: str) -> dict:
        """The rolling error-budget ledger for one SLO: seconds of
        badness allowed in the window vs consumed (bad ticks x scrape
        step)."""
        st = self._state[name]
        total = self.budget_frac * self.budget_window_s
        consumed = sum(b for _t, b in st.samples) * self.rec.scrape_s
        remaining = max(0.0, total - consumed)
        return {
            "window_s": self.budget_window_s,
            "budget_frac": self.budget_frac,
            "budget_s": round(total, 3),
            "consumed_s": round(consumed, 3),
            "remaining_s": round(remaining, 3),
            "remaining_frac": round(remaining / total, 6)
            if total > 0 else 0.0,
        }

    def healthz_checks(self) -> dict:
        """Check blocks merged into /healthz.  A firing burn-rate
        alert DEGRADES ("budget burning fast"); a bad latest sample
        without a tripped rule is a warn ("momentary blip") — visible,
        never down."""
        out = {}
        for spec in self.specs:
            st = self._state[spec.name]
            if st.last_value is None:
                continue
            key = f"slo_{spec.name}"
            check = {"value": round(float(st.last_value), 6),
                     "budget": spec.threshold,
                     "ok": st.firing is None}
            if st.firing is not None:
                check["detail"] = (
                    f"error budget burning fast (rule={st.firing}, "
                    f"severity={st.severity}, consumed="
                    f"{self.budget(spec.name)['consumed_s']}s of "
                    f"{self.budget(spec.name)['budget_s']}s)")
            elif st.last_bad:
                check["warn"] = True
                check["detail"] = ("momentary blip — within error "
                                   "budget, no burn rule tripped")
            out[key] = check
        return out

    def snapshot(self) -> dict:
        """The flight-record enrichment: every spec's budget ledger +
        alert state, and the offending series' recent window for any
        firing spec."""
        specs = {}
        offending = {}
        for spec in self.specs:
            st = self._state[spec.name]
            specs[spec.name] = {
                "kind": spec.kind, "series": spec.series,
                "threshold": spec.threshold,
                "last_value": st.last_value,
                "last_bad": st.last_bad,
                "firing": st.firing, "severity": st.severity,
                "episode": st.episode,
                "alerts_total": st.alerts_total,
                "worst_burn": round(st.worst_burn, 3),
                "budget": self.budget(spec.name),
            }
            if st.firing is not None:
                horizon = max(r.long_s for r in self.rules)
                win = {}
                for k in self.rec.match(spec.series, spec.label_map()):
                    win[k] = self.rec.window(k, st.last_t - horizon)
                offending[spec.name] = win
        return {"tag": self.tag, "specs": specs,
                "offending": offending,
                "rules": [vars(r) for r in self.rules]}

    def _persist(self) -> None:
        """slo-state.json next to the member's tsdb blocks (atomic),
        so bench runs stamp budget/burn provenance cross-process."""
        if self.rec.dir is None:
            return
        from heatmap_tpu.obs.xproc import atomic_write_json

        specs = {}
        worst = 0.0
        alerts = 0
        for spec in self.specs:
            st = self._state[spec.name]
            b = self.budget(spec.name)
            specs[spec.name] = {
                "firing": st.firing,
                "alerts_total": st.alerts_total,
                "worst_burn": round(st.worst_burn, 3),
                "consumed_s": b["consumed_s"],
                "budget_s": b["budget_s"],
                "remaining_frac": b["remaining_frac"],
            }
            worst = max(worst, st.worst_burn)
            alerts += st.alerts_total
        mdir = os.path.join(self.rec.dir, self.tag)
        try:
            os.makedirs(mdir, exist_ok=True)
            atomic_write_json(os.path.join(mdir, "slo-state.json"), {
                "tag": self.tag,
                "updated_unix": round(float(self.rec.clock()), 3),
                "alerts_fired_total": alerts,
                "worst_burn": round(worst, 3),
                "budget_consumed_frac": round(max(
                    (1.0 - s["remaining_frac"] for s in specs.values()),
                    default=0.0), 6),
                "specs": specs,
            })
        except OSError:
            log.warning("slo state persist failed", exc_info=True)


def slo_stamp(dir_path: str | None = None,
              env: Mapping[str, str] | None = None) -> dict:
    """The ``slo`` artifact block bench.py / tools/bench_serve.py /
    tools/bench_history.py stamp when the telemetry history ran during
    the round: budget consumed, worst burn-rate multiple, and alerts
    fired, aggregated over every member's persisted slo-state.json.

    {} when HEATMAP_TSDB is off — a knob-off artifact stays
    byte-compatible with pre-tsdb rounds.  Refusal provenance:
    tools/check_bench_regress.py REFUSES an artifact whose run fired a
    burn-rate alert (a number earned while the pipeline was violating
    its own SLOs must never become the bar), and refuses mixed
    tsdb-knob pairs."""
    from heatmap_tpu.obs.tsdb import ENV_DIR, tsdb_enabled

    e = os.environ if env is None else env
    if not tsdb_enabled(e):
        return {}
    d = dir_path if dir_path is not None else e.get(ENV_DIR, "")
    out = {"enabled": True, "alerts_fired": 0, "worst_burn": 0.0,
           "budget_consumed_frac": 0.0, "members": 0}
    if d:
        import glob as _glob
        import json as _json

        for p in sorted(_glob.glob(os.path.join(
                _glob.escape(d), "*", "slo-state.json"))):
            try:
                with open(p, "r", encoding="utf-8") as fh:
                    st = _json.load(fh)
            except (OSError, ValueError):
                continue
            if not isinstance(st, dict):
                continue
            out["members"] += 1
            out["alerts_fired"] += int(st.get("alerts_fired_total", 0))
            out["worst_burn"] = max(out["worst_burn"],
                                    float(st.get("worst_burn", 0.0)))
            out["budget_consumed_frac"] = max(
                out["budget_consumed_frac"],
                float(st.get("budget_consumed_frac", 0.0)))
    return {"slo": out}
