"""Per-batch freshness lineage: event time → sink-commit ack, staged.

The paper's headline claims are end-to-end (<500 ms p50 micro-batch
latency, real-time freshness of the served heatmap), but the per-stage
span telemetry stopped being end-to-end the moment the feed stage ran
AHEAD of the fold (prefetch) and packed emits started PARKING on device
(engine.step.EmitRing): a batch's wall-time spans describe work, not how
stale its events are when they finally reach the sink.  GeoFlink and
LMStream (PAPERS.md) both report ingest-to-availability latency as the
quantity a streaming spatial system must publish — this module is that
substrate.

One ``LineageRecord`` (a plain JSON-friendly dict) is opened per polled
batch and stamped at every stage boundary with ONE shared clock, so the
decomposition telescopes exactly:

    event ts --poll_wait--> poll --prefetch_queue--> dispatch
      --fold--> ring-enter --ring--> flush/pull --sink_commit--> ack

    age(mean event ts -> ack) == poll_wait + prefetch_queue + fold
                                 + ring + sink_commit      (exactly)

The tracker keeps a bounded tail of CLOSED (sink-acked) records for
``/debug/freshness`` and the flight recorder, plus the newest committed
event timestamp the serving layer samples into the ingest→serve
freshness gauge.  The clock is injectable so tests can prove the
conservation property with a synthetic clock.

Stamping is lock-free on the record itself: each stage has a single
owner (step thread through the flush, writer thread for the commit ack)
and the writer queue is the happens-before edge between them.  Only the
tail append and the newest-committed watermark take the tracker lock.
"""

from __future__ import annotations

import collections
import threading
import time

# Stage keys, in pipeline order (the decomposition /debug/freshness and
# the conservation test enumerate).  view_apply is the cross-process
# extension stage: time from the sink-commit ack until the batch is
# visible in a materialized tile view — stamped by the process that
# applies the view (the writer-fed view in-process today; a replicated
# serve worker in the scale-out shape), and stitched into the fleet
# decomposition by lineage id (obs.fleet).  Records without a view
# stay 5-stage; conservation holds over whichever stages exist.
STAGES = ("poll_wait", "prefetch_queue", "fold", "ring", "sink_commit",
          "view_apply")


def json_safe(obj):
    """Best-effort conversion to JSON-serializable types: numpy scalars
    via ``.item()``, containers recursively, anything else via repr.
    Lineage records carry source offsets (arbitrary per-source objects)
    and must stay dump-able for /debug/freshness and flightrec."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    item = getattr(obj, "item", None)
    if item is not None and getattr(obj, "shape", None) == ():
        try:
            return item()  # numpy scalar
        except Exception:  # noqa: BLE001 - fall through to repr
            pass
    if isinstance(obj, dict):
        return {str(k): json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return repr(obj)


class LineageTracker:
    """Opens, stamps, and retains per-batch freshness lineage records."""

    def __init__(self, capacity: int = 256, clock=time.time,
                 origin: str = "local"):
        self.clock = clock
        # the lineage-id namespace: records are stamped
        # ``lid="<origin>-<seq>"`` so contributions from DIFFERENT
        # processes (a runtime shard's fold stages, a serve worker's
        # view-apply stage) stitch back together in the fleet
        # aggregator.  The runtime passes its fleet tag; "local" keeps
        # standalone trackers unique-enough within one process.
        self.origin = str(origin)
        self._lock = threading.Lock()
        self._seq = 0
        self._tail: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))
        self._newest_committed_ts: float | None = None

    # ------------------------------------------------------------ stages
    def open(self, *, n_events: int, ev_min_ts: int, ev_max_ts: int,
             ev_mean_ts: float, offset=None,
             t_poll: float | None = None) -> dict:
        """Create a record at poll time (t_poll = now).  ``t_poll``
        overrides the stamp for rows fetched by an EARLIER poll — a
        carry-drained overshoot tail must bill its wait since that poll
        as queue time, not hide it inside poll_wait."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        return {
            "seq": seq,
            "lid": f"{self.origin}-{seq}",  # cross-process stitch key
            "epoch": None,              # stamped at dispatch
            "n_events": int(n_events),
            "ev_min_ts": int(ev_min_ts),
            "ev_max_ts": int(ev_max_ts),
            "ev_mean_ts": float(ev_mean_ts),
            "offset": json_safe(offset),
            "t_poll": self.clock() if t_poll is None else float(t_poll),
        }

    def dispatched(self, rec: dict, epoch: int) -> None:
        """The batch left the prefetch queue and entered the fold."""
        rec["epoch"] = int(epoch)
        rec["t_dispatch"] = self.clock()

    def ring_entered(self, rec: dict) -> None:
        """The fold dispatched; its packed emits parked in the EmitRing."""
        rec["t_ring"] = self.clock()

    def flushed(self, rec: dict, ring_batches: int | None = None) -> None:
        """The flush covering this batch pulled it off the device."""
        rec["t_flush"] = self.clock()
        if ring_batches is not None:
            rec["ring_batches"] = int(ring_batches)

    def committed(self, rec: dict) -> dict:
        """Sink-commit ack: close the record — derive the per-stage
        decomposition and event ages, append to the tail, and advance
        the newest-committed event-time watermark.  Returns ``rec``."""
        t_sink = rec["t_sink"] = self.clock()
        rec["stages"] = {
            "poll_wait": rec["t_poll"] - rec["ev_mean_ts"],
            "prefetch_queue": rec["t_dispatch"] - rec["t_poll"],
            "fold": rec["t_ring"] - rec["t_dispatch"],
            "ring": rec["t_flush"] - rec["t_ring"],
            "sink_commit": t_sink - rec["t_flush"],
        }
        rec["age_s"] = {
            # ages keyed by which event of the batch they describe: the
            # oldest event (min ts) has aged the most by ack time
            "oldest": t_sink - rec["ev_min_ts"],
            "mean": t_sink - rec["ev_mean_ts"],
            "newest": t_sink - rec["ev_max_ts"],
        }
        with self._lock:
            self._tail.append(rec)
            if (self._newest_committed_ts is None
                    or rec["ev_max_ts"] > self._newest_committed_ts):
                self._newest_committed_ts = rec["ev_max_ts"]
        return rec

    def view_applied(self, rec: dict, view_seq=None) -> dict:
        """The materialized view covering this batch is applied: stamp
        the ``view_apply`` stage (ack → view-visible) and the visible
        age.  In the writer-fed view the apply completes before the ack
        returns, so in-process this stage measures ~0 — its value is
        the FORMAT: a replicated serve worker (ROADMAP item 1) stamps
        its own view_applied on delta arrival, and the fleet stitch
        (obs.fleet) merges it under the same lineage id.  Called on the
        writer thread after :meth:`committed`; mutations run under the
        tracker lock because the record is already in the tail."""
        with self._lock:
            t_view = rec["t_view"] = self.clock()
            if "stages" in rec:
                rec["stages"]["view_apply"] = t_view - rec["t_sink"]
                rec["age_s"]["visible"] = t_view - rec["ev_mean_ts"]
            if view_seq is not None:
                rec["view_seq"] = int(view_seq)
        return rec

    # ------------------------------------------------------------ reads
    @property
    def newest_committed_ts(self) -> float | None:
        """Max event timestamp across sink-acked batches — what the
        ingest→serve freshness gauge subtracts from render wall time."""
        with self._lock:
            return self._newest_committed_ts

    def newest_event_age_s(self, now: float | None = None) -> float:
        """Age of the newest sink-acked event right now — the
        ``event_age`` leg the delivery lineage (obs.delivery) seeds its
        telescoping decomposition with.  O(1): one watermark read, no
        tail scan.  0.0 before any commit (the leg is simply absent,
        not negative)."""
        with self._lock:
            ts = self._newest_committed_ts
        if ts is None:
            return 0.0
        t = self.clock() if now is None else float(now)
        return max(0.0, t - ts)

    def tail(self, n: int = 50) -> list:
        """Newest-first closed records.  Copies are taken UNDER the
        tracker lock, and the nested ``stages``/``age_s`` dicts are
        copied too: :meth:`view_applied` mutates records already in the
        tail (under the same lock), so a shallow copy handed out here
        would share dicts a writer-thread callback is still inserting
        into — and callers serialize these outside any lock."""
        out = []
        with self._lock:
            for r in list(self._tail)[::-1][: max(0, int(n))]:
                c = dict(r)
                for k in ("stages", "age_s"):
                    if k in c:
                        c[k] = dict(c[k])
                out.append(c)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._tail)
