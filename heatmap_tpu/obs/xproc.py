"""Cross-process supervisor→child metrics channel (file-backed).

The supervisor (stream/supervisor.py) runs the streaming job as a child
process; the child owns the HTTP /metrics endpoint.  Without a channel,
the supervisor's restart/backoff/failover counters — exactly the
telemetry that explains "why did the stream blip" — are invisible to
scrapes, and everything resets when the child dies.

This channel is a single small JSON file written atomically
(tmp + rename) by the supervisor and read by anyone holding the path:

- the supervisor passes the path to the child via
  ``HEATMAP_SUPERVISOR_CHANNEL`` in its env, so the child's /metrics can
  merge ``supervisor_*`` series into its exposition;
- counters survive child restarts trivially (the parent owns them), and
  survive *supervisor* restarts too: ``SupervisorChannel.load()`` at
  startup resumes the persisted totals.

A file (not a pipe/socket) because the reader must never block the
writer, a half-written read must be impossible (rename is atomic on
POSIX), and stale data must be detectable (``updated_unix`` rides in the
payload).  mmap would save a syscall per scrape — not worth the
portability trade at a 1/scrape read rate.
"""

from __future__ import annotations

import json
import logging
import os
import time

log = logging.getLogger(__name__)

ENV_CHANNEL = "HEATMAP_SUPERVISOR_CHANNEL"

# numeric fields exported to /metrics as supervisor_* series; everything
# else in the payload (reason strings, timestamps) serves /trace-style
# debugging via /metrics.json
COUNTER_FIELDS = ("restarts_total", "failures_total", "stalls_total",
                  "failovers_total")
GAUGE_FIELDS = ("failed_over", "backoff_s", "gave_up",
                "recent_failures", "child_running")

# Per-child freshness summary keys (obs.lineage): each CHILD runtime
# publishes these into a sibling file next to the channel
# (``<channel>.fresh-<tag>``, tag = "p<process_index>"), so the process
# that owns /metrics — the child itself, a serve-only process, or a
# multi-host parent holding the same channel path — exposes per-child
# freshness as ``heatmap_child_<key>{child="<tag>"}`` gauges.  Lineage
# itself stays host-local; only this summary crosses processes.
FRESHNESS_FIELDS = ("event_age_p50_s", "event_age_p99_s",
                    "ring_residency_mean_s")


def atomic_write_json(path: str, payload: dict) -> None:
    """THE tmp+rename JSON write (channel, child freshness, flight
    records all use it): a reader can never see a half-written file;
    the tmp is cleaned up on failure and the error re-raised for the
    caller to contextualize."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, separators=(",", ":"))
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def child_freshness_path(channel_path: str, tag: str) -> str:
    return f"{channel_path}.fresh-{tag}"


def publish_child_freshness(channel_path: str, tag: str,
                            summary: dict) -> None:
    """Atomic write of one child's freshness summary next to the
    channel; unwritable degrades to a warning (telemetry must never
    take the pipeline down)."""
    payload = {k: summary[k] for k in FRESHNESS_FIELDS
               if isinstance(summary.get(k), (int, float))}
    payload["updated_unix"] = round(time.time(), 3)
    try:
        atomic_write_json(child_freshness_path(channel_path, tag), payload)
    except OSError as e:
        log.warning("child freshness publish failed: %s", e)


def child_freshness_from(channel_path: str | None,
                         max_age_s: float = 900.0) -> dict:
    """{tag: summary dict} for every published child next to the
    channel; {} when no channel / none published.  Summaries whose
    ``updated_unix`` is older than ``max_age_s`` are dropped — a dead
    child's last file must not keep exporting a frozen-green freshness
    gauge forever (staleness is detectable, per the channel contract)."""
    if not channel_path:
        return {}
    import glob

    now = time.time()
    out = {}
    for p in sorted(glob.glob(glob.escape(channel_path) + ".fresh-*")):
        tag = p.rsplit(".fresh-", 1)[1]
        if ".tmp" in tag:  # in-flight atomic write of any publisher
            continue
        d = SupervisorChannel.load(p)
        upd = d.get("updated_unix")
        if not isinstance(upd, (int, float)) or now - upd > max_age_s:
            continue
        out[tag] = d
    return out


class SupervisorChannel:
    def __init__(self, path: str):
        self.path = path
        self.state: dict = {
            "restarts_total": 0,
            "failures_total": 0,
            "stalls_total": 0,
            "failovers_total": 0,
            "failed_over": 0,
            "gave_up": 0,
            "child_running": 0,
            "backoff_s": 0.0,
            "failure_times": [],     # wall clock of recent failures
            "last_reason": "",
            "started_unix": round(time.time(), 3),
            "updated_unix": 0.0,
        }

    def resume(self) -> "SupervisorChannel":
        """Fold persisted TOTALS back in (a restarted supervisor keeps
        counting where its predecessor stopped).  Point-in-time flags
        (gave_up, failed_over, child_running, backoff_s) deliberately do
        NOT resume: they describe the predecessor process — a fresh
        supervisor is actively supervising again, and carrying a stale
        gave_up=1 would pin /healthz at down (503) forever."""
        prior = self.load(self.path)
        for k in COUNTER_FIELDS:
            if isinstance(prior.get(k), (int, float)):
                self.state[k] = prior[k]
        if isinstance(prior.get("failure_times"), list):
            self.state["failure_times"] = [
                float(t) for t in prior["failure_times"][-64:]
                if isinstance(t, (int, float))]
        return self

    def update(self, **fields) -> None:
        self.state.update(fields)
        self.publish()

    def note_failure(self, reason: str, stalled: bool = False,
                     window_s: float = 3600.0) -> None:
        now = time.time()
        ft = [t for t in self.state["failure_times"] if now - t <= window_s]
        ft.append(now)
        self.state["failure_times"] = ft[-64:]
        self.state["failures_total"] += 1
        if stalled:
            self.state["stalls_total"] += 1
        self.state["last_reason"] = str(reason)[:200]
        self.publish()

    def publish(self) -> None:
        """Atomic write; an unwritable channel degrades to a warning —
        telemetry must never take the supervisor down."""
        self.state["updated_unix"] = round(time.time(), 3)
        try:
            atomic_write_json(self.path, self.state)
        except OSError as e:
            log.warning("supervisor channel write failed: %s", e)

    @staticmethod
    def load(path: str | None) -> dict:
        """Read a channel file; {} when absent/unreadable/corrupt (a
        scrape must never 500 because the supervisor died mid-write —
        which the atomic rename already precludes — or never existed)."""
        if not path:
            return {}
        try:
            with open(path, "r", encoding="utf-8") as fh:
                d = json.load(fh)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    @staticmethod
    def metrics_from(path: str | None,
                     rate_window_s: float = 3600.0) -> dict:
        """Flatten a channel file into /metrics-ready numeric fields,
        with the derived recent-failure count the /healthz restart-rate
        SLO evaluates.  {} when no channel."""
        d = SupervisorChannel.load(path)
        if not d:
            return {}
        now = time.time()
        ft = [t for t in d.get("failure_times", ())
              if isinstance(t, (int, float)) and now - t <= rate_window_s]
        out = {"recent_failures": len(ft)}
        for k in (*COUNTER_FIELDS, "failed_over", "gave_up",
                  "child_running", "backoff_s"):
            v = d.get(k)
            if isinstance(v, (int, float)):
                out[k] = v
        return out
