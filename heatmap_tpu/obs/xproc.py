"""Cross-process fleet channel (file-backed): supervisor counters,
per-member metrics snapshots, and episode-correlation broadcasts.

The supervisor (stream/supervisor.py) runs the streaming job as a child
process; the child owns the HTTP /metrics endpoint.  Without a channel,
the supervisor's restart/backoff/failover counters — exactly the
telemetry that explains "why did the stream blip" — are invisible to
scrapes, and everything resets when the child dies.

This channel is a single small JSON file written atomically
(tmp + rename) by the supervisor and read by anyone holding the path:

- the supervisor passes the path to the child via
  ``HEATMAP_SUPERVISOR_CHANNEL`` in its env, so the child's /metrics can
  merge ``supervisor_*`` series into its exposition;
- counters survive child restarts trivially (the parent owns them), and
  survive *supervisor* restarts too: ``SupervisorChannel.load()`` at
  startup resumes the persisted totals.

A file (not a pipe/socket) because the reader must never block the
writer, a half-written read must be impossible (rename is atomic on
POSIX), and stale data must be detectable (``updated_unix`` rides in the
payload).  mmap would save a syscall per scrape — not worth the
portability trade at a 1/scrape read rate.

The fleet observatory (obs/fleet.py) extends the same file-per-writer
discipline to three more artifact kinds next to the channel:

- ``<channel>.fresh-<tag>``  — the PR 3 per-child freshness summary
  (kept unchanged: old children keep surfacing as ``heatmap_child_*``
  gauges next to the richer format below);
- ``<channel>.member-<tag>`` — one member's FULL observability
  snapshot: its metrics-registry exposition text, freshness summary,
  /healthz verdict, and a compact lineage tail
  (:func:`publish_member_snapshot` / :func:`members_from`);
- ``<channel>.episode``      — the fleet-wide episode-correlation
  broadcast: when any member's SLO verdict transitions into degraded,
  it claims one episode ID here so EVERY member's flight-recorder dump
  for the incident carries the same ID (one episode, one dump set;
  :func:`broadcast_episode` / :func:`read_episode`).

Reads are hardened: a torn/corrupt member file, a missing
``updated_unix``, a stale snapshot, or a future-dated clock (skewed
writer) is SKIPPED and reported to the caller — never raised — so one
sick member cannot take down the fleet's aggregated surfaces
(``heatmap_fleet_stale_members`` counts them at /fleet/metrics).
"""

from __future__ import annotations

import json
import logging
import os
import time

log = logging.getLogger(__name__)

ENV_CHANNEL = "HEATMAP_SUPERVISOR_CHANNEL"
# Fleet-observatory knobs (obs/fleet.py shares them):
#   HEATMAP_FLEET_MAX_AGE_S   snapshot staleness window (default 30 s —
#                             members publish every HEATMAP_FLEET_
#                             PUBLISH_S, so a member quiet for this long
#                             is dead or wedged)
#   HEATMAP_FLEET_PUBLISH_S   member snapshot publish cadence (default
#                             2 s; 0 disables publishing)
#   HEATMAP_FLEET_TAG         names the RUNTIME member (default
#                             p<process_index>); serve-only workers
#                             suffix it -serve<pid> (default
#                             serve<pid>) so they never collide with
#                             the runtime on one member file
ENV_FLEET_MAX_AGE = "HEATMAP_FLEET_MAX_AGE_S"
ENV_FLEET_PUBLISH = "HEATMAP_FLEET_PUBLISH_S"
ENV_FLEET_TAG = "HEATMAP_FLEET_TAG"


def fleet_max_age_s(default: float = 30.0) -> float:
    raw = os.environ.get(ENV_FLEET_MAX_AGE, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        log.warning("%s=%r is not a number; using %s",
                    ENV_FLEET_MAX_AGE, raw, default)
        return default


def fleet_publish_s(default: float = 2.0) -> float:
    raw = os.environ.get(ENV_FLEET_PUBLISH, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        log.warning("%s=%r is not a number; using %s",
                    ENV_FLEET_PUBLISH, raw, default)
        return default

# numeric fields exported to /metrics as supervisor_* series; everything
# else in the payload (reason strings, timestamps) serves /trace-style
# debugging via /metrics.json
COUNTER_FIELDS = ("restarts_total", "failures_total", "stalls_total",
                  "failovers_total")
GAUGE_FIELDS = ("failed_over", "backoff_s", "gave_up",
                "recent_failures", "child_running")

# Per-child freshness summary keys (obs.lineage): each CHILD runtime
# publishes these into a sibling file next to the channel
# (``<channel>.fresh-<tag>``, tag = "p<process_index>"), so the process
# that owns /metrics — the child itself, a serve-only process, or a
# multi-host parent holding the same channel path — exposes per-child
# freshness as ``heatmap_child_<key>{child="<tag>"}`` gauges.  Lineage
# itself stays host-local; only this summary crosses processes.
FRESHNESS_FIELDS = ("event_age_p50_s", "event_age_p99_s",
                    "ring_residency_mean_s")


def supervisor_metrics_lines(chan: dict) -> list:
    """Supervisor channel fields -> exposition lines
    (``heatmap_supervisor_*``; xproc names carry their own _total
    suffixes).  Shared by serve/api's /metrics merge and the
    supervisor's OWN fleet member snapshot (stream/supervisor.py) — the
    supervisor process must not import the serve layer to describe
    itself."""
    from heatmap_tpu.obs.registry import _fmt

    lines = []
    for k in COUNTER_FIELDS:
        if isinstance(chan.get(k), (int, float)):
            lines.append(f"# TYPE heatmap_supervisor_{k} counter")
            lines.append(f"heatmap_supervisor_{k} {_fmt(chan[k])}")
    for k in GAUGE_FIELDS:
        if isinstance(chan.get(k), (int, float)):
            lines.append(f"# TYPE heatmap_supervisor_{k} gauge")
            lines.append(f"heatmap_supervisor_{k} {_fmt(chan[k])}")
    return lines


def atomic_write_json(path: str, payload: dict) -> None:
    """THE tmp+rename JSON write (channel, child freshness, flight
    records all use it): a reader can never see a half-written file;
    the tmp is cleaned up on failure and the error re-raised for the
    caller to contextualize."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, separators=(",", ":"))
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def child_freshness_path(channel_path: str, tag: str) -> str:
    return f"{channel_path}.fresh-{tag}"


def publish_child_freshness(channel_path: str, tag: str,
                            summary: dict) -> None:
    """Atomic write of one child's freshness summary next to the
    channel; unwritable degrades to a warning (telemetry must never
    take the pipeline down)."""
    payload = {k: summary[k] for k in FRESHNESS_FIELDS
               if isinstance(summary.get(k), (int, float))}
    payload["updated_unix"] = round(time.time(), 3)
    try:
        atomic_write_json(child_freshness_path(channel_path, tag), payload)
    except OSError as e:
        log.warning("child freshness publish failed: %s", e)


def child_freshness_from(channel_path: str | None,
                         max_age_s: float = 900.0) -> dict:
    """{tag: summary dict} for every published child next to the
    channel; {} when no channel / none published.  Summaries whose
    ``updated_unix`` is older than ``max_age_s`` are dropped — a dead
    child's last file must not keep exporting a frozen-green freshness
    gauge forever (staleness is detectable, per the channel contract)."""
    if not channel_path:
        return {}
    import glob

    now = time.time()
    out = {}
    for p in sorted(glob.glob(glob.escape(channel_path) + ".fresh-*")):
        tag = p.rsplit(".fresh-", 1)[1]
        if ".tmp" in tag:  # in-flight atomic write of any publisher
            continue
        d = SupervisorChannel.load(p)
        upd = d.get("updated_unix")
        if not isinstance(upd, (int, float)) or now - upd > max_age_s:
            continue
        out[tag] = d
    return out


# ---------------------------------------------------------------- fleet
# Full member snapshots: one file per process, next to the channel.
# The freshness-only format above stays untouched (back-compat: old
# children keep publishing .fresh-<tag> files and they keep surfacing
# as heatmap_child_* gauges); the member snapshot is the superset the
# fleet aggregator (obs/fleet.py) federates.

def member_path(channel_path: str, tag: str) -> str:
    return f"{channel_path}.member-{tag}"


def publish_member_snapshot(channel_path: str, tag: str, *, role: str,
                            metrics_text: str = "",
                            freshness: dict | None = None,
                            healthz: dict | None = None,
                            lineage: list | None = None,
                            audit: dict | None = None,
                            cq: dict | None = None,
                            hist: dict | None = None,
                            delivery: dict | None = None,
                            infer: dict | None = None,
                            quality: dict | None = None,
                            left: bool = False) -> None:
    """Atomic write of one member's full observability snapshot:
    Prometheus exposition text of its registry, its freshness summary,
    its /healthz verdict, and a compact lineage tail (lid-keyed stage
    contributions the fleet freshness stitch merges).  Unwritable
    degrades to a warning — telemetry never takes a member down.

    ``audit`` carries the member's integrity-observatory block
    (obs.audit.AuditState.member_block: ledger counts, residuals,
    per-shard digests) — /fleet/audit stitches these cross-process
    exactly as /fleet/freshness stitches lineage; absent when
    HEATMAP_AUDIT is off, keeping snapshots byte-compatible.

    ``cq`` carries the member's continuous-query block
    (query.continuous.ContinuousQueryEngine.member_block: registered
    standing queries, evaluations, matches, eval lag, index size) —
    what ``obs_top --fleet`` renders per serve member; absent on
    members without the engine.

    ``left=True`` marks the snapshot a DEPARTURE tombstone: the member
    closed cleanly and is leaving the fleet on purpose.  Readers
    (``members_from``) report it as neither fresh nor stale — without
    the tombstone a finished bounded job would degrade /fleet/healthz
    as "stale" forever (and deleting its file would flip the reason to
    "vanished" on every live aggregator).  A rejoining member simply
    overwrites its own tombstone."""
    payload = {
        "tag": str(tag),
        "role": str(role),
        "pid": os.getpid(),
        "metrics_text": str(metrics_text),
        "freshness": freshness or {},
        "healthz": healthz or {},
        "lineage": lineage or [],
        "updated_unix": round(time.time(), 3),
    }
    if audit:
        payload["audit"] = audit
    if cq:
        payload["cq"] = cq
    if hist:
        # the member's space-time history block (query/history.py
        # HistoryCompactor.member_block / serve-side
        # compaction_status): chunks, covered span, compaction lag,
        # backfills — absent on members without the tier, keeping
        # snapshots byte-compatible
        payload["hist"] = hist
    if delivery:
        # the member's delivery-lineage block (obs.delivery
        # DeliveryTracker.member_block: delivered-age p50/p99, per-stage
        # p50s, worst stage, residual bound) — /fleet/delivery rolls
        # these up and names the worst replica; absent on members
        # without subscribers or with HEATMAP_DELIVERY off, keeping
        # snapshots byte-compatible
        payload["delivery"] = delivery
    if infer:
        # the member's streaming-inference block (infer.engine
        # InferenceEngine.member_block: entity-table occupancy/capacity,
        # seed/evict/reseed counts, per-reason anomaly totals) — what
        # ``obs_top --fleet`` renders per runtime shard; absent on
        # members without the kalman reducer, keeping snapshots
        # byte-compatible
        payload["infer"] = infer
    if quality:
        # the member's inference-quality block (obs.quality
        # QualityObservatory.member_block: scorecard conservation
        # identity, rolling live skill per (grid, horizon), NIS
        # coverage vs the calibration band, anomaly rates, entity-table
        # pressure) — /fleet/quality plain-sums these and names the
        # worst shard; absent with HEATMAP_QUALITY off, keeping
        # snapshots byte-compatible
        payload["quality"] = quality
    if left:
        payload["left"] = True
    try:
        atomic_write_json(member_path(channel_path, tag), payload)
    except (OSError, TypeError, ValueError) as e:
        log.warning("fleet member snapshot publish failed: %s", e)


def members_from(channel_path: str | None,
                 max_age_s: float | None = None,
                 skew_s: float | None = None) -> tuple[dict, dict]:
    """``({tag: snapshot}, {tag: skip reason})`` for every member file
    next to the channel.  The second dict is the hardening surface: a
    torn/corrupt file, a snapshot whose ``updated_unix`` is older than
    ``max_age_s``, or one dated further than ``skew_s`` into the future
    (a writer with a skewed clock must not masquerade as eternally
    fresh) is skipped WITH its reason instead of raised — the fleet
    aggregator exports the count as ``heatmap_fleet_stale_members``."""
    if not channel_path:
        return {}, {}
    if max_age_s is None:
        max_age_s = fleet_max_age_s()
    if skew_s is None:
        skew_s = max(5.0, max_age_s)
    import glob

    now = time.time()
    members: dict = {}
    skipped: dict = {}
    for p in sorted(glob.glob(glob.escape(channel_path) + ".member-*")):
        tag = p.rsplit(".member-", 1)[1]
        if ".tmp" in tag:  # in-flight atomic write of any publisher
            continue
        try:
            with open(p, "r", encoding="utf-8") as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            # torn write can't happen via atomic_write_json, but a
            # foreign/partial writer (chaos, disk-full cp) can leave one
            skipped[tag] = "corrupt"
            continue
        if isinstance(d, dict) and d.get("left"):
            # departure tombstone: a clean close, not an incident —
            # checked BEFORE staleness so an hours-old tombstone still
            # reads as "left", never degrading the fleet
            skipped[tag] = "left"
            continue
        upd = d.get("updated_unix") if isinstance(d, dict) else None
        if not isinstance(upd, (int, float)):
            skipped[tag] = "corrupt"
            continue
        if now - upd > max_age_s:
            skipped[tag] = f"stale {now - upd:.1f}s"
            continue
        if upd - now > skew_s:
            skipped[tag] = f"clock skew +{upd - now:.1f}s"
            continue
        members[tag] = d
    return members, skipped


# ---------------------------------------------------------- shard wm
# Cross-shard watermark alignment (stream/shardmap.py): each runtime
# shard publishes its event-time high watermark next to the channel;
# every shard's effective cutoff is bounded by the fleet LOW watermark
# (min over fresh peers), so no shard closes (evicts and finalizes) a
# window that a straggling shard is still folding events into.  The
# same file-per-writer, atomic-rename, staleness-detectable discipline
# as every other channel artifact — a dead shard's stale file drops out
# of the bound after ``max_age_s`` instead of freezing eviction
# fleet-wide forever.

def shard_watermark_path(channel_path: str, tag: str) -> str:
    return f"{channel_path}.wm-{tag}"


def publish_shard_watermark(channel_path: str, tag: str,
                            max_event_ts: int) -> None:
    """Atomic write of one shard's event-time high watermark; unwritable
    degrades to a warning (telemetry never takes a shard down)."""
    payload = {"max_event_ts": int(max_event_ts),
               "updated_unix": round(time.time(), 3)}
    try:
        atomic_write_json(shard_watermark_path(channel_path, tag), payload)
    except OSError as e:
        log.warning("shard watermark publish failed: %s", e)


def shard_watermarks_from(channel_path: str | None,
                          max_age_s: float | None = None) -> dict:
    """{tag: max_event_ts} for every FRESH shard watermark next to the
    channel; {} when no channel / none published.  Stale, torn, or
    corrupt files are skipped (never raised): a wedged shard must
    eventually release the fleet low bound, and a sick file must not
    take the step loop down."""
    if not channel_path:
        return {}
    if max_age_s is None:
        max_age_s = fleet_max_age_s()
    import glob

    now = time.time()
    out: dict = {}
    for p in sorted(glob.glob(glob.escape(channel_path) + ".wm-*")):
        tag = p.rsplit(".wm-", 1)[1]
        if ".tmp" in tag:  # in-flight atomic write of any publisher
            continue
        d = SupervisorChannel.load(p)
        ts = d.get("max_event_ts")
        upd = d.get("updated_unix")
        if not isinstance(ts, (int, float)) \
                or not isinstance(upd, (int, float)) \
                or now - upd > max_age_s:
            continue
        out[tag] = int(ts)
    return out


# -------------------------------------------------------------- episode
# Fleet-wide incident correlation: the first member whose SLO verdict
# transitions into degraded claims ONE episode id in this file; every
# other member's watchdog sees it and writes its own flight-recorder
# dump under the same id, so an incident leaves one correlated dump SET
# instead of N unrelated files.

def episode_path(channel_path: str) -> str:
    return channel_path + ".episode"


def broadcast_episode(channel_path: str, origin: str, reason: str) -> str:
    """Claim a fleet episode: write the correlation broadcast and
    return its id ('' when the write failed — degradation handling must
    never depend on a writable channel)."""
    import uuid

    eid = uuid.uuid4().hex[:12]
    payload = {
        "episode_id": eid,
        "origin": str(origin),
        "reason": str(reason)[:300],
        "updated_unix": round(time.time(), 3),
    }
    try:
        atomic_write_json(episode_path(channel_path), payload)
    except (OSError, TypeError, ValueError) as e:
        log.warning("fleet episode broadcast failed: %s", e)
        return ""
    return eid


def read_episode(channel_path: str | None,
                 max_age_s: float = 600.0) -> dict:
    """The current fleet episode broadcast, or {} when none / expired /
    unreadable (same never-raise contract as every channel read)."""
    if not channel_path:
        return {}
    try:
        with open(episode_path(channel_path), "r", encoding="utf-8") as fh:
            d = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(d, dict) or not d.get("episode_id"):
        return {}
    upd = d.get("updated_unix")
    if not isinstance(upd, (int, float)) or time.time() - upd > max_age_s:
        return {}
    return d


def clear_episode(channel_path: str | None, origin: str | None = None) -> bool:
    """Close the fleet episode: remove the broadcast file so the NEXT
    incident mints a fresh id instead of being conflated under (and
    dump-suppressed by) this one.  With ``origin`` set, only an episode
    that origin claimed is removed — a member must not close an
    incident some other member is still correlating.  Called on
    recovery (the claiming watchdog's degraded→ok transition) and by
    the supervisor when a failure follows a full healthy window (a
    separate incident, not a continuation).  Never raises; returns
    whether a broadcast was removed."""
    if not channel_path:
        return False
    path = episode_path(channel_path)
    if origin is not None:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            return False
        if not isinstance(d, dict) or d.get("origin") != str(origin):
            return False
    try:
        os.unlink(path)
        return True
    except OSError:
        return False


def ensure_episode(channel_path: str, origin: str, reason: str,
                   max_age_s: float = 600.0) -> dict:
    """Join the fresh fleet episode if one is open, else claim a new
    one — a member degrading WHILE an incident is already broadcast
    must correlate with it, not mint a second id for the same event.

    The claim itself is an O_EXCL create of ``<episode>.claim``:
    without it, two members degrading in the same watchdog tick window
    (a shared-cause incident is exactly when that happens) would both
    read-empty-then-broadcast, the second atomic rename would erase
    the first id, and one incident would leave two uncorrelated dump
    sets.  The winner broadcasts and removes the claim; a loser adopts
    the winner's broadcast (brief re-read), or returns {} and
    correlates on its next tick.  A claim orphaned by a crashed winner
    is swept by mtime so it cannot wedge the NEXT incident."""
    ep = read_episode(channel_path, max_age_s=max_age_s)
    if ep:
        return ep
    claim = episode_path(channel_path) + ".claim"
    try:
        os.close(os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except FileExistsError:
        # another member is claiming right now — adopt its broadcast
        for _ in range(50):
            ep = read_episode(channel_path, max_age_s=max_age_s)
            if ep:
                return ep
            time.sleep(0.01)
        try:  # orphaned claim (winner crashed mid-broadcast): sweep it
            if time.time() - os.path.getmtime(claim) > 10.0:
                os.unlink(claim)
        except OSError:
            pass
        return {}
    except OSError:
        pass  # unwritable channel dir: degrade to best-effort broadcast
    # claim won — but a PREVIOUS winner may have broadcast and removed
    # its claim between our read-empty entry and our O_EXCL create:
    # re-read under the claim and adopt, or our rename would replace
    # its id and split the incident into two uncorrelated dump sets
    ep = read_episode(channel_path, max_age_s=max_age_s)
    if ep:
        try:
            os.unlink(claim)
        except OSError:
            pass
        return ep
    eid = broadcast_episode(channel_path, origin, reason)
    try:
        os.unlink(claim)
    except OSError:
        pass
    return {"episode_id": eid, "origin": str(origin),
            "reason": str(reason)[:300]} if eid else {}


class SupervisorChannel:
    def __init__(self, path: str):
        self.path = path
        self.state: dict = {
            "restarts_total": 0,
            "failures_total": 0,
            "stalls_total": 0,
            "failovers_total": 0,
            "failed_over": 0,
            "gave_up": 0,
            "child_running": 0,
            "backoff_s": 0.0,
            "failure_times": [],     # wall clock of recent failures
            "last_reason": "",
            "started_unix": round(time.time(), 3),
            "updated_unix": 0.0,
        }

    def resume(self) -> "SupervisorChannel":
        """Fold persisted TOTALS back in (a restarted supervisor keeps
        counting where its predecessor stopped).  Point-in-time flags
        (gave_up, failed_over, child_running, backoff_s) deliberately do
        NOT resume: they describe the predecessor process — a fresh
        supervisor is actively supervising again, and carrying a stale
        gave_up=1 would pin /healthz at down (503) forever."""
        prior = self.load(self.path)
        for k in COUNTER_FIELDS:
            if isinstance(prior.get(k), (int, float)):
                self.state[k] = prior[k]
        if isinstance(prior.get("failure_times"), list):
            self.state["failure_times"] = [
                float(t) for t in prior["failure_times"][-64:]
                if isinstance(t, (int, float))]
        return self

    def update(self, **fields) -> None:
        self.state.update(fields)
        self.publish()

    def note_failure(self, reason: str, stalled: bool = False,
                     window_s: float = 3600.0) -> None:
        now = time.time()
        ft = [t for t in self.state["failure_times"] if now - t <= window_s]
        ft.append(now)
        self.state["failure_times"] = ft[-64:]
        self.state["failures_total"] += 1
        if stalled:
            self.state["stalls_total"] += 1
        self.state["last_reason"] = str(reason)[:200]
        self.publish()

    def publish(self) -> None:
        """Atomic write; an unwritable channel degrades to a warning —
        telemetry must never take the supervisor down."""
        self.state["updated_unix"] = round(time.time(), 3)
        try:
            atomic_write_json(self.path, self.state)
        except OSError as e:
            log.warning("supervisor channel write failed: %s", e)

    @staticmethod
    def load(path: str | None) -> dict:
        """Read a channel file; {} when absent/unreadable/corrupt (a
        scrape must never 500 because the supervisor died mid-write —
        which the atomic rename already precludes — or never existed)."""
        if not path:
            return {}
        try:
            with open(path, "r", encoding="utf-8") as fh:
                d = json.load(fh)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    @staticmethod
    def metrics_from(path: str | None,
                     rate_window_s: float = 3600.0) -> dict:
        """Flatten a channel file into /metrics-ready numeric fields,
        with the derived recent-failure count the /healthz restart-rate
        SLO evaluates.  {} when no channel."""
        d = SupervisorChannel.load(path)
        if not d:
            return {}
        now = time.time()
        ft = [t for t in d.get("failure_times", ())
              if isinstance(t, (int, float)) and now - t <= rate_window_s]
        out = {"recent_failures": len(ft)}
        for k in (*COUNTER_FIELDS, "failed_over", "gave_up",
                  "child_running", "backoff_s"):
            v = d.get(k)
            if isinstance(v, (int, float)):
                out[k] = v
        return out
