"""Sampling Python stack profiler — always-available, low-overhead.

The span telemetry says WHICH stage of a batch is slow; it cannot say
WHERE INSIDE the host code the time goes (a hot ``json.dumps``, a numpy
fold, a lock convoy on the writer thread).  The classical answer is a
sampling profiler, and the streaming answer is one that is cheap enough
to leave running in production: a daemon thread wakes at
``HEATMAP_STACKPROF_HZ`` (default 29 — deliberately co-prime with
common 10/100 Hz periodic work so the samples don't alias onto it),
walks ``sys._current_frames()`` once, and counts the TOP frame of every
other thread.  Per wake that is one dict walk over a handful of
threads — microseconds — so the steady-state overhead is well under
0.1% of one core.

Aggregated output (top-of-stack counts per frame, per thread name)
serves at ``/debug/stacks`` and rides the flight-recorder dump, so an
SLO-triggered capture shows what the host threads were ACTUALLY doing
in the incident window, not just that a stage was slow.

One sampler per process (module singleton): ``/debug/stacks`` and the
runtime's watchdog share it; ``ensure_started()`` is idempotent and
thread-safe.
"""

from __future__ import annotations

import collections
import logging
import os
import sys
import threading
import time

log = logging.getLogger(__name__)

ENV_HZ = "HEATMAP_STACKPROF_HZ"
DEFAULT_HZ = 29.0


def _env_hz(env=None) -> float:
    e = os.environ if env is None else env
    raw = e.get(ENV_HZ, "")
    if not raw:
        return DEFAULT_HZ
    try:
        hz = float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %s", ENV_HZ, raw,
                    DEFAULT_HZ)
        return DEFAULT_HZ
    if hz <= 0:
        return 0.0  # explicit disable
    return min(hz, 250.0)  # ceiling: the GIL makes faster pointless


class StackSampler:
    """Counts top-of-stack frames across threads at a fixed rate."""

    def __init__(self, hz: float | None = None):
        self.hz = _env_hz() if hz is None else float(hz)
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._samples = 0
        self._t_started: float | None = None
        # (thread_name, file, line, func) -> count
        self._counts: collections.Counter = collections.Counter()

    # ------------------------------------------------------------ control
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def ensure_started(self) -> bool:
        """Start the sampler thread if not running; False when disabled
        (hz <= 0)."""
        if self.hz <= 0:
            return False
        with self._lock:
            if self.running:
                return True
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="stackprof", daemon=True)
            self._t_started = time.monotonic()
            self._thread.start()
        # join the sampler BEFORE interpreter finalization: a daemon
        # thread walking sys._current_frames() while the XLA client
        # tears down intermittently aborts the process (observed:
        # "terminate called without an active exception" at exit)
        import atexit

        atexit.register(self.stop)
        return True

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            self._stop.set()
            t.join(timeout=2.0)

    # ------------------------------------------------------------ sampling
    @staticmethod
    def _walk(me: int, names: dict) -> list:
        """One frame walk, isolated in its own scope so the frames dict
        (and every frame it references) is freed the moment this
        returns.  Holding frames any longer keeps OTHER threads' locals
        alive — observed: a dead serve thread's listening socket held
        open into the next bind (EADDRINUSE), an exported shm
        memoryview blocking close() (BufferError)."""
        frames = sys._current_frames()
        return [
            (names.get(tid, str(tid)), frame.f_code.co_filename,
             frame.f_lineno, frame.f_code.co_name)
            for tid, frame in frames.items() if tid != me
        ]

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        me = threading.get_ident()
        names = {}
        while not self._stop.wait(interval):
            if len(names) != threading.active_count():
                names = {t.ident: t.name for t in threading.enumerate()}
            try:
                now_keys = self._walk(me, names)
            except Exception:  # noqa: BLE001 - never kill the process
                continue
            with self._lock:
                self._samples += 1
                for k in now_keys:
                    self._counts[k] += 1

    # ------------------------------------------------------------ reads
    def snapshot(self, n: int = 40) -> dict:
        """Aggregated top-of-stack output: the n hottest frames with
        their share of samples, newest aggregate first."""
        with self._lock:
            samples = self._samples
            top = self._counts.most_common(max(1, int(n)))
            started = self._t_started
        frames = [{
            "thread": t_name,
            "frame": f"{fname}:{lineno}:{func}",
            "count": count,
            "share": round(count / samples, 4) if samples else 0.0,
        } for (t_name, fname, lineno, func), count in top]
        return {
            "running": self.running,
            "hz": self.hz,
            "samples": samples,
            "uptime_s": (round(time.monotonic() - started, 3)
                         if started is not None else 0.0),
            "frames": frames,
        }

    def tail(self, n: int = 20) -> list:
        """The flight-recorder view: the n hottest frames only."""
        return self.snapshot(n)["frames"]


_SAMPLER: StackSampler | None = None
_SAMPLER_LOCK = threading.Lock()


def get_sampler() -> StackSampler:
    """The process-wide sampler (created on first use; not started)."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            _SAMPLER = StackSampler()
        return _SAMPLER
