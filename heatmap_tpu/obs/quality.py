"""quality — the inference quality observatory (ISSUE 20).

PR 19 made the pipeline emit *model outputs* (Kalman velocity fields,
advected occupancy forecasts, reason-tagged anomalies); every quality
number stayed offline — ``tools/score_forecast.py`` is a CLI you
remember to run, and a silently mis-calibrated filter serves wrong
forecasts under a green /healthz.  This module turns statistical
correctness into the same live production invariants PR 12 built for
byte conservation and PR 18 built for latency SLOs, in three coupled
ledgers:

1. **Online forecast scoring.**  Every ``/api/tiles/forecast`` horizon
   registers a pending *scorecard* (the forecast's cell map plus the
   persistence baseline captured eagerly, while the base window is
   still live in the view).  When the target time matures in the event
   stream — or lands in the PR 15 history tier after a restart — the
   card is scored with the *same* :func:`score_maps` skill-vs-
   persistence math the offline CLI uses (the CLI imports it from
   here), into rolling per-(grid, horizon) skill gauges.  The ledger
   carries a conservation identity in the PR 12 style::

       registered == scored + expired_unscorable + pending

   pinned by tests across window advance, fake-clock eviction, and a
   kill+resume restart that scores via the history tier (scorecards
   ride the checkpoint extras).

2. **Filter-calibration ledgers.**  Per-shard NIS coverage against the
   chi-square reference — a well-calibrated filter puts ~95% of
   innovations inside the 95% gate, so the observed fraction must sit
   in the ``HEATMAP_SLO_NIS_BAND`` band — plus innovation-mean bias
   (meters), anomaly rates by reason over rolling event-time windows,
   and entity-table pressure (occupancy, TTL-vs-LRU eviction mix,
   handoff rate).  The anomaly reason set is CLOSED
   (:data:`infer.engine.ANOMALY_REASONS`): an unknown reason raises —
   a new detector must be documented, never silently binned.

3. **Drift → incident.**  The gauges ride the registry, so the PR 18
   tsdb records them and the SLO engine evaluates
   ``HEATMAP_SLO_FORECAST_SKILL`` (skill BELOW the floor is bad — the
   first lower-is-worse objective, ``SloSpec(op="lt")``) and
   ``HEATMAP_SLO_NIS_BAND`` (distance outside the coverage band) as
   burn-rate SLOs: sustained drift burns error budget, degrades
   /healthz naming (grid, reducer, shard), claims ONE correlated PR 6
   episode, and dumps a flight record enriched with the calibration
   snapshot (the runtime registers :meth:`QualityObservatory.snapshot`
   as a flightrec source).  ``/debug/timeline`` and ``obs_top
   --replay`` reconstruct a model regression from the retained series.

Gated by ``HEATMAP_QUALITY=1``; knob-off, nothing is constructed, no
family registers, and the runtime stays byte-identical (tiles, feed
bytes, conservation counters, window seqs) — the differential test
pins it.  Knob-ON is observe-only too: registration happens after the
forecast body is built and scoring never touches view state, so the
same surfaces stay byte-identical either way.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from typing import Mapping

log = logging.getLogger(__name__)

ENV_QUALITY = "HEATMAP_QUALITY"
ENV_NIS_BAND = "HEATMAP_SLO_NIS_BAND"            # "lo,hi" coverage band
ENV_FORECAST_SKILL = "HEATMAP_SLO_FORECAST_SKILL"  # rolling-skill floor

# the quality-drift objectives obs/slo.py evaluates; quality_stamp
# counts THEIR fired alerts as the artifact's drift provenance
QUALITY_SLOS = ("forecast_skill", "nis_band")

DEFAULT_NIS_BAND = (0.85, 0.995)
# calibration verdicts need statistics, not anecdotes: below this many
# update rounds in the rolling window the coverage gauges stay neutral
MIN_WINDOW_UPDATES = 100
# bounded pending set: past it the OLDEST card is evicted as
# expired_unscorable (accounted — the conservation identity still holds)
MAX_PENDING = 4096
# rolling skill per (grid, horizon): mean of the last N scored cards
SKILL_ROLL_N = 32

SCORE_OUTCOMES = ("scored", "expired_unscorable")


def quality_enabled(env: Mapping[str, str] | None = None) -> bool:
    e = os.environ if env is None else env
    return e.get(ENV_QUALITY, "0") not in ("0", "false", "")


def parse_nis_band(env: Mapping[str, str] | None = None) -> tuple:
    """(lo, hi) from ``HEATMAP_SLO_NIS_BAND="lo,hi"``; the default band
    brackets the chi-square 95% expectation with room for f32 rounding
    and short-window noise."""
    e = os.environ if env is None else env
    raw = e.get(ENV_NIS_BAND, "")
    if raw:
        try:
            lo_s, hi_s = raw.split(",")
            lo, hi = float(lo_s), float(hi_s)
            if 0.0 <= lo < hi <= 1.0:
                return (lo, hi)
        except ValueError:
            pass
        log.warning("bad %s=%r (want 'lo,hi' in [0,1]); using default",
                    ENV_NIS_BAND, raw)
    return DEFAULT_NIS_BAND


# --------------------------------------------------------------- scoring
# THE scoring implementation (ISSUE 20 satellite): tools/score_forecast.py
# imports these — the offline CLI and the live observatory score with
# the same math by construction, and the differential test pins it.

def features_to_counts(features) -> dict:
    """{cellId: count} from a features list (forecast or range docs)."""
    out: dict = {}
    for f in features or ():
        cid = f.get("cellId")
        if cid is None:
            continue
        out[str(cid)] = out.get(str(cid), 0.0) + float(f.get("count", 0))
    return out


def normalize(counts: dict) -> dict:
    """Counts -> occupancy fractions (sum 1.0); {} stays {}."""
    total = sum(counts.values())
    if total <= 0:
        return {}
    return {k: v / total for k, v in counts.items()}


def mae(pred: dict, actual: dict) -> float:
    keys = set(pred) | set(actual)
    if not keys:
        return 0.0
    return sum(abs(pred.get(k, 0.0) - actual.get(k, 0.0))
               for k in keys) / len(keys)


def score_maps(forecast: dict, persistence: dict, actual: dict) -> dict:
    """Shape-only skill of normalized forecast vs persistence."""
    f, p, a = normalize(forecast), normalize(persistence), normalize(actual)
    mae_f, mae_p = mae(f, a), mae(p, a)
    skill = (1.0 - mae_f / mae_p) if mae_p > 0 else None
    return {
        "cells_forecast": len(f),
        "cells_persistence": len(p),
        "cells_actual": len(a),
        "mae_forecast": round(mae_f, 6),
        "mae_persistence": round(mae_p, 6),
        "skill_vs_persistence": round(skill, 4)
        if skill is not None else None,
    }


# ----------------------------------------------------------- observatory
class QualityObservatory:
    """The three coupled ledgers; one per runtime shard (like the
    audit/infer blocks), attached to the inference engine's fold."""

    def __init__(self, cfg, *, registry=None, view=None, tag: str = ""):
        self.cfg = cfg
        self.view = view
        self.tag = str(tag)
        self.reducer = "kalman"
        self.window_s = float(getattr(cfg, "quality_window_s", 600.0))
        self.lookback_s = float(getattr(cfg, "quality_lookback_s", 300.0))
        self.mature_s = float(getattr(cfg, "quality_mature_s", 60.0))
        self.ttl_s = float(getattr(cfg, "quality_ttl_s", 3600.0))
        self.band = parse_nis_band()
        try:
            self.skill_floor = float(
                os.environ.get(ENV_FORECAST_SKILL, 0.0))
        except (TypeError, ValueError):
            self.skill_floor = 0.0
        self._lock = threading.Lock()
        self._hist_reader = None
        self._hist_tried = False
        # scorecard ledger
        self._pending: deque = deque()
        self._registered = 0
        self._outcomes = {o: 0 for o in SCORE_OUTCOMES}
        self._skill_roll: dict = {}          # (grid, h) -> deque of skill
        self._last_score: dict | None = None
        # calibration ledger: event-time rolling window of per-fold
        # (t, updates, inside, inn_n, inn_e, {reason: delta}) entries
        self._folds: deque = deque()
        self._anom_last: dict = {}
        self._drift_checks = 0
        self._table: dict = {}
        self._tbl_first: dict | None = None
        # registered only when the observatory is constructed (the knob
        # gate), so knob-off exposition stays byte-identical
        self._g_skill = self._g_cov = self._g_band = None
        self._g_bias = self._g_pending = self._c_cards = None
        self._g_rate = None
        if registry is not None:
            self._g_skill = registry.gauge(
                "heatmap_quality_forecast_skill",
                "rolling live skill-vs-persistence of served forecasts "
                "per (grid, horizon), scored at target maturity with "
                "the offline CLI's exact math (obs.quality.score_maps)",
                labels=("grid", "h"))
            self._g_cov = registry.gauge(
                "heatmap_quality_nis_coverage",
                "fraction of filter-update innovations inside the "
                "chi-square 95% gate over the rolling window "
                "(calibrated ~0.95; HEATMAP_SLO_NIS_BAND bounds it)")
            self._g_band = registry.gauge(
                "heatmap_quality_nis_band_error",
                "distance of NIS coverage outside the configured band "
                "(0 inside; the drift SLO burns while it is positive)")
            self._g_bias = registry.gauge(
                "heatmap_quality_innovation_bias_m",
                "magnitude of the mean innovation vector (meters) over "
                "the rolling window — a persistent offset means the "
                "motion model or the measurements are biased")
            self._g_pending = registry.gauge(
                "heatmap_quality_pending_scorecards",
                "forecast scorecards registered but not yet matured "
                "(registered == scored + expired_unscorable + pending)",
                fn=lambda: float(len(self._pending)))
            self._c_cards = registry.counter(
                "heatmap_quality_scorecards_total",
                "forecast scorecards resolved by outcome (scored | "
                "expired_unscorable); with the pending gauge this is "
                "the scorecard conservation identity",
                labels=("outcome",))
            for o in SCORE_OUTCOMES:
                self._c_cards.labels(outcome=o)
            self._g_rate = registry.gauge(
                "heatmap_quality_anomaly_rate",
                "reason-tagged anomaly events per second over the "
                "rolling calibration window (closed reason set)",
                labels=("reason",))

    # ------------------------------------------------------- span reads
    def _grid_for_res(self, res: int) -> str:
        """The grid label the runtime writes for ``res`` under the
        reference window — the same default rule as the serve tier's
        bare endpoints (config.default_grid, generalized per res)."""
        wins = self.cfg.windows_minutes or (self.cfg.tile_minutes,)
        wmin = (self.cfg.tile_minutes
                if self.cfg.tile_minutes in wins else wins[0])
        return self.cfg.pair_grid(int(res), wmin)

    def _reader(self):
        """A history-tier reader (view overlaid) for spans the live
        view no longer holds — the restart scoring path.  Built
        lazily; None without HEATMAP_HIST_DIR."""
        if self._hist_tried:
            return self._hist_reader
        self._hist_tried = True
        hist_dir = getattr(self.cfg, "hist_dir", "") or ""
        if hist_dir:
            try:
                from heatmap_tpu.query.history import (FileHistorySource,
                                                       HistoryReader)

                self._hist_reader = HistoryReader(
                    FileHistorySource(hist_dir), view=self.view)
            except Exception:  # noqa: BLE001 - observe-only tier
                log.warning("quality history reader unavailable",
                            exc_info=True)
        return self._hist_reader

    def _span_counts(self, grid: str, t0: float, t1: float) -> dict:
        """{cellId: count} summed over windows with t0 <= ws < t1 —
        exactly the offline CLI's ``/api/tiles/range`` aggregate
        semantics (history.windows_in_range + aggregate_range), read
        from the history tier when configured (live view overlaid),
        else from the live view alone."""
        out: dict = {}
        reader = self._reader()
        if reader is not None:
            per_window = reader.windows_in_range(grid, t0, t1)
            for ws in per_window:
                for d in per_window[ws]["docs"]:
                    cid = str(d.get("cellId"))
                    out[cid] = out.get(cid, 0.0) + float(
                        d.get("count", 0))
            return out
        if self.view is None:
            return out
        for ws, (_ws_dt, _we_dt, docs) in \
                self.view.window_docs(grid).items():
            if t0 <= ws < t1:
                for d in docs:
                    cid = str(d.get("cellId"))
                    out[cid] = out.get(cid, 0.0) + float(
                        d.get("count", 0))
        return out

    # ------------------------------------------------------- scorecards
    def register_forecast(self, res: int, h_s: float,
                          base_ts: int | None, cells: dict) -> None:
        """Register one served forecast as a pending scorecard.  Called
        from the serve handler AFTER the response body is built — the
        response stays byte-identical to a knob-off run.  The
        persistence baseline (history around base_ts) is captured NOW,
        while its windows are still live; the card itself carries both
        maps so a restart can still score it."""
        if base_ts is None:
            return  # nothing folded yet: unanchored, unscorable
        grid = self._grid_for_res(int(res))
        forecast = {format(int(c), "x"): float(n)
                    for c, n in (cells or {}).items()}
        persistence = self._span_counts(
            grid, float(base_ts) - self.lookback_s, float(base_ts) + 1)
        card = {
            "grid": grid,
            "res": int(res),
            "h": float(h_s),
            "base_ts": int(base_ts),
            "target_ts": int(base_ts) + int(h_s),
            "forecast": forecast,
            "persistence": persistence,
        }
        with self._lock:
            self._registered += 1
            self._pending.append(card)
            if len(self._pending) > MAX_PENDING:
                # bounded like every ledger: the oldest card leaves as
                # expired_unscorable, never silently dropped
                self._resolve_locked(self._pending.popleft(),
                                     "expired_unscorable")

    def _resolve_locked(self, card: dict, outcome: str,
                        skill=None) -> None:
        self._outcomes[outcome] += 1
        if self._c_cards is not None:
            self._c_cards.labels(outcome=outcome).inc()
        if outcome != "scored" or skill is None:
            return
        key = (card["grid"], int(card["h"]))
        roll = self._skill_roll.get(key)
        if roll is None:
            roll = self._skill_roll[key] = deque(maxlen=SKILL_ROLL_N)
        roll.append(float(skill))
        if self._g_skill is not None:
            self._g_skill.labels(grid=key[0], h=str(key[1])).set(
                round(sum(roll) / len(roll), 4))

    def mature(self, now_ts: int) -> None:
        """Advance the scorecard lifecycle against the event-time high
        watermark: cards whose target has matured score against the
        view/history span; cards unscorable for ``ttl_s`` past their
        target expire as ``expired_unscorable``.  Deterministic — a
        function of the event stream, never the wall clock (the
        fake-clock eviction test pins it)."""
        due: list = []
        with self._lock:
            if not self._pending:
                return
            keep: deque = deque()
            for card in self._pending:
                if now_ts >= card["target_ts"] + self.mature_s:
                    due.append(card)
                else:
                    keep.append(card)
            self._pending = keep
        for card in due:
            outcome, skill = "expired_unscorable", None
            try:
                actual = self._span_counts(
                    card["grid"],
                    card["target_ts"] - self.lookback_s,
                    card["target_ts"] + 1)
            except Exception:  # noqa: BLE001 - observe-only tier
                log.warning("scorecard span read failed", exc_info=True)
                actual = {}
            if actual:
                s = score_maps(card["forecast"], card["persistence"],
                               actual)
                outcome = "scored"
                skill = s["skill_vs_persistence"]
                self._last_score = {**s, "grid": card["grid"],
                                    "h": card["h"],
                                    "base_ts": card["base_ts"],
                                    "target_ts": card["target_ts"]}
            elif now_ts < card["target_ts"] + self.ttl_s:
                # matured but the span isn't answerable YET (history
                # compaction lag after a restart): stays pending until
                # the TTL calls it unscorable
                with self._lock:
                    self._pending.append(card)
                continue
            with self._lock:
                self._resolve_locked(card, outcome, skill)

    def identity(self) -> dict:
        """The scorecard conservation identity, PR 12 style."""
        with self._lock:
            reg = self._registered
            scored = self._outcomes["scored"]
            expired = self._outcomes["expired_unscorable"]
            pending = len(self._pending)
        return {
            "registered": reg,
            "scored": scored,
            "expired_unscorable": expired,
            "pending": pending,
            "ok": reg == scored + expired + pending,
        }

    # ------------------------------------------------------ calibration
    def note_fold(self, *, t: int, updates: int, inside: int,
                  inn_n: float, inn_e: float, anomalies: dict,
                  table: dict) -> None:
        """One fold's calibration contribution, called by the engine
        under its fold lock.  ``anomalies`` is the engine's CUMULATIVE
        per-reason counter dict; the reason set is CLOSED — an unknown
        reason raises (a new detector must be wired through the docs
        and the metric label set, never silently binned)."""
        from heatmap_tpu.infer.engine import ANOMALY_REASONS

        unknown = set(anomalies) - set(ANOMALY_REASONS)
        if unknown:
            raise ValueError(
                f"unknown anomaly reason(s) {sorted(unknown)}: the "
                f"quality ledger's reason set is pinned closed to "
                f"{ANOMALY_REASONS}")
        with self._lock:
            deltas = {}
            for r in ANOMALY_REASONS:
                cur = int(anomalies.get(r, 0))
                deltas[r] = cur - self._anom_last.get(r, 0)
                self._anom_last[r] = cur
            self._folds.append((int(t), int(updates), int(inside),
                                float(inn_n), float(inn_e), deltas))
            cutoff = int(t) - self.window_s
            while self._folds and self._folds[0][0] <= cutoff:
                self._folds.popleft()
            self._table = dict(table)
            if self._tbl_first is None:
                self._tbl_first = dict(table)
            self._publish_locked()

    def _window_stats_locked(self) -> dict:
        upd = sum(f[1] for f in self._folds)
        inside = sum(f[2] for f in self._folds)
        inn_n = sum(f[3] for f in self._folds)
        inn_e = sum(f[4] for f in self._folds)
        rates: dict = {}
        if self._folds:
            t0 = self._folds[0][0]
            t1 = self._folds[-1][0]
            span = max(float(t1 - t0), 1.0)
            for _t, _u, _i, _n, _e, d in self._folds:
                for r, n in d.items():
                    rates[r] = rates.get(r, 0.0) + n
            rates = {r: round(n / span, 4) for r, n in rates.items()}
        cov = inside / upd if upd else None
        bias = ((inn_n / upd) ** 2 + (inn_e / upd) ** 2) ** 0.5 \
            if upd else None
        band_err = 0.0
        if cov is not None and upd >= MIN_WINDOW_UPDATES:
            lo, hi = self.band
            band_err = max(0.0, lo - cov, cov - hi)
        return {"updates": upd, "inside": inside, "coverage": cov,
                "band_error": round(band_err, 4), "bias_m": bias,
                "anomaly_rate": rates}

    def _publish_locked(self) -> None:
        if self._g_cov is None:
            return
        s = self._window_stats_locked()
        if s["coverage"] is not None:
            self._g_cov.set(round(s["coverage"], 4))
            self._g_band.set(s["band_error"])
        if s["bias_m"] is not None:
            self._g_bias.set(round(s["bias_m"], 3))
        for r, v in s["anomaly_rate"].items():
            self._g_rate.labels(reason=r).set(v)

    # --------------------------------------------------------- surfaces
    def _worst_skill_locked(self):
        """(grid, h, rolling skill) of the worst-scoring horizon."""
        worst = None
        for (grid, h), roll in self._skill_roll.items():
            if not roll:
                continue
            v = sum(roll) / len(roll)
            if worst is None or v < worst[2]:
                worst = (grid, h, v)
        return worst

    def healthz_checks(self) -> tuple[dict, bool]:
        """Instant quality checks merged into /healthz; the burn-rate
        duration discipline lives in obs/slo.py over the same gauges —
        these provide the NAMING (grid, reducer, shard) the generic
        slo_* checks cannot."""
        checks: dict = {}
        degraded = False
        with self._lock:
            cal = self._window_stats_locked()
            worst = self._worst_skill_locked()
        ident = self.identity()
        if cal["coverage"] is not None \
                and cal["updates"] >= MIN_WINDOW_UPDATES:
            lo, hi = self.band
            ok = cal["band_error"] <= 0.0
            check = {"value": round(cal["coverage"], 4),
                     "budget": f"[{lo:g}, {hi:g}]", "ok": ok}
            if not ok:
                check["detail"] = (
                    f"NIS coverage {cal['coverage']:.3f} outside the "
                    f"calibration band (reducer={self.reducer}, "
                    f"shard={self.tag or '?'}, "
                    f"updates={cal['updates']})")
            checks["quality_nis_coverage"] = check
            degraded |= not ok
        if worst is not None:
            grid, h, v = worst
            ok = v >= self.skill_floor
            check = {"value": round(v, 4), "budget": self.skill_floor,
                     "ok": ok}
            if not ok:
                check["detail"] = (
                    f"live forecast skill {v:.3f} below the SLO floor "
                    f"(grid={grid}, h={h}s, reducer={self.reducer}, "
                    f"shard={self.tag or '?'})")
            checks["quality_forecast_skill"] = check
            degraded |= not ok
        if not ident["ok"]:
            checks["quality_scorecards"] = {
                "value": (f"registered={ident['registered']} != "
                          f"scored={ident['scored']} + expired="
                          f"{ident['expired_unscorable']} + pending="
                          f"{ident['pending']}"),
                "ok": False,
                "detail": "scorecard conservation identity violated "
                          f"(shard={self.tag or '?'})"}
            degraded = True
        return checks, degraded

    def member_block(self) -> dict:
        """The fleet snapshot's ``quality`` block (obs.xproc) —
        /fleet/quality plain-sums these and names the worst shard."""
        with self._lock:
            cal = self._window_stats_locked()
            skill = {f"{g}|{h}": round(sum(r) / len(r), 4)
                     for (g, h), r in self._skill_roll.items() if r}
            table = dict(self._table)
            first = dict(self._tbl_first or {})
        ident = self.identity()
        pressure = {}
        if table:
            cap = max(int(table.get("capacity", 0)), 1)
            ev_ttl = int(table.get("evicted_ttl", 0)) \
                - int(first.get("evicted_ttl", 0))
            ev_lru = int(table.get("evicted_lru", 0)) \
                - int(first.get("evicted_lru", 0))
            pressure = {
                "occupancy": int(table.get("entities", 0)),
                "capacity": cap,
                "occupancy_frac": round(
                    int(table.get("entities", 0)) / cap, 4),
                "evicted_ttl": ev_ttl,
                "evicted_lru": ev_lru,
                "lru_evict_frac": round(
                    ev_lru / max(ev_ttl + ev_lru, 1), 4),
                "reseed_handoff": int(table.get("reseed_handoff", 0)),
                "reseed_teleport": int(table.get("reseed_teleport", 0)),
            }
        return {
            "enabled": True,
            "scorecards": ident,
            "skill": skill,
            "skill_floor": self.skill_floor,
            "nis": {
                "coverage": (round(cal["coverage"], 4)
                             if cal["coverage"] is not None else None),
                "band": list(self.band),
                "band_error": cal["band_error"],
                "updates": cal["updates"],
                "bias_m": (round(cal["bias_m"], 3)
                           if cal["bias_m"] is not None else None),
            },
            "anomaly_rate": cal["anomaly_rate"],
            "table": pressure,
        }

    def snapshot(self) -> dict:
        """The flight-record enrichment: the full calibration picture
        at dump time — what the SLO engine's drift dump carries."""
        blk = self.member_block()
        with self._lock:
            blk["last_score"] = self._last_score
            blk["pending_tail"] = [
                {k: card[k] for k in ("grid", "h", "base_ts",
                                      "target_ts")}
                for card in list(self._pending)[-8:]]
        return blk

    # ------------------------------------------------------- checkpoint
    def snapshot_extra(self) -> dict:
        """Checkpoint extras payload (numpy-array dict, like the infer
        table): the pending scorecards + resolved counters as one JSON
        blob, committed atomically WITH the entity table and offsets so
        a kill+resume keeps the conservation identity exact and scores
        restored cards via the history tier."""
        import numpy as np

        with self._lock:
            state = {
                "registered": self._registered,
                "outcomes": dict(self._outcomes),
                "pending": list(self._pending),
            }
        blob = json.dumps(state).encode("utf-8")
        return {"state": np.frombuffer(blob, dtype=np.uint8)}

    def restore_extra(self, data: dict) -> int:
        """Restore a :meth:`snapshot_extra` payload; returns the number
        of pending scorecards resumed."""
        import numpy as np

        raw = data.get("state")
        if raw is None:
            return 0
        try:
            state = json.loads(np.asarray(raw, np.uint8).tobytes()
                               .decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            log.warning("quality checkpoint extra unreadable; starting "
                        "cold", exc_info=True)
            return 0
        with self._lock:
            self._registered = int(state.get("registered", 0))
            for o in SCORE_OUTCOMES:
                self._outcomes[o] = int(
                    (state.get("outcomes") or {}).get(o, 0))
            self._pending = deque(state.get("pending") or ())
            return len(self._pending)


# ------------------------------------------------------------ provenance
def quality_stamp(block: dict | None = None,
                  env: Mapping[str, str] | None = None) -> dict:
    """The ``quality`` artifact block bench.py / tools/bench_infer.py
    stamp: knob state, the run's live skill and NIS coverage (from the
    observatory's member block when the caller has one), and how many
    quality-drift SLO alerts fired (from the members' persisted
    slo-state.json, the same cross-process path as slo_stamp).

    {} when HEATMAP_QUALITY is off — a knob-off artifact stays
    byte-compatible with pre-quality rounds.  Refusal provenance:
    tools/check_bench_regress.py REFUSES an artifact whose run fired a
    drift alert and refuses mixed quality-knob pairs, and ratchets
    live_skill when both rounds carry one."""
    e = os.environ if env is None else env
    if not quality_enabled(e):
        return {}
    out = {"enabled": True, "live_skill": None, "nis_coverage": None,
           "drift_alerts": 0}
    if isinstance(block, dict):
        skills = [v for v in (block.get("skill") or {}).values()
                  if isinstance(v, (int, float))]
        if skills:
            out["live_skill"] = round(min(skills), 4)
        cov = (block.get("nis") or {}).get("coverage")
        if isinstance(cov, (int, float)):
            out["nis_coverage"] = round(float(cov), 4)
    # drift alerts: the quality SLOs' fired counts across every
    # member's persisted slo-state.json (absent/neutral without tsdb)
    from heatmap_tpu.obs.tsdb import ENV_DIR

    d = e.get(ENV_DIR, "")
    if d:
        import glob as _glob

        for p in sorted(_glob.glob(os.path.join(
                _glob.escape(d), "*", "slo-state.json"))):
            try:
                with open(p, "r", encoding="utf-8") as fh:
                    st = json.load(fh)
            except (OSError, ValueError):
                continue
            specs = st.get("specs") if isinstance(st, dict) else None
            if not isinstance(specs, dict):
                continue
            for name in QUALITY_SLOS:
                s = specs.get(name)
                if isinstance(s, dict):
                    out["drift_alerts"] += int(
                        s.get("alerts_total", 0))
    return {"quality": out}
