"""Failure detection + elastic restart for the streaming job.

The reference delegates this entirely to Spark's restart-from-checkpoint
model (SURVEY.md §5.3; reference: heatmap_stream.py:241-249 relies on
the cluster manager to resurrect a dead driver).  Here the framework
owns it: the supervisor runs the streaming job as a child process and
restarts it from its own checkpoint when it crashes — or when it
*stalls*, the failure mode clusters can't see from an exit code.

Why a stall detector is first-class: with a remote-attached accelerator
(TPU over a tunnel), the observed failure mode is not a crash but a
device op that never returns — the JAX client sleeps in a read against a
connection that no longer exists.  The runtime's step loop writes a
heartbeat file (MicroBatchRuntime._touch_heartbeat, at most 1/s); the
supervisor declares a stall when the beacon goes quiet past
``stall_timeout_s``, kills the child, and restarts it.  The sink's
idempotent upserts + the offsets-after-commit checkpoint discipline make
the replay safe (same contract that makes crash-restart safe,
stream/checkpoint.py).

Optional platform failover: after ``failover_after`` consecutive
failures, the child is restarted with ``HEATMAP_PLATFORM=<failover_
platform>`` (default cpu) so a pipeline whose accelerator link died
keeps serving — degraded — instead of crash-looping.  Set
``failover_after=None`` to insist on the accelerator.

Usage: ``python -m heatmap_tpu.stream --supervise [pipeline]`` (the CLI
builds the child argv from its own), or programmatically::

    Supervisor([sys.executable, "-m", "heatmap_tpu.stream", "mbta"],
               RestartPolicy(stall_timeout_s=120)).run()
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import NamedTuple

from heatmap_tpu.obs import ENV_CHANNEL, SupervisorChannel

log = logging.getLogger("supervisor")


class RestartPolicy(NamedTuple):
    """Restart budget and failure thresholds.

    ``max_restarts`` within ``window_s`` bounds a crash loop (an old
    failure ages out of the budget after the window); exponential
    backoff between restarts keeps a hard-down dependency from being
    hammered."""

    max_restarts: int = 5
    window_s: float = 300.0
    backoff_s: float = 1.0
    backoff_max_s: float = 30.0
    # After the first beacon, a beacon gap past this declares a stall.
    # Legitimate LONG device ops mid-run (slab-growth retrace, post-
    # failover recompile) are covered by the runtime's in-flight beacon
    # watchdog (runtime._hb_watchdog_loop), which keeps the beacon alive
    # while a step is dispatching for up to HEATMAP_DISPATCH_GRACE_S
    # (default 300 s) — so only an op that outlives BOTH that grace and
    # this timeout is killed.  Raise HEATMAP_DISPATCH_GRACE_S (child
    # env) rather than this if recompiles are routinely slower.
    stall_timeout_s: float = 120.0
    # grace before the FIRST beacon: the child's first step traces and
    # compiles the whole streaming program, which on a remote-attached
    # chip routinely takes minutes — killing it mid-compile would make
    # supervised mode unable to ever start.  After the first beacon the
    # tighter stall_timeout_s applies.
    startup_grace_s: float = 600.0
    term_grace_s: float = 10.0     # SIGTERM → SIGKILL escalation
    failover_after: int | None = None
    failover_platform: str = "cpu"

    @classmethod
    def from_env(cls, env=os.environ) -> "RestartPolicy":
        """Env-var form for the CLI (HEATMAP_SUPERVISE_* namespace)."""
        def _f(name, cast, default):
            v = env.get(f"HEATMAP_SUPERVISE_{name}")
            return cast(v) if v not in (None, "") else default

        failover = _f("FAILOVER_AFTER", int, None)
        return cls(
            max_restarts=_f("MAX_RESTARTS", int, cls._field_defaults["max_restarts"]),
            window_s=_f("WINDOW_S", float, cls._field_defaults["window_s"]),
            backoff_s=_f("BACKOFF_S", float, cls._field_defaults["backoff_s"]),
            backoff_max_s=_f("BACKOFF_MAX_S", float,
                             cls._field_defaults["backoff_max_s"]),
            stall_timeout_s=_f("STALL_TIMEOUT_S", float,
                               cls._field_defaults["stall_timeout_s"]),
            startup_grace_s=_f("STARTUP_GRACE_S", float,
                               cls._field_defaults["startup_grace_s"]),
            term_grace_s=_f("TERM_GRACE_S", float,
                            cls._field_defaults["term_grace_s"]),
            failover_after=failover,
            failover_platform=_f("FAILOVER_PLATFORM", str,
                                 cls._field_defaults["failover_platform"]),
        )


class Supervisor:
    def __init__(self, argv: list[str], policy: RestartPolicy | None = None,
                 env: dict | None = None, heartbeat_path: str | None = None,
                 poll_s: float = 0.2, channel_path: str | None = None):
        self.argv = list(argv)
        self.policy = policy or RestartPolicy()
        self.env = dict(env if env is not None else os.environ)
        self.heartbeat_path = heartbeat_path or os.path.join(
            tempfile.gettempdir(), f"heatmap-hb-{os.getpid()}")
        self.poll_s = poll_s
        self.restarts = 0            # total child launches after the first
        self.failed_over = False
        # cross-process metrics channel (obs.xproc): the child's /metrics
        # merges this file's restart/backoff/failover counters.  The path
        # defaults next to the heartbeat; a caller-supplied STABLE path
        # (or a pre-set env var) also survives supervisor restarts —
        # resume() folds persisted totals back in either way.
        self.channel = SupervisorChannel(
            channel_path or self.env.get(ENV_CHANNEL)
            or self.heartbeat_path + ".chan").resume()
        # resumed launch total: published counters continue from the
        # predecessor supervisor's count instead of resetting to this
        # process's self.restarts
        self._restarts_base = int(self.channel.state["restarts_total"])
        # fleet observatory (obs.fleet): the supervisor is a member too
        # — its snapshot carries the channel counters + its own verdict
        # so /fleet/healthz can see the control plane, not just the
        # children.  Fixed tag: one supervisor per channel (the env
        # HEATMAP_FLEET_TAG names the CHILD runtime, which inherits it).
        self._fleet_tag = "supervisor"
        self._member_pub_last = 0.0
        # A plain bool, NOT a threading.Event: stop() runs inside signal
        # handlers (supervise_cli), and Event.set() acquires the Event's
        # non-reentrant Condition lock — which the interrupted main
        # thread holds in the prologue/epilogue of every wait(), so a
        # badly-timed signal would self-deadlock the supervisor.  A bool
        # store is async-signal-safe; responsiveness comes from _wait()
        # sleeping in poll_s slices (a signal interrupts time.sleep, the
        # handler sets the flag, PEP 475 resumes the <=poll_s remainder,
        # and the slice loop exits — worst-case stop latency poll_s).
        self._stop_flag = False

    # -------------------------------------------------------------- child

    def _spawn(self) -> subprocess.Popen:
        env = dict(self.env)
        env["HEATMAP_HEARTBEAT_FILE"] = self.heartbeat_path
        env[ENV_CHANNEL] = self.channel.path
        try:
            os.remove(self.heartbeat_path)  # age from THIS child's start
        except OSError:
            pass
        log.info("starting child: %s", " ".join(self.argv))
        self.channel.update(
            child_running=1,
            restarts_total=self._restarts_base + self.restarts,
            failed_over=int(self.failed_over))
        return subprocess.Popen(self.argv, env=env)

    def _heartbeat_age(self, child_started: float) -> tuple[float, bool]:
        """(seconds since the child last proved liveness, beacon seen):
        age of its latest beacon write, or of its start time if it never
        wrote one (covers a child wedged inside backend init / the first
        compile — judged against startup_grace_s, not stall_timeout_s)."""
        try:
            return (time.monotonic() - max(
                child_started,
                self._mono_of(os.stat(self.heartbeat_path).st_mtime)), True)
        except OSError:
            return time.monotonic() - child_started, False

    @staticmethod
    def _mono_of(wall_ts: float) -> float:
        """Translate a wall-clock mtime onto the monotonic axis."""
        return time.monotonic() - max(0.0, time.time() - wall_ts)

    def _wait(self, seconds: float) -> None:
        """Sleep up to ``seconds``, returning within ``poll_s`` of
        stop() — including stop() from a signal handler.  Every slice
        also rides the fleet member publish (rate-limited inside), so
        the supervisor stays fresh on /fleet/healthz through poll loops
        AND long restart backoffs alike."""
        deadline = time.monotonic() + seconds
        while not self._stop_flag:
            self._publish_member_snapshot()
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(self.poll_s, left))

    def _publish_member_snapshot(self, force: bool = False,
                                 left: bool = False) -> None:
        """Fleet member snapshot for the supervisor itself (obs.xproc):
        channel counters as exposition text + a control-plane verdict.
        Rate-limited to HEATMAP_FLEET_PUBLISH_S (0 disables); guarded —
        telemetry never takes the supervisor down."""
        from heatmap_tpu.obs.xproc import (fleet_publish_s,
                                           publish_member_snapshot,
                                           supervisor_metrics_lines)

        interval = fleet_publish_s()
        if interval <= 0:
            return
        now = time.monotonic()
        if not force and now - self._member_pub_last < interval:
            return
        self._member_pub_last = now
        try:
            chan = SupervisorChannel.metrics_from(self.channel.path)
            lines = supervisor_metrics_lines(chan)
            checks = {
                "child_running": {
                    "value": int(chan.get("child_running", 0)), "ok": True},
            }
            degraded = bool(self.failed_over)
            if self.failed_over:
                checks["failover"] = {
                    "value": self.env.get("HEATMAP_PLATFORM", "?"),
                    "ok": False}
            down = bool(chan.get("gave_up"))
            if down:
                checks["supervisor"] = {"value": "gave_up", "ok": False}
            healthz = {
                "ok": not down,
                "status": ("down" if down
                           else "degraded" if degraded else "ok"),
                "checks": checks,
            }
            publish_member_snapshot(
                self.channel.path, self._fleet_tag, role="supervisor",
                metrics_text="\n".join(lines) + ("\n" if lines else ""),
                healthz=healthz, left=left)
        except Exception:  # noqa: BLE001 - never kill the supervise loop
            log.warning("supervisor fleet snapshot publish failed",
                        exc_info=True)

    def _kill(self, proc: subprocess.Popen) -> None:
        """SIGTERM, grace period, SIGKILL."""
        if proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(self.policy.term_grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()

    # --------------------------------------------------------------- loop

    def run(self) -> int:
        """Supervise until the child exits 0 (done), the restart budget
        is exhausted, or stop() is called.  Returns the final child exit
        code (0 on clean completion)."""
        p = self.policy
        recent: list[float] = []     # monotonic times of recent failures
        backoff = p.backoff_s
        failures_in_a_row = 0
        rc = 1
        while not self._stop_flag:
            proc = self._spawn()
            started = time.monotonic()
            reason = None
            healthy_span = 0.0
            while reason is None and not self._stop_flag:
                code = proc.poll()
                if code is not None:
                    if code == 0:
                        log.info("child exited cleanly; done")
                        self.channel.update(child_running=0)
                        # departure tombstone: a finished job leaves
                        # the fleet instead of going "stale" on it
                        self._publish_member_snapshot(force=True,
                                                      left=True)
                        return 0
                    reason = f"exit code {code}"
                    # exit-code failure: the child ran under its own
                    # power until it ended — its lifetime was healthy
                    healthy_span = time.monotonic() - started
                    rc = code
                    break
                age, beacon_seen = self._heartbeat_age(started)
                limit = (p.stall_timeout_s if beacon_seen
                         else max(p.stall_timeout_s, p.startup_grace_s))
                if age > limit:
                    reason = f"stall: no heartbeat for >{limit:.1f}s"
                    # healthy span ends at the LAST beacon, not at kill
                    # time: the stall-detection wait is not health, or a
                    # child that only ever wedged (startup grace > window)
                    # would reset the streak on every iteration and the
                    # budget/failover could never trip
                    healthy_span = max(0.0,
                                       time.monotonic() - started - age)
                    self._kill(proc)
                    rc = 1
                    break
                self._wait(self.poll_s)
            if self._stop_flag:
                self._kill(proc)
                log.info("stopped; child terminated")
                self.channel.update(child_running=0)
                self._publish_member_snapshot(force=True, left=True)
                return 0
            # failure bookkeeping for the child's /metrics and the
            # /healthz restart-rate SLO: timestamps retained for at
            # least an hour (the SLO's rate window)
            self.channel.note_failure(
                reason, stalled=reason.startswith("stall"),
                window_s=max(3600.0, p.window_s))
            # fleet episode correlation (obs.xproc): a dead child is ONE
            # incident across the whole fleet — claim (or join) the
            # episode broadcast so every surviving member's watchdog
            # writes its flight-recorder dump under the same id.  The
            # broadcast itself is a file write; it happens whether or
            # not THIS process records flights.
            from heatmap_tpu.obs.xproc import clear_episode, ensure_episode

            if healthy_span > p.window_s:
                # a failure after a FULL healthy window is a separate
                # incident: close our own previous episode (if it is
                # still broadcast) so this one mints a fresh id — joined
                # stale, the surviving watchdogs would skip it as
                # already-dumped and the new incident would leave no
                # correlated dump set
                clear_episode(self.channel.path, origin=self._fleet_tag)
            episode = ensure_episode(self.channel.path, self._fleet_tag,
                                     f"child failed ({reason})")
            # supervisor-side flight record (obs.flightrec): the child's
            # own recorder misses hard deaths (SIGKILL, a wedged device
            # op the stall detector shot) — dump the PARENT's view so
            # every failure leaves a post-mortem artifact.  Best-effort:
            # dump_snapshot never raises.
            frdir = self.env.get("HEATMAP_FLIGHTREC_DIR")
            if frdir:
                from heatmap_tpu.obs.flightrec import dump_snapshot

                dump_snapshot(frdir, f"supervisor: child failed ({reason})",
                              {"channel": dict(self.channel.state),
                               "argv": self.argv,
                               "failed_over": self.failed_over,
                               "restarts": self.restarts,
                               **({"episode": episode} if episode else {})},
                              episode_id=episode.get("episode_id"))
            # forced: the failure bookkeeping (and the open episode)
            # must reach /fleet/healthz now, not a publish-cadence later
            self._publish_member_snapshot(force=True)
            if healthy_span > p.window_s:
                # the child ran healthy for a full budget window before
                # this failure — an isolated blip, not a streak.  Without
                # the reset, one crash a day would eventually trip
                # failover_after and permanently degrade to the failover
                # platform despite a working accelerator.
                failures_in_a_row = 0
                backoff = p.backoff_s
            failures_in_a_row += 1
            now = time.monotonic()
            recent = [t for t in recent if now - t <= p.window_s]
            recent.append(now)
            if len(recent) > p.max_restarts:
                log.error("giving up: %d failures within %.0fs (last: %s)",
                          len(recent), p.window_s, reason)
                self.channel.update(gave_up=1, child_running=0)
                self._publish_member_snapshot(force=True)
                return rc
            if (p.failover_after is not None and not self.failed_over
                    and failures_in_a_row >= p.failover_after):
                log.warning(
                    "%d consecutive failures — failing over to "
                    "HEATMAP_PLATFORM=%s (degraded; restart without the "
                    "override to return to the accelerator)",
                    failures_in_a_row, p.failover_platform)
                self.env["HEATMAP_PLATFORM"] = p.failover_platform
                self.failed_over = True
                self.channel.update(
                    failovers_total=self.channel.state["failovers_total"]
                    + 1, failed_over=1)
            log.warning("child failed (%s); restarting in %.1fs "
                        "(%d/%d in window)", reason, backoff,
                        len(recent), p.max_restarts)
            self.restarts += 1
            self.channel.update(
                child_running=0, backoff_s=backoff,
                restarts_total=self._restarts_base + self.restarts)
            self._wait(backoff)
            backoff = min(backoff * 2, p.backoff_max_s)
        if self._stop_flag:  # stop() during backoff = clean stop
            self._publish_member_snapshot(force=True, left=True)
            return 0
        return rc

    def stop(self) -> None:
        """Ask run() to terminate the child and return (signal-safe)."""
        self._stop_flag = True


class _ShardChild:
    """Per-shard lifecycle record of a FleetSupervisor (one child =
    one H3-partitioned runtime shard, stream/shardmap.py)."""

    def __init__(self, index: int, heartbeat_path: str):
        self.index = index
        self.tag = f"shard{index}"
        self.heartbeat_path = heartbeat_path
        self.proc: subprocess.Popen | None = None
        self.started = 0.0
        self.recent: list[float] = []   # monotonic times of failures
        self.backoff = 0.0
        self.next_spawn_at = 0.0        # monotonic; 0 = spawn now
        self.restarts = 0
        self.done = False               # clean exit 0
        self.gave_up = False
        self.rc = 0

    @property
    def terminal(self) -> bool:
        return self.done or self.gave_up


class FleetSupervisor:
    """Spawn/restart/SIGTERM-fanout for the N shard children of a
    partitioned runtime (ISSUE 7 tentpole; the single-child Supervisor
    above is unchanged for unsharded jobs).

    Every child runs the same argv with a per-shard env:
    ``HEATMAP_SHARDS=N``, ``HEATMAP_SHARD_INDEX=i``, its own heartbeat
    file, and the SHARED supervisor channel — so each shard publishes
    PR 6 member snapshots tagged ``shard<i>`` and its own per-shard
    checkpoint namespace resumes only its own offsets.  Failure
    handling is per child (stall detection, exponential backoff,
    restart budget); a failure claims/joins ONE fleet episode so every
    member's flight-recorder dump for the incident correlates.  One
    child exhausting its budget marks that shard down (the fleet keeps
    serving its remaining cell space, degraded) rather than killing
    the whole fleet.  Platform failover is not fanned out: a per-shard
    CPU fallback would desync the fleet's partition economics — the
    policy's ``failover_after`` is ignored with a warning."""

    def __init__(self, argv: list[str], n_shards: int,
                 policy: RestartPolicy | None = None,
                 env: dict | None = None, heartbeat_dir: str | None = None,
                 poll_s: float = 0.2, channel_path: str | None = None):
        if n_shards < 2:
            raise ValueError(f"FleetSupervisor needs >= 2 shards, "
                             f"got {n_shards}")
        self.argv = list(argv)
        self.n_shards = int(n_shards)
        self.policy = policy or RestartPolicy()
        if self.policy.failover_after is not None:
            log.warning("fleet mode ignores failover_after: a per-shard "
                        "platform failover would desync the fleet")
        self.env = dict(env if env is not None else os.environ)
        hb_dir = heartbeat_dir or tempfile.gettempdir()
        self.poll_s = poll_s
        self.channel = SupervisorChannel(
            channel_path or self.env.get(ENV_CHANNEL)
            or os.path.join(hb_dir, f"heatmap-fleet-{os.getpid()}.chan")
        ).resume()
        self._restarts_base = int(self.channel.state["restarts_total"])
        self.children = [
            _ShardChild(i, os.path.join(
                hb_dir, f"heatmap-hb-{os.getpid()}-shard{i}"))
            for i in range(n_shards)]
        self.restarts = 0
        self._fleet_tag = "supervisor"
        self._member_pub_last = 0.0
        self._stop_flag = False  # plain bool: signal-safe (see Supervisor)

    # -------------------------------------------------------------- child

    def _spawn(self, ch: _ShardChild) -> None:
        env = dict(self.env)
        env["HEATMAP_SHARDS"] = str(self.n_shards)
        env["HEATMAP_SHARD_INDEX"] = str(ch.index)
        env["HEATMAP_HEARTBEAT_FILE"] = ch.heartbeat_path
        env[ENV_CHANNEL] = self.channel.path
        try:
            os.remove(ch.heartbeat_path)  # age from THIS launch
        except OSError:
            pass
        log.info("starting shard %d: %s", ch.index, " ".join(self.argv))
        ch.proc = subprocess.Popen(self.argv, env=env)
        ch.started = time.monotonic()
        self._publish_state()

    def _kill(self, proc: subprocess.Popen) -> None:
        if proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(self.policy.term_grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()

    def _heartbeat_age(self, ch: _ShardChild) -> tuple[float, bool]:
        try:
            return (time.monotonic() - max(
                ch.started,
                Supervisor._mono_of(
                    os.stat(ch.heartbeat_path).st_mtime)), True)
        except OSError:
            return time.monotonic() - ch.started, False

    def _publish_state(self) -> None:
        self.channel.update(
            child_running=sum(1 for c in self.children
                              if c.proc is not None
                              and c.proc.poll() is None),
            restarts_total=self._restarts_base + self.restarts,
            gave_up=int(all(c.gave_up for c in self.children)))

    def _publish_member_snapshot(self, force: bool = False,
                                 left: bool = False) -> None:
        """The fleet supervisor's own member snapshot: channel counters
        plus one check per shard child, so /fleet/healthz names the
        down shard from the control plane's view too."""
        from heatmap_tpu.obs.xproc import (fleet_publish_s,
                                           publish_member_snapshot,
                                           supervisor_metrics_lines)

        interval = fleet_publish_s()
        if interval <= 0:
            return
        now = time.monotonic()
        if not force and now - self._member_pub_last < interval:
            return
        self._member_pub_last = now
        try:
            chan = SupervisorChannel.metrics_from(self.channel.path)
            lines = supervisor_metrics_lines(chan)
            checks = {}
            degraded = False
            for c in self.children:
                running = c.proc is not None and c.proc.poll() is None
                state = ("gave_up" if c.gave_up
                         else "done" if c.done
                         else "running" if running else "backoff")
                ok = not c.gave_up
                degraded |= not ok
                checks[c.tag] = {"value": state, "ok": ok}
            down = all(c.gave_up for c in self.children)
            healthz = {
                "ok": not down,
                "status": ("down" if down
                           else "degraded" if degraded else "ok"),
                "checks": checks,
            }
            publish_member_snapshot(
                self.channel.path, self._fleet_tag, role="supervisor",
                metrics_text="\n".join(lines) + ("\n" if lines else ""),
                healthz=healthz, left=left)
        except Exception:  # noqa: BLE001 - never kill the supervise loop
            log.warning("fleet supervisor snapshot publish failed",
                        exc_info=True)

    def _note_failure(self, ch: _ShardChild, reason: str,
                      healthy_span: float) -> None:
        p = self.policy
        self.channel.note_failure(
            f"{ch.tag}: {reason}", stalled=reason.startswith("stall"),
            window_s=max(3600.0, p.window_s))
        from heatmap_tpu.obs.xproc import clear_episode, ensure_episode

        if healthy_span > p.window_s:
            # separate incident after a full healthy window — same rule
            # as the single-child supervisor: close our own broadcast
            # so this incident mints a fresh id
            clear_episode(self.channel.path, origin=self._fleet_tag)
            ch.recent = []
            ch.backoff = p.backoff_s
        episode = ensure_episode(self.channel.path, self._fleet_tag,
                                 f"{ch.tag} failed ({reason})")
        frdir = self.env.get("HEATMAP_FLIGHTREC_DIR")
        if frdir:
            from heatmap_tpu.obs.flightrec import dump_snapshot

            dump_snapshot(
                frdir, f"fleet supervisor: {ch.tag} failed ({reason})",
                {"channel": dict(self.channel.state), "argv": self.argv,
                 "shard": ch.index, "restarts": ch.restarts,
                 **({"episode": episode} if episode else {})},
                episode_id=episode.get("episode_id"))
        now = time.monotonic()
        ch.recent = [t for t in ch.recent if now - t <= p.window_s]
        ch.recent.append(now)
        if len(ch.recent) > p.max_restarts:
            log.error("%s: giving up — %d failures within %.0fs (last: "
                      "%s); the fleet keeps serving without its cell "
                      "space", ch.tag, len(ch.recent), p.window_s, reason)
            ch.gave_up = True
        else:
            backoff = ch.backoff or p.backoff_s
            log.warning("%s failed (%s); restarting in %.1fs (%d/%d in "
                        "window)", ch.tag, reason, backoff,
                        len(ch.recent), p.max_restarts)
            ch.next_spawn_at = now + backoff
            ch.backoff = min(backoff * 2, p.backoff_max_s)
            ch.restarts += 1
            self.restarts += 1
        self._publish_state()
        self._publish_member_snapshot(force=True)

    # --------------------------------------------------------------- loop

    def run(self) -> int:
        """Supervise until every shard is terminal (exited 0 or
        exhausted its budget) or stop() is called.  Returns 0 when
        every shard ended cleanly (or on stop), else the first failing
        shard's exit code."""
        p = self.policy
        while not self._stop_flag:
            now = time.monotonic()
            for ch in self.children:
                if ch.terminal:
                    continue
                if ch.proc is None:
                    if now >= ch.next_spawn_at:
                        self._spawn(ch)
                    continue
                code = ch.proc.poll()
                if code is not None:
                    ch.proc = None
                    span = time.monotonic() - ch.started
                    if code == 0:
                        log.info("%s exited cleanly", ch.tag)
                        ch.done = True
                        self._publish_state()
                    else:
                        ch.rc = code
                        self._note_failure(ch, f"exit code {code}", span)
                    continue
                age, beacon_seen = self._heartbeat_age(ch)
                limit = (p.stall_timeout_s if beacon_seen
                         else max(p.stall_timeout_s, p.startup_grace_s))
                if age > limit:
                    span = max(0.0, time.monotonic() - ch.started - age)
                    self._kill(ch.proc)
                    ch.proc = None
                    ch.rc = 1
                    self._note_failure(
                        ch, f"stall: no heartbeat for >{limit:.1f}s", span)
            if all(c.terminal for c in self.children):
                break
            self._publish_member_snapshot()
            time.sleep(self.poll_s)
        if self._stop_flag:
            # SIGTERM fanout: every live shard gets the same stop
            for ch in self.children:
                if ch.proc is not None:
                    self._kill(ch.proc)
                    ch.proc = None
            log.info("stopped; %d shard children terminated",
                     self.n_shards)
            self._publish_state()
            self._publish_member_snapshot(force=True, left=True)
            return 0
        self._publish_state()
        clean = all(c.done for c in self.children)
        self._publish_member_snapshot(force=True, left=clean)
        if clean:
            return 0
        return next((c.rc for c in self.children if c.gave_up and c.rc),
                    1)

    def stop(self) -> None:
        """Ask run() to SIGTERM-fanout and return (signal-safe)."""
        self._stop_flag = True


def supervise_cli(child_argv: list[str], shards: int = 1) -> int:
    """CLI glue: run ``child_argv`` under a Supervisor (or, with
    ``shards`` > 1, a FleetSupervisor fanning out N shard children)
    configured from HEATMAP_SUPERVISE_* env vars; SIGTERM/SIGINT stop
    children + parent."""
    if shards > 1:
        sup: "Supervisor | FleetSupervisor" = FleetSupervisor(
            child_argv, shards, RestartPolicy.from_env())
    else:
        sup = Supervisor(child_argv, RestartPolicy.from_env())

    def _on_signal(signum, frame):  # noqa: ARG001
        sup.stop()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    return sup.run()


if __name__ == "__main__":  # pragma: no cover - tiny manual harness
    logging.basicConfig(level=logging.INFO)
    sys.exit(supervise_cli(sys.argv[1:]))
