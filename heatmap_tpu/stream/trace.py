"""Profiling hooks (SURVEY.md §5.1: the reference has none; the BASELINE
metric is p50 micro-batch latency, so the hot loop must be traceable).

Two layers:

- wall-clock spans per batch (poll / build / device / sink_submit) feed
  ``stream.metrics`` and surface at /metrics — always on, nanosecond-cheap.
- a ``jax.profiler`` device trace, enabled by env: set
  ``HEATMAP_PROFILE_DIR=/tmp/trace`` to capture
  ``HEATMAP_PROFILE_BATCHES`` (default 16) batches starting at
  ``HEATMAP_PROFILE_SKIP`` (default 2, skipping compile batches).  The
  capture is viewable in TensorBoard / Perfetto; each batch is wrapped in
  a ``StepTraceAnnotation`` so device ops group by micro-batch.
"""

from __future__ import annotations

import contextlib
import logging
import os

log = logging.getLogger(__name__)


class Tracer:
    """Env-gated jax.profiler trace over a window of micro-batches."""

    def __init__(self, env=None):
        e = os.environ if env is None else env
        self.dir = e.get("HEATMAP_PROFILE_DIR", "")
        self.skip, self.batches = 2, 16
        if self.dir:  # only parse knobs when profiling is requested
            try:
                self.skip = int(e.get("HEATMAP_PROFILE_SKIP", self.skip))
                self.batches = int(e.get("HEATMAP_PROFILE_BATCHES",
                                         self.batches))
            except ValueError as err:
                log.warning("bad profiler env value (%s); using skip=%d "
                            "batches=%d", err, self.skip, self.batches)
        self._active = False
        self._done = bool(not self.dir)

    def batch(self, epoch: int):
        """Context manager wrapping one micro-batch."""
        if self._done and not self._active:
            return contextlib.nullcontext()
        return self._batch_ctx(epoch)

    @contextlib.contextmanager
    def _batch_ctx(self, epoch: int):
        import jax

        if not self._active and not self._done and epoch >= self.skip:
            try:
                jax.profiler.start_trace(self.dir)
                self._active = True
                self._stop_at = epoch + self.batches
                log.info("profiler: tracing %d batches -> %s",
                         self.batches, self.dir)
            except Exception as e:  # profiler races / unsupported backend
                log.warning("profiler start failed: %s", e)
                self._done = True
        if self._active:
            try:
                with jax.profiler.StepTraceAnnotation("microbatch",
                                                      step_num=epoch):
                    yield
            finally:
                # stop at window end, and on an exception escaping the
                # batch — a dangling trace would be lost and would block
                # any later capture in this process
                if epoch + 1 >= self._stop_at or self._exception_pending():
                    self.stop()
        else:
            yield

    @staticmethod
    def _exception_pending() -> bool:
        import sys

        return sys.exc_info()[0] is not None

    def stop(self) -> None:
        """Flush an in-flight trace (runtime.close() calls this so a short
        stream still writes its partial capture)."""
        if not self._active:
            return
        import jax

        try:
            jax.profiler.stop_trace()
            log.info("profiler: trace written to %s", self.dir)
        except Exception as e:
            log.warning("profiler stop failed: %s", e)
        self._active = False
        self._done = True
