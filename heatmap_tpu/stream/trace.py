"""Profiling hooks (SURVEY.md §5.1: the reference has none; the BASELINE
metric is p50 micro-batch latency, so the hot loop must be traceable).

Two layers:

- wall-clock spans per batch (poll / build / device / sink_submit) feed
  ``stream.metrics`` and surface at /metrics — always on, nanosecond-cheap.
- a ``jax.profiler`` device trace over a WINDOW of micro-batches,
  armed two ways:

  * at boot by env: ``HEATMAP_PROFILE_DIR=/tmp/trace`` captures
    ``HEATMAP_PROFILE_BATCHES`` (default 16) batches starting at
    ``HEATMAP_PROFILE_SKIP`` (default 2, skipping compile batches);
  * at runtime via :meth:`ProfilerTracer.arm` — the ``POST
    /debug/profile`` endpoint (serve.api) re-arms the stream runtime's
    tracer for a fresh window without a restart, the operability gap
    the boot-only env left open.

  The capture is viewable in TensorBoard / Perfetto; each batch is
  wrapped in a ``StepTraceAnnotation`` so device ops group by
  micro-batch.  One window may be in flight at a time: ``arm`` refuses
  (returns False → HTTP 409) while a window is pending or active.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading

log = logging.getLogger(__name__)


def _parse_window(e, skip: int, batches: int) -> tuple[int, int]:
    """Window knobs from env, defaults on garbage, clamped to sane
    bounds (a negative skip or a zero-batch window would arm a capture
    that can never produce a usable trace)."""
    try:
        skip = int(e.get("HEATMAP_PROFILE_SKIP", skip))
        batches = int(e.get("HEATMAP_PROFILE_BATCHES", batches))
    except ValueError as err:
        log.warning("bad profiler env value (%s); using skip=%d "
                    "batches=%d", err, skip, batches)
    return max(0, skip), max(1, batches)


class ProfilerTracer:
    """jax.profiler trace over a window of micro-batches.

    State machine: idle → pending (armed, epoch < skip) → active
    (tracing) → idle (window complete / stop()).  ``arm`` may re-enter
    only from idle.  The lock covers state TRANSITIONS; the per-batch
    fast path (idle, nothing armed) is one attribute read.
    """

    def __init__(self, env=None):
        e = os.environ if env is None else env
        self._lock = threading.Lock()
        self.dir = e.get("HEATMAP_PROFILE_DIR", "")
        self.skip, self.batches = 2, 16
        if self.dir:  # only parse knobs when profiling is requested
            self.skip, self.batches = _parse_window(e, self.skip,
                                                    self.batches)
        self._active = False
        self._done = bool(not self.dir)
        self._stop_at = 0

    # ------------------------------------------------------------ status
    @property
    def busy(self) -> bool:
        """A window is pending or actively tracing (arm would refuse)."""
        return self._active or not self._done

    def arm(self, dir_path: str, batches: int = 16, skip: int = 0,
            base_epoch: int = 0) -> bool:
        """Arm a capture window at runtime: trace ``batches``
        micro-batches starting ``skip`` batches after ``base_epoch``
        (the caller passes the runtime's current epoch, so ``skip``
        counts forward from NOW — the boot-time env counts from epoch
        0, where skipping compiles was the point).  False when a window
        is already pending/active — the caller answers 409."""
        if not dir_path:
            return False
        with self._lock:
            if self.busy:
                return False
            self.dir = dir_path
            self.skip = base_epoch + max(0, int(skip))
            self.batches = max(1, int(batches))
            self._done = False
            self._active = False
        log.info("profiler armed: %d batches from epoch %d -> %s",
                 self.batches, self.skip, self.dir)
        return True

    # ------------------------------------------------------------ window
    def batch(self, epoch: int):
        """Context manager wrapping one micro-batch."""
        if self._done and not self._active:
            return contextlib.nullcontext()
        return self._batch_ctx(epoch)

    @contextlib.contextmanager
    def _batch_ctx(self, epoch: int):
        import jax

        with self._lock:
            start = (not self._active and not self._done
                     and epoch >= self.skip)
            if start:
                try:
                    jax.profiler.start_trace(self.dir)
                    self._active = True
                    self._stop_at = epoch + self.batches
                    log.info("profiler: tracing %d batches -> %s",
                             self.batches, self.dir)
                except Exception as e:  # profiler races / unsupported
                    log.warning("profiler start failed: %s", e)
                    self._done = True
            active = self._active
        if active:
            try:
                with jax.profiler.StepTraceAnnotation("microbatch",
                                                      step_num=epoch):
                    yield
            finally:
                # stop at window end, and on an exception escaping the
                # batch — a dangling trace would be lost and would block
                # any later capture in this process
                if epoch + 1 >= self._stop_at or self._exception_pending():
                    self.stop()
        else:
            yield

    @staticmethod
    def _exception_pending() -> bool:
        import sys

        return sys.exc_info()[0] is not None

    def stop(self) -> None:
        """Flush an in-flight trace (runtime.close() calls this so a
        short stream still writes its partial capture).  Safe to call
        twice, and from a pending-but-not-started window (which it
        cancels)."""
        with self._lock:
            was_active, self._active = self._active, False
            self._done = True
        if not was_active:
            return
        import jax

        try:
            jax.profiler.stop_trace()
            log.info("profiler: trace written to %s", self.dir)
        except Exception as e:
            log.warning("profiler stop failed: %s", e)


# Historical name (PR 1 docstrings and the runtime import the short
# form; the ISSUE/serve layer use the explicit one).
Tracer = ProfilerTracer
