"""Runtime metrics: the counters BASELINE.json measures (SURVEY.md §5.5).

events/sec in, rows upserted, p50/p95 micro-batch latency, plus per-span
timings (poll / build / pull / snap / device / sink_submit) so the
bottleneck is visible.  Built on the obs registry: latency, freshness,
and spans are real fixed-bucket histograms with Prometheus exposition
(served at /metrics), while ``snapshot()`` keeps every historical JSON
key byte-compatible (served at /metrics.json) — the recent-window
quantiles the old Percentiles deque provided now come from each
histogram's bounded sample window.

Named event counters stay a plain ``collections.Counter`` (names are
dynamic, e.g. per-pair late counts) and are rendered into the
exposition generically as ``heatmap_<name>_total``.
"""

from __future__ import annotations

import collections
import time
from typing import Iterable, Mapping

from heatmap_tpu.obs import (
    DEFAULT_LAG_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Registry,
    render_flat_counters,
)

# Counter-dict entries that are point-in-time values, not monotonic
# counts — typed as gauges in the exposition
GAUGE_NAMES = frozenset({
    "state_overflow_last_epoch", "state_capacity_per_shard",
    "uptime_s", "events_per_sec",
})

# The CLOSED set of event-drop reasons (integrity observatory,
# obs.audit): every path that discards an event must account it under
# exactly one of these labels — an untagged drop is a permanent
# conservation-ledger residual (polled == folded + dropped{reason}).
#   invalid       parse/validation rejects (stream.events)
#   late          watermark-late (incl. the clock-skew future-window
#                 poison drop, which the device fold folds into late)
#   out_of_shard  rows owned by another H3 shard (stream/shardmap.py)
#   oversample    the same ownership drop in HEATMAP_SHARD_OVERSAMPLE
#                 mode, where foreign rows are the EXPECTED majority of
#                 every poll — labeled apart so partition-skew drops
#                 don't read as misrouted-topic trouble
#   exchange      all_to_all lane-skew overflow (parallel.sharded)
#   handoff       cross-shard entity handoff re-seeds (infer.engine):
#                 the event itself WAS folded by the count path — the
#                 tag records the Kalman reducer discarding an entity's
#                 cross-shard filter history, so it is always accounted
#                 with audit=False (outside the event-conservation
#                 identity, which stays closed without it)
# ``Metrics.drop`` validates against this set (tests pin it closed) and
# keeps the legacy flat counters in lockstep.
DROP_REASONS = ("invalid", "late", "out_of_shard", "oversample",
                "exchange", "handoff")
_DROP_LEGACY = {
    "invalid": "events_invalid",
    "late": "events_late",
    "out_of_shard": "events_out_of_shard",
    "oversample": "events_out_of_shard",
    "exchange": "events_bucket_dropped",
    "handoff": "infer_handoff_reseed",
}


class Metrics:
    def __init__(self):
        self.t_start = time.monotonic()
        self.counters: collections.Counter = collections.Counter()
        self.registry = Registry()
        self.batch_latency = self.registry.histogram(
            "heatmap_batch_latency_seconds",
            "end-to-end wall time of one micro-batch step",
            buckets=DEFAULT_TIME_BUCKETS)
        self.freshness = self.registry.histogram(
            "heatmap_freshness_seconds",
            "emit wall time minus the batch's newest event timestamp",
            buckets=DEFAULT_LAG_BUCKETS)
        self._span_fam = self.registry.histogram(
            "heatmap_batch_span_seconds",
            "per-batch span wall time (poll/build/pull/snap/device/"
            "sink_submit; span=total is the whole step)",
            labels=("span",), buckets=DEFAULT_TIME_BUCKETS)
        # ---- freshness lineage series (obs.lineage): these measure the
        # END-TO-END quantity the batch spans cannot — event timestamp
        # to sink-commit ack, through prefetch queueing and the
        # device-resident emit ring (batches park up to
        # HEATMAP_EMIT_FLUSH_K deep, which the per-stage spans
        # systematically understate)
        self.event_age = self.registry.histogram(
            "heatmap_event_age_seconds",
            "event timestamp to sink commit ack per flushed batch "
            "(bound=oldest/mean/newest event of the batch) — the "
            "end-to-end ingest-to-durability freshness",
            labels=("bound",), buckets=DEFAULT_LAG_BUCKETS)
        self.ring_residency = self.registry.histogram(
            "heatmap_emit_ring_residency_seconds",
            "wall seconds a packed emit batch stayed parked in the "
            "device emit ring before the flush that pulled it",
            buckets=DEFAULT_TIME_BUCKETS)
        self.ring_residency_batches = self.registry.histogram(
            "heatmap_emit_ring_residency_batches",
            "ring appends from a batch's own (inclusive) to the flush "
            "that pulled it — how many batches deep it was held",
            buckets=(1, 2, 4, 8, 16, 32, 64))
        # reason-labeled drop accounting (integrity observatory): one
        # family every drop path increments via ``drop`` — children
        # materialized up front so the exposition carries the full
        # closed reason set from step one
        self.dropped = self.registry.counter(
            "heatmap_events_dropped_total",
            "events discarded per closed drop reason (invalid, late, "
            "out_of_shard, oversample, exchange, handoff) — the "
            "conservation ledger's dropped{reason} term; an untagged "
            "drop path is a permanent audit residual (handoff is "
            "filter-state-only and rides outside the ledger)",
            labels=("reason",))
        for r in DROP_REASONS:
            self.dropped.labels(reason=r)
        # integrity-observatory ledger (obs.audit.AuditState), attached
        # by the runtime when HEATMAP_AUDIT=1; ``drop`` forwards every
        # tagged drop into it so the conservation identity closes
        self.audit = None
        # name -> histogram child, in observation order (snapshot() keys)
        self.spans: dict[str, object] = {}

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    def drop(self, reason: str, n: int = 1, audit: bool = True) -> None:
        """Account ``n`` discarded events under a CLOSED drop reason:
        bumps the reason-labeled family, the legacy flat counter, and
        (when attached, for the primary accounting stream only —
        ``audit=False`` keeps secondary-pair drops out of the event
        conservation identity) the audit ledger.  An unknown reason
        raises — the set stays closed by construction."""
        legacy = _DROP_LEGACY.get(reason)
        if legacy is None:
            raise ValueError(
                f"unknown drop reason {reason!r}; the closed set is "
                f"{DROP_REASONS}")
        if n <= 0:
            return
        self.counters[legacy] += n
        self.dropped.labels(reason=reason).inc(n)
        if audit and self.audit is not None:
            self.audit.add(f"dropped_{reason}", n)

    def gauge(self, name: str, help_: str = "", fn=None, labels=()):
        """Registry gauge pass-through for the layers this Metrics is
        threaded into (runtime state capacity, writer queue depth, …)."""
        return self.registry.gauge(name, help_, labels=labels, fn=fn)

    def observe_batch(self, latency_s: float,
                      spans: Mapping[str, float]) -> None:
        self.batch_latency.observe(latency_s)
        for k, v in spans.items():
            h = self.spans.get(k)
            if h is None:
                h = self.spans[k] = self._span_fam.labels(span=k)
            h.observe(v)
        # span=total rides in the span family too, so PER-STAGE vs
        # WHOLE-STEP comparisons (and the event-age-vs-step acceptance
        # check) stay within one labeled series
        t = self.spans.get("total")
        if t is None:
            t = self.spans["total"] = self._span_fam.labels(span="total")
        t.observe(latency_s)

    def freshness_summary(self) -> dict:
        """Event-age / ring-residency summary keys — what bench &
        e2e_rate stamp into their artifacts and the per-child xproc
        freshness files publish.  {} until the first flushed batch.
        The quantiles come from the histogram's bounded RECENT window
        (not lifetime buckets) — ``window_batches`` rides along so an
        artifact reader knows how much of the run the p50/p99 cover;
        the mean is lifetime (sum/count)."""
        out: dict = {}
        mean = self.event_age.labels(bound="mean")
        if mean.count:
            out["event_age_p50_s"] = round(mean.quantile(0.5), 6)
            out["event_age_p99_s"] = round(mean.quantile(0.99), 6)
            out["window_batches"] = len(mean.samples)
        if self.ring_residency.count:
            out["ring_residency_mean_s"] = round(
                self.ring_residency.sum / self.ring_residency.count, 6)
        return out

    def snapshot(self) -> dict:
        elapsed = max(time.monotonic() - self.t_start, 1e-9)
        out = dict(self.counters)
        out["uptime_s"] = round(elapsed, 3)
        out["events_per_sec"] = round(self.counters.get("events_valid", 0) / elapsed, 1)
        out["batch_latency_p50_ms"] = round(self.batch_latency.quantile(0.5) * 1e3, 3)
        out["batch_latency_p95_ms"] = round(self.batch_latency.quantile(0.95) * 1e3, 3)
        if self.freshness.samples:
            out["freshness_p50_s"] = round(self.freshness.quantile(0.5), 3)
            out["freshness_p95_s"] = round(self.freshness.quantile(0.95), 3)
        # list() snapshot: observe_batch (step thread) inserts new span
        # keys mid-run (conditional sub-spans like poll_wait appear on
        # first observation) while scrapes iterate from the HTTP thread
        for k, p in list(self.spans.items()):
            out[f"span_{k}_p50_ms"] = round(p.quantile(0.5) * 1e3, 3)
        out.update(self.freshness_summary())
        return out

    def expose_text(self, extra_counters: Mapping[str, float] | None = None,
                    extra_lines: Iterable[str] = ()) -> str:
        """Prometheus text exposition: the registry's typed series, then
        the ad-hoc counter dict (plus any caller-merged dicts — writer /
        source counters) as generically-typed series."""
        flat = dict(self.counters)
        elapsed = max(time.monotonic() - self.t_start, 1e-9)
        flat["uptime_s"] = round(elapsed, 3)
        flat["events_per_sec"] = round(
            self.counters.get("events_valid", 0) / elapsed, 1)
        if extra_counters:
            flat.update({k: v for k, v in extra_counters.items()
                         if isinstance(v, (int, float))})
        lines = render_flat_counters(flat, prefix="heatmap_",
                                     gauge_names=GAUGE_NAMES)
        lines.extend(extra_lines)
        return self.registry.expose_text(extra=lines)
