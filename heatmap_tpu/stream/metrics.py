"""Runtime metrics: the counters BASELINE.json measures (SURVEY.md §5.5).

events/sec in, rows upserted, p50/p95 micro-batch latency, plus per-span
timings (ingest / build / device / sink) so the bottleneck is visible.
Exposed by the serving layer at /metrics.
"""

from __future__ import annotations

import collections
import time
from typing import Mapping


class Percentiles:
    def __init__(self, window: int = 512):
        self.samples: collections.deque = collections.deque(maxlen=window)

    def add(self, v: float) -> None:
        self.samples.append(v)

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        i = min(len(s) - 1, int(q * len(s)))
        return s[i]


class Metrics:
    def __init__(self):
        self.t_start = time.monotonic()
        self.counters: collections.Counter = collections.Counter()
        self.batch_latency = Percentiles()
        self.freshness = Percentiles()  # emit wall time − newest event ts
        self.spans: dict[str, Percentiles] = collections.defaultdict(Percentiles)

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    def observe_batch(self, latency_s: float, spans: Mapping[str, float]) -> None:
        self.batch_latency.add(latency_s)
        for k, v in spans.items():
            self.spans[k].add(v)

    def snapshot(self) -> dict:
        elapsed = max(time.monotonic() - self.t_start, 1e-9)
        out = dict(self.counters)
        out["uptime_s"] = round(elapsed, 3)
        out["events_per_sec"] = round(self.counters.get("events_valid", 0) / elapsed, 1)
        out["batch_latency_p50_ms"] = round(self.batch_latency.quantile(0.5) * 1e3, 3)
        out["batch_latency_p95_ms"] = round(self.batch_latency.quantile(0.95) * 1e3, 3)
        if self.freshness.samples:
            out["freshness_p50_s"] = round(self.freshness.quantile(0.5), 3)
            out["freshness_p95_s"] = round(self.freshness.quantile(0.95), 3)
        for k, p in self.spans.items():
            out[f"span_{k}_p50_ms"] = round(p.quantile(0.5) * 1e3, 3)
        return out
