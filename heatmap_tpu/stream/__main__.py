"""Standalone streaming job: ``python -m heatmap_tpu.stream [pipeline]``.

The counterpart of the reference's ``spark-submit heatmap_stream.py``
(reference: heatmap_stream.py:241-249): consume the configured source,
aggregate on device, upsert the store, checkpoint, repeat until
interrupted.  ``pipeline`` is one of heatmap_tpu.models.pipelines (default
``mbta_default``); env config is the same flat set the reference reads.
"""

import argparse
import logging

# light imports only (pipelines/source/config carry no jax); everything
# that touches a device is imported inside main() AFTER the probe below
from heatmap_tpu.models.pipelines import PIPELINES, get_pipeline
from heatmap_tpu.sink import make_store


def install_flightrec_handlers(rt) -> None:
    """Flight-recorder wiring for a standalone streaming job (no-op when
    the runtime has no recorder armed — HEATMAP_FLIGHTREC_DIR unset).

    SIGTERM becomes a SystemExit raised in the main thread, so run()'s
    finally reaches rt.close(), which sees the unwinding exception and
    writes the flight record before the process dies (the supervisor's
    kill path and any orchestrator stop signal both land here).  The
    atexit hook is the backstop for exits that bypass close(); it is a
    no-op once close() dumped or disarmed the recorder."""
    rec = getattr(rt, "flightrec", None)
    if rec is None:
        return
    import atexit
    import signal

    def _on_term(signum, frame):  # noqa: ARG001
        raise SystemExit(143)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # not the main thread (embedded use)
        pass
    atexit.register(
        lambda: rec.dump("atexit: interpreter exit bypassed close()"))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pipeline", nargs="?", default="mbta_default",
                    choices=sorted(PIPELINES))
    ap.add_argument("--max-batches", type=int, default=None)
    ap.add_argument("--supervise", action="store_true",
                    help="run the job as a supervised child: restart on "
                         "crash AND on heartbeat stall (wedged device op),"
                         " resuming from the checkpoint; policy via "
                         "HEATMAP_SUPERVISE_* (stream/supervisor.py)")
    ap.add_argument("--shards", type=int, default=None,
                    help="with --supervise: fan out N H3-partitioned "
                         "runtime shard children (stream/shardmap.py), "
                         "each folding a disjoint cell space into the "
                         "shared store; defaults to HEATMAP_SHARDS (1)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    import os

    shards = (args.shards if args.shards is not None
              else int(os.environ.get("HEATMAP_SHARDS", "1") or 1))
    if args.shards is not None and args.shards > 1 and not args.supervise:
        # the flag means "fan out a fleet", which only the supervisor
        # does; a standalone single-shard run is instead configured via
        # HEATMAP_SHARDS + HEATMAP_SHARD_INDEX in the env (each
        # orchestrator-managed shard process does exactly that)
        raise SystemExit("--shards needs --supervise (the fleet "
                         "supervisor spawns one child per shard)")
    if args.shards == 1 and args.supervise:
        # an explicit --shards 1 must WIN over an inherited fleet env
        # (HEATMAP_SHARDS=4 exported from a prior fleet run): the
        # single-child Supervisor passes the env through unchanged, and
        # a child silently folding 1/4 of the stream as shard 0 of a
        # phantom fleet is exactly the footgun the flag exists to close
        os.environ["HEATMAP_SHARDS"] = "1"
        os.environ["HEATMAP_SHARD_INDEX"] = "0"
    if args.supervise:
        # the PARENT never probes (it runs no device op) and must not pin
        # HEATMAP_PLATFORM: each child probes per launch, so an
        # accelerator that comes back between restarts gets retried
        import sys

        from heatmap_tpu.stream.supervisor import supervise_cli

        child = [sys.executable, "-m", "heatmap_tpu.stream", args.pipeline]
        if args.max_batches is not None:
            child += ["--max-batches", str(args.max_batches)]
        raise SystemExit(supervise_cli(child, shards=shards))

    # with a dead accelerator relay, the first jax touch (module-level
    # engine constants behind the runtime import) hangs forever — the
    # probe pins CPU instead (skipped under HEATMAP_PLATFORM / multihost)
    from heatmap_tpu.utils.device_probe import ensure_reachable_backend

    ensure_reachable_backend()
    p = get_pipeline(args.pipeline)

    # distributed + multi-device setup: HEATMAP_COORDINATOR et al. start
    # the cross-host runtime (parallel.multihost); any multi-device
    # topology gets a sharded mesh
    import jax

    from heatmap_tpu.parallel import make_mesh, multihost
    from heatmap_tpu.stream import MicroBatchRuntime

    multihost.init_from_env()
    mesh = None
    n_shards = p.config.num_shards or len(jax.devices())
    if n_shards > 1 or jax.process_count() > 1:
        mesh = make_mesh(p.config.num_shards or None)

    store = make_store(p.config)
    src = p.make_source(p.config)
    rt = MicroBatchRuntime(p.config, src, store, mesh=mesh)
    install_flightrec_handlers(rt)
    log = logging.getLogger("stream")
    log.info("pipeline %s: %s", p.name, p.description)
    try:
        # run() checkpoints and closes the runtime in its own finally
        rt.run(max_batches=args.max_batches)
    except KeyboardInterrupt:
        log.info("interrupted; shutting down")
    finally:
        store.close()


if __name__ == "__main__":
    main()
