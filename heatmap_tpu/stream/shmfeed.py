"""Kafka ingest in a separate OS process over shared memory.

Replaces what the reference gets from Spark's executor/driver split
(reference: heatmap_stream.py:241-249 — the Kafka receiver runs in
executor JVMs while the driver schedules): here a FEEDER process owns
the wire fetch + columnar decode and hands finished `EventColumns`
batches to the runtime through a SharedMemory slot ring, so the
runtime's fold never shares a GIL (or an XLA-spinning core slice) with
socket reads and record decoding.

Round-5 motivation (PERF_E2E.md): inside the single-process runtime the
identical consume loop that standalone does ~70 ms per 262k batch
inflates ~10x — the fetch threads starve against the fold's device
dispatch in the same interpreter.  A second process gets its own GIL
and OS-scheduled core share; on a multi-core host the legs genuinely
overlap, and even on one core the OS time-slices far better than
Python's switch interval.

Protocol
--------
* a SharedMemory block holds `slots` fixed-capacity columnar slabs
  (8 f32/i32 lanes x `cap` rows, the EventColumns array fields);
* `full_q` carries (slot, n, gen, final, offsets, prov_delta,
  veh_delta, n_dropped) metas feeder -> runtime; `free_q` returns slot
  ids.  A poll that overshoots the slot capacity (the wire source
  consumes whole columnar records) spans MULTIPLE slots: only the last
  carries `final=True` and the post-poll offset, and the runtime side
  reassembles them into one logical batch — so a checkpointed offset
  can never advance past rows still sitting in the ring;
* provider/vehicle intern tables are synchronized by DELTA: the feeder
  sends only newly-interned names, both sides append in order, so the
  id arrays index identical tables;
* `seek` bumps a generation counter: the feeder flushes, re-seeks its
  KafkaSource, and stamps subsequent metas with the new generation —
  stale in-flight metas are discarded (slots recycled) on arrival.

The feeder child imports only the wire client + decode path (no jax —
a dead accelerator tunnel or a second backend init must never block
ingest).
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import queue as queue_mod
import time
from multiprocessing import shared_memory
from typing import Any

import numpy as np

from heatmap_tpu.stream.events import EventColumns, empty_columns
from heatmap_tpu.stream.source import Source

log = logging.getLogger(__name__)

# lane name -> dtype; fixed order defines the shm layout
_LANES = (
    ("lat_rad", np.float32), ("lng_rad", np.float32),
    ("lat_deg", np.float32), ("lng_deg", np.float32),
    ("speed_kmh", np.float32), ("ts_s", np.int32),
    ("provider_id", np.int32), ("vehicle_id", np.int32),
)
_IDLE_SLEEP_S = 0.01


def _slot_views(buf, slots: int, cap: int):
    """Per-slot dict of lane views into the shared buffer."""
    out = []
    lane_bytes = cap * 4
    slot_bytes = lane_bytes * len(_LANES)
    for s in range(slots):
        views = {}
        off = s * slot_bytes
        for name, dt in _LANES:
            views[name] = np.frombuffer(buf, dtype=dt, count=cap,
                                        offset=off)
            off += lane_bytes
        out.append(views)
    return out


def _feeder_main(shm_name: str, slots: int, cap: int, bootstrap: str,
                 topic: str, full_q, free_q, cmd_q, ready_evt,
                 env: dict) -> None:
    """Child entry: attach the shm, run the loop in its own frame (so
    every numpy view into the mmap is freed before close), detach."""
    os.environ.update(env)
    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        _feeder_loop(shm, slots, cap, bootstrap, topic, full_q, free_q,
                     cmd_q, ready_evt)
    finally:
        shm.close()


def _feeder_loop(shm, slots: int, cap: int, bootstrap: str, topic: str,
                 full_q, free_q, cmd_q, ready_evt) -> None:
    from heatmap_tpu.stream.source import KafkaSource

    src = KafkaSource(bootstrap, topic)
    # the consumer is ATTACHED (offsets pinned at latest) only now —
    # producers waiting to publish a bounded replay can go ahead
    ready_evt.set()
    try:
        views = _slot_views(shm.buf, slots, cap)
        gen = 0
        sent_p = sent_v = 0
        providers: list = []
        vehicles: list = []
        while True:
            # commands take priority (seek must not race new fills)
            try:
                cmd = cmd_q.get_nowait()
            except queue_mod.Empty:
                cmd = None
            if cmd is not None:
                if cmd[0] == "stop":
                    break
                if cmd[0] == "seek":
                    _g, off = cmd[1], cmd[2]
                    src.seek(off)
                    gen = _g
                    continue
            try:
                slot = free_q.get(timeout=0.25)
            except queue_mod.Empty:
                continue
            cols = src.poll(cap)
            n = len(cols) if cols is not None else 0
            if n == 0:
                free_q.put(slot)
                # an EMPTY meta keeps the runtime's poll from blocking a
                # full timeout when the topic is simply drained — but
                # only when none is pending, or a slow-polling runtime
                # accumulates stale metas without bound (r5 review)
                if full_q.empty():
                    full_q.put((None, 0, gen, True, src.offset(), [],
                                [], 0))
                time.sleep(_IDLE_SLEEP_S)
                continue
            # intern-table deltas: cols carries the source's GLOBAL
            # tables; send only what the runtime has not seen
            providers, vehicles = cols.providers, cols.vehicles
            pd = providers[sent_p:]
            vd = vehicles[sent_v:]
            sent_p, sent_v = len(providers), len(vehicles)
            off = src.offset()
            # the wire source consumes whole records and may overshoot
            # cap: span slots, final flag + offset on the LAST slice
            start = 0
            while start < n:
                if start > 0:
                    slot = free_q.get()  # blocking: the batch must land
                take = min(cap, n - start)
                v = views[slot]
                for name, _dt in _LANES:
                    v[name][:take] = getattr(cols, name)[start:start + take]
                final = start + take >= n
                full_q.put((slot, take, gen, final, off,
                            pd if final else [], vd if final else [],
                            cols.n_dropped if final else 0))
                start += take
    finally:
        src.close()


class ShmFeederSource(Source):
    """A `KafkaSource` running in its own OS process, delivering decoded
    columnar batches through shared memory (see module docstring)."""

    def __init__(self, bootstrap: str, topic: str, batch_size: int,
                 slots: int = 4):
        self.cap = int(batch_size)
        self.slots = int(slots)
        nbytes = self.slots * self.cap * 4 * len(_LANES)
        self._shm = shared_memory.SharedMemory(create=True, size=nbytes)
        self._views = _slot_views(self._shm.buf, self.slots, self.cap)
        ctx = mp.get_context("spawn")
        self._full_q = ctx.Queue()
        self._free_q = ctx.Queue()
        self._cmd_q = ctx.Queue()
        for s in range(self.slots):
            self._free_q.put(s)
        # the child must come up on the CPU decode path no matter what
        # the parent's accelerator situation is
        env = {k: v for k, v in os.environ.items()
               if k.startswith(("HEATMAP_", "KAFKA_"))}
        env.setdefault("HEATMAP_PLATFORM", "cpu")
        env["JAX_PLATFORMS"] = "cpu"  # belt and braces: no device init
        self._ready = ctx.Event()
        self._proc = ctx.Process(
            target=_feeder_main,
            args=(self._shm.name, self.slots, self.cap, bootstrap, topic,
                  self._full_q, self._free_q, self._cmd_q, self._ready,
                  env),
            daemon=True)
        self._proc.start()
        # interpreter startup in the child is seconds on this host; the
        # construction contract matches KafkaSource's (consumer attached,
        # offsets pinned at latest, before __init__ returns).  Watch
        # child liveness too: a broker that died between the caller's
        # probe and the child's attach makes the child EXIT, and waiting
        # the full budget for a dead process would stall pipeline
        # startup ~2 minutes before the synthetic fallback engages
        deadline = time.monotonic() + 120
        while not self._ready.wait(timeout=0.25):
            if not self._proc.is_alive():
                self.close()
                raise RuntimeError(
                    "shm feeder process exited before attaching to the "
                    "broker (unreachable or incompatible)")
            if time.monotonic() >= deadline:
                self.close()
                raise RuntimeError("shm feeder process failed to attach "
                                   "to the broker")
        self._gen = 0
        self._offset: Any = None
        self._providers: list[str] = []
        self._vehicles: list[str] = []
        self.n_dropped_total = 0
        # poll sub-spans (Source.take_spans): wall spent WAITING on the
        # feeder process (full_q) vs copying slot lanes out of the shm
        # ring — a big "wait" means the feeder can't keep up (or shares
        # the core), a big "decode" means the slot memcpy itself costs
        self._spans = {"wait": 0.0, "decode": 0.0}

    def take_spans(self):
        out = {k: v for k, v in self._spans.items() if v > 0.0}
        self._spans = {"wait": 0.0, "decode": 0.0}
        return out

    # ------------------------------------------------------------- source
    def poll(self, max_events: int):
        """Like KafkaSource's columnar behavior, a poll may return MORE
        than ``max_events``: the feeder consumes whole records, and an
        oversize poll arrives as a multi-slot spanning batch reassembled
        here (offset stamped only on the final slice).  The runtime
        absorbs oversize returns through its carry path and defers
        checkpoints mid-carry, so offsets never advance past
        undelivered rows."""
        deadline = time.monotonic() + 1.0
        parts: list[dict] = []
        while True:
            timeout = max(0.05, deadline - time.monotonic())
            t_wait = time.monotonic()
            try:
                (slot, n, gen, final, off, pd, vd,
                 dropped) = self._full_q.get(timeout=timeout)
                self._spans["wait"] += time.monotonic() - t_wait
            except queue_mod.Empty:
                self._spans["wait"] += time.monotonic() - t_wait
                if parts:  # mid-assembly: the final slice is coming
                    deadline = time.monotonic() + 1.0
                    continue
                return empty_columns(self._providers, self._vehicles)
            # intern deltas are generation-INDEPENDENT (append-only, and
            # the feeder never resends them): a stale post-seek meta must
            # still contribute its names or later ids point past the
            # runtime-side tables (r5 review finding)
            self._providers.extend(pd)
            self._vehicles.extend(vd)
            if gen != self._gen:
                if slot is not None:
                    self._free_q.put(slot)  # pre-seek leftover
                parts = []  # any assembly in flight was pre-seek too
                continue
            if slot is None:
                if parts:
                    continue  # stray empty meta between slices
                self._offset = off
                return empty_columns(self._providers, self._vehicles)
            t_copy = time.monotonic()
            v = self._views[slot]
            parts.append({name: v[name][:n].copy()
                          for name, _dt in _LANES})
            self._free_q.put(slot)
            self._spans["decode"] += time.monotonic() - t_copy
            if not final:
                continue
            self._offset = off
            self.n_dropped_total += dropped
            t_copy = time.monotonic()
            if len(parts) == 1:
                lanes = parts[0]
            else:
                lanes = {name: np.concatenate([p[name] for p in parts])
                         for name, _dt in _LANES}
            self._spans["decode"] += time.monotonic() - t_copy
            return EventColumns(**lanes, providers=self._providers,
                                vehicles=self._vehicles,
                                n_dropped=dropped)

    def offset(self):
        return self._offset

    def seek(self, offset) -> None:
        self._gen += 1
        self._cmd_q.put(("seek", self._gen, offset))
        self._offset = offset

    def close(self) -> None:
        if self._proc.is_alive():
            self._cmd_q.put(("stop",))
            self._proc.join(timeout=5)
            if self._proc.is_alive():  # wedged on a dead broker socket
                self._proc.terminate()
                self._proc.join(timeout=5)
        self._views = None  # release exported pointers into the mmap
        try:
            self._shm.close()
            self._shm.unlink()
        except FileNotFoundError:  # pragma: no cover - already unlinked
            pass
