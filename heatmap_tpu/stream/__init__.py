"""stream — the micro-batch runtime (replaces Spark Structured Streaming).

The reference delegates micro-batch scheduling, offset/state checkpointing
and watermark bookkeeping to the Spark JVM (reference:
heatmap_stream.py:41-48,79-86,241-249).  This package owns all of it
in-framework:

- ``events``      — the canonical 8-field GPS event schema + columnar
                    parsing/validation (reference schema:
                    heatmap_stream.py:52-61, filters :96-108).
- ``source``      — pluggable pull sources with replayable offsets:
                    in-memory, JSONL replay, synthetic generator, Kafka
                    (gated on a client lib being installed).
- ``runtime``     — the driver loop: poll → fixed-shape batch → device
                    aggregation step(s) → async sink upserts → watermark →
                    checkpoint commit.
- ``checkpoint``  — offsets + device-state snapshots, atomic on disk
                    (replaces the Spark checkpointLocation contract,
                    heatmap_stream.py:37,244).
- ``metrics``     — the counters/latency spans BASELINE.json measures.
"""

from heatmap_tpu.stream.events import EventColumns, parse_events  # noqa: F401
from heatmap_tpu.stream.source import (  # noqa: F401
    JsonlReplaySource,
    MemorySource,
    RampSource,
    Source,
    SyntheticSource,
)

# The runtime (and engine behind it) touch jax at import; resolving them
# lazily keeps `import heatmap_tpu.stream` — and crucially the package
# import that `python -m heatmap_tpu.stream` performs BEFORE __main__'s
# device probe can run — free of device init, so a dead accelerator
# relay can't hang the CLI before its CPU-fallback logic exists.
_LAZY = {"MicroBatchRuntime", "StateOverflowError"}


def __getattr__(name):  # PEP 562
    if name in _LAZY:
        from heatmap_tpu.stream import runtime

        return getattr(runtime, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
