"""stream — the micro-batch runtime (replaces Spark Structured Streaming).

The reference delegates micro-batch scheduling, offset/state checkpointing
and watermark bookkeeping to the Spark JVM (reference:
heatmap_stream.py:41-48,79-86,241-249).  This package owns all of it
in-framework:

- ``events``      — the canonical 8-field GPS event schema + columnar
                    parsing/validation (reference schema:
                    heatmap_stream.py:52-61, filters :96-108).
- ``source``      — pluggable pull sources with replayable offsets:
                    in-memory, JSONL replay, synthetic generator, Kafka
                    (gated on a client lib being installed).
- ``runtime``     — the driver loop: poll → fixed-shape batch → device
                    aggregation step(s) → async sink upserts → watermark →
                    checkpoint commit.
- ``checkpoint``  — offsets + device-state snapshots, atomic on disk
                    (replaces the Spark checkpointLocation contract,
                    heatmap_stream.py:37,244).
- ``metrics``     — the counters/latency spans BASELINE.json measures.
"""

from heatmap_tpu.stream.events import EventColumns, parse_events  # noqa: F401
from heatmap_tpu.stream.source import (  # noqa: F401
    JsonlReplaySource,
    MemorySource,
    Source,
    SyntheticSource,
)
from heatmap_tpu.stream.runtime import (  # noqa: F401
    MicroBatchRuntime,
    StateOverflowError,
)
