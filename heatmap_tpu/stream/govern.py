"""Adaptive micro-batching: a feedback governor for the step loop.

``BATCH_SIZE``, ``HEATMAP_EMIT_FLUSH_K`` and ``HEATMAP_PREFETCH_BATCHES``
are static env knobs, but a stream system tuned for one offered load is
wrong at every other load (LMStream's GPU micro-batch sizing, GeoFlink's
load-aware partitioning — PAPERS.md).  The PR 3/5 telemetry already
measures everything a controller needs: the conservation-exact event-age
lineage (the freshness quantity ``HEATMAP_SLO_FRESHNESS_P50_MS`` budgets),
emit-ring residency, post-warmup retrace detection, and device-memory
watermarks.  ``BatchGovernor`` closes that loop: with ``HEATMAP_GOVERN=1``
the static knobs become *initial* values and the governor resizes all
three within guardrails, every ``HEATMAP_GOVERN_INTERVAL_S``.

Control law (AIMD along a bucket ladder; one move per interval):

- **breach** (recent event-age p50 over the SLO):
  - feed **saturated** (dispatch fill >= 90%): the system is
    throughput-bound — step the batch bucket UP, raise prefetch
    (``reason="saturated"``).  Shrinking here would run away in the
    wrong direction.
  - otherwise the staleness is hold/padding-bound — multiplicative
    back-off toward latency: halve flush-K; once flush-K is already 1
    and the fill is low, step the batch bucket DOWN
    (``reason="latency"``).
- **healthy** (p50 under ``HEATMAP_GOVERN_HEALTHY_FRAC`` x SLO):
  - feed **starved** (idle polls — engine idle, queue empty): additive
    recovery
    toward throughput — one bucket up, flush-K/prefetch back toward
    their configured initial values (``reason="starved"``).  Idle polls
    force an emit-ring flush, so latency is safe while starved.
  - feed **full** with headroom: one bucket up, flush-K/prefetch +1 up
    to the hard bounds (``reason="headroom"``).
- in between: hold.

Hard guardrails, both pinned by tests:

1. **No retrace storms.**  Batch sizes move only along a PRECOMPILED
   bucket ladder — power-of-two pad buckets warmed at startup by
   dispatching all-invalid batches through the instrumented step
   (identity on the empty state, so warmup can never perturb results).
   A post-warmup retrace observed by the PR 5 ``CompileTracker`` (e.g.
   a slab-growth resize invalidating every warmed shape) immediately
   FREEZES the governor at its current values and latches the offending
   bucket out of the ladder; ``/healthz`` degrades naming it.
2. **Memory.**  With ``HEATMAP_SLO_MEM_BYTES`` set, a watermark over
   budget blocks all growth and steps prefetch/bucket down
   (``reason="mem"``); the EmitRing growth-pressure flush path can
   force a flush-K step-down (``reason="growth_pressure"``).

Differential safety net (PR 2/7 discipline): a governed run over a
fixed corpus produces byte-identical merged emits to an ungoverned run —
knob changes may re-partition batching, never results
(tests/test_govern.py).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

# fill-ratio threshold of the control law (rows dispatched per bucket
# slot over the interval): >= SAT_FILL reads as throughput-bound.
# Starvation is the literal "engine idle, queue empty" signal — idle
# polls (which force ring flushes, so latency is safe while starved).
SAT_FILL = 0.9


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        log.warning("%s=%r is not a number; using %s", name,
                    os.environ.get(name), default)
        return float(default)


def bucket_ladder(batch_size: int, min_batch: int) -> list:
    """The precompiled pad-bucket ladder: every power of two in
    [min_batch, batch_size), plus ``batch_size`` itself as the top
    bucket (the configured static shape, whether or not it is a power
    of two).  Ascending; always non-empty (a min at/above the batch
    size degenerates to the single static bucket)."""
    batch_size = int(batch_size)
    min_batch = max(64, int(min_batch))
    if min_batch >= batch_size:
        return [batch_size]
    sizes = []
    b = 1 << (min_batch - 1).bit_length()  # min rounded up to a pow2
    while b < batch_size:
        sizes.append(b)
        b <<= 1
    sizes.append(batch_size)
    return sizes


class BatchGovernor:
    """Resizes the live batch bucket / flush-K / prefetch depth to hold
    the freshness SLO.  Owned by the step loop: ``decide()`` runs the
    (rate-limited) control step; the runtime applies the decision
    properties at the next step boundary.  All mutation happens under
    one lock so /metrics scrapes and the step loop never tear a
    decision."""

    def __init__(self, cfg, registry, *, event_age=None,
                 compile_tracker=None, memory=None, clock=time.monotonic,
                 shard=None):
        self.cfg = cfg
        self.clock = clock
        # ``shard``: mesh-shard index for the partitioned mesh fast path
        # (stream/runtime.py runs one governor PER mesh device, so
        # skewed devices converge to different batch buckets).  The
        # metric families then carry a shard= label; None keeps the
        # historical unlabeled single-governor exposition.  All mesh
        # governors share ONE CompileTracker, so the retrace-freeze
        # guardrail latches per-LADDER: a post-warmup retrace anywhere
        # on the mesh freezes every shard's governor (the warmed-shape
        # invariant is a property of the shared ladder, not of the
        # shard that happened to trip it).
        self.shard = None if shard is None else int(shard)
        self.interval_s = float(cfg.govern_interval_s)
        self._age = event_age          # histogram child (bound="mean")
        self._tracker = compile_tracker
        self._memory = memory
        self._lock = threading.Lock()
        self.ladder = bucket_ladder(cfg.batch_size, cfg.govern_min_batch)
        # decisions: static knobs are the INITIAL values, clamped into
        # the governor's bounds
        self._idx = len(self.ladder) - 1          # start at the top
        # ceilings never override the operator's INITIAL values: a
        # configured emit_flush_k/prefetch above the growth ceiling
        # raises the ceiling rather than being silently clamped down
        # on enable (the static knobs BECOME the initial values)
        self.flush_k_min = 1
        self.flush_k_max = max(int(cfg.govern_max_flush_k),
                               int(cfg.emit_flush_k))
        self.prefetch_min = 0
        self.prefetch_max = max(int(cfg.govern_max_prefetch),
                                int(cfg.prefetch_batches))
        self._flush_k = max(cfg.emit_flush_k, self.flush_k_min)
        self._prefetch = max(cfg.prefetch_batches, self.prefetch_min)
        # recovery targets: "toward throughput" recovers to the
        # operator's configured values, not the hard ceiling
        self._flush_k_initial = self._flush_k
        self._prefetch_initial = self._prefetch
        self.frozen = False
        self.frozen_why = ""
        self.latched_bucket: int | None = None
        self._pinned_batch: int | None = None
        self._last_decide = self.clock()
        self._last_adjust: float | None = None
        # interval accounting (note_* feed these from the step loop)
        self._rows = 0
        self._dispatches = 0
        self._idles = 0
        self._growth_pressure = False
        self._age_count_last = (self._age.count
                                if self._age is not None else 0)
        self._retrace_base = (self._retraces()
                              if self._tracker is not None else 0)
        self.trail: collections.deque = collections.deque(maxlen=256)
        # ---- enforced metric families (ARCHITECTURE.md §Adaptive
        # micro-batching).  With a mesh shard index the same family
        # names carry a shard= label (one child per device governor);
        # the fleet aggregator re-labels either shape with proc=.
        labelnames = () if self.shard is None else ("shard",)

        def _child(fam):
            return (fam if self.shard is None
                    else fam.labels(shard=str(self.shard)))

        self._g_batch = _child(registry.gauge(
            "heatmap_govern_batch_rows",
            "live feed-batch pad bucket the governor currently targets "
            "(rows; moves only along the precompiled bucket ladder)",
            labels=labelnames))
        self._g_flush = _child(registry.gauge(
            "heatmap_govern_flush_k",
            "live emit-ring flush interval the governor currently "
            "targets (batches per pull)", labels=labelnames))
        self._g_prefetch = _child(registry.gauge(
            "heatmap_govern_prefetch",
            "live prefetch depth the governor currently targets "
            "(batches polled ahead of the fold)", labels=labelnames))
        self._g_frozen = _child(registry.gauge(
            "heatmap_govern_frozen",
            "1 when the governor is frozen (post-warmup retrace "
            "guardrail latched a bucket out of the ladder); knobs stay "
            "at their last values", labels=labelnames))
        self._adjust_fam = registry.counter(
            "heatmap_govern_adjust_total",
            "governor knob adjustments by direction (up/down/set/"
            "freeze) and control-law reason (latency/saturated/"
            "starved/headroom/mem/growth_pressure/forced/retrace)",
            labels=("dir", "reason") + labelnames)
        age = _child(registry.gauge(
            "heatmap_govern_last_adjust_age_seconds",
            "seconds since the governor last changed any knob (NaN "
            "before the first adjustment)",
            labels=labelnames,
            fn=self._last_adjust_age if self.shard is None else None))
        if self.shard is not None:
            # labeled children share the family's make_child, so the
            # callback must be attached per child, not per family
            age.fn = self._last_adjust_age
        self._publish()

    def _adjust_inc(self, direction: str, reason: str) -> None:
        kw = {"dir": direction, "reason": reason}
        if self.shard is not None:
            kw["shard"] = str(self.shard)
        self._adjust_fam.labels(**kw).inc()

    # ------------------------------------------------------------ reads
    @property
    def batch_rows(self) -> int:
        # frozen pins the LIVE value even though the latched bucket
        # left the ladder: the current shape just (re)compiled, so
        # staying put is the only move that cannot retrace again
        if self.frozen and self._pinned_batch is not None:
            return self._pinned_batch
        return self.ladder[self._idx]

    @property
    def flush_k(self) -> int:
        return self._flush_k

    @property
    def prefetch(self) -> int:
        return self._prefetch

    def _last_adjust_age(self) -> float:
        t = self._last_adjust
        return float("nan") if t is None else max(0.0, self.clock() - t)

    def snapshot(self) -> dict:
        """Decision state for artifacts / flight records / the fleet
        member snapshot (the gauges carry the same values at /metrics)."""
        with self._lock:
            return {
                "batch_rows": self.batch_rows,
                "flush_k": self._flush_k,
                "prefetch": self._prefetch,
                "ladder": list(self.ladder),
                "frozen": self.frozen,
                "frozen_why": self.frozen_why,
                "latched_bucket": self.latched_bucket,
                "adjustments": len(self.trail),
            }

    def bounds(self) -> dict:
        """The guardrail bounds, for artifact provenance stamps."""
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "min_batch": self.ladder[0],
            "max_batch": self.ladder[-1],
            "flush_k_max": self.flush_k_max,
            "prefetch_max": self.prefetch_max,
        }

    # ---------------------------------------------------- step-loop feed
    def note_dispatch(self, n_rows: int) -> None:
        """One batch dispatched with ``n_rows`` live rows (fill
        accounting for the saturated/starved classification)."""
        self._rows += int(n_rows)
        self._dispatches += 1

    def note_idle(self) -> None:
        """One idle poll (source empty) — the starvation signal."""
        self._idles += 1

    def note_growth_pressure(self) -> None:
        """The step loop flushed the ring under state-growth pressure:
        parked batches were holding unaccounted minting against the
        slab — the next control step backs flush-K off one halving."""
        self._growth_pressure = True

    # ------------------------------------------------------------ control
    def _retraces(self) -> int:
        n = getattr(self._tracker, "retraces_total", None)
        if n is not None:  # the cheap per-step accessor (CompileTracker)
            return int(n)
        snap = self._tracker.snapshot()
        return int(snap.get("retraces_after_warmup", 0))

    def freeze(self, why: str, bucket: int | None = None) -> None:
        """Latch the governor: knobs stay at their current values, the
        offending bucket leaves the ladder, /healthz degrades naming it
        (serve.api.healthz_payload)."""
        with self._lock:
            if self.frozen:
                return
            self.frozen = True
            self.frozen_why = why
            # pin the LIVE batch value first: the freeze must not move
            # the shape (the current one just recompiled and is the
            # only warm shape left — stepping off it would retrace
            # AGAIN, observed in the live drive), even though the
            # latched bucket leaves the ladder
            self._pinned_batch = self.batch_rows
            self.latched_bucket = (self.batch_rows
                                   if bucket is None else int(bucket))
            if len(self.ladder) > 1 and self.latched_bucket in self.ladder:
                at = self.ladder.index(self.latched_bucket)
                self.ladder.pop(at)
                if self._idx >= at:
                    self._idx = max(0, self._idx - 1)
            self.trail.append({"t": self.clock(), "dir": "freeze",
                               "reason": why,
                               "bucket": self.latched_bucket})
            self._adjust_inc("freeze", "retrace")
            self._publish()
        log.warning("governor FROZEN (%s); bucket %s latched out of the "
                    "ladder, knobs pinned at batch=%d flush_k=%d "
                    "prefetch=%d", why, self.latched_bucket,
                    self.batch_rows, self._flush_k, self._prefetch)

    def check_retrace(self) -> bool:
        """The retrace guardrail, checked on the step loop (cheap: one
        locked deque read).  True when it froze the governor."""
        if self.frozen or self._tracker is None:
            return self.frozen
        if self._retraces() > self._retrace_base:
            self.freeze("post-warmup retrace detected "
                        "(CompileTracker)")
            return True
        return False

    def decide(self, now: float | None = None) -> bool:
        """One rate-limited control step; True when any knob changed.
        Runs on the step thread (the runtime applies the new decisions
        at the same step boundary)."""
        now = self.clock() if now is None else now
        if now - self._last_decide < self.interval_s:
            return False
        if self.check_retrace():
            self._last_decide = now
            return False
        with self._lock:
            self._last_decide = now
            rows, self._rows = self._rows, 0
            disp, self._dispatches = self._dispatches, 0
            idles, self._idles = self._idles, 0
            pressure, self._growth_pressure = self._growth_pressure, False
            # the interval's OWN event-age p50: only the samples that
            # landed since the last control step (the histogram's
            # 512-sample recent window spans far more than one interval,
            # and a quantile over it would see a load swing minutes
            # late).  Copy under the histogram lock — the writer thread
            # appends concurrently.
            window: list = []
            if self._age is not None:
                with self._age._lock:
                    age_n = self._age.count
                    new = min(max(0, age_n - self._age_count_last),
                              len(self._age.samples))
                    if new:
                        window = list(self._age.samples)[-new:]
                self._age_count_last = age_n
            p50_ms = None
            if window:
                window.sort()
                p50_ms = window[len(window) // 2] * 1e3
            fresh = bool(window)
            slo_ms = _env_float("HEATMAP_SLO_FRESHNESS_P50_MS", 10000.0)
            fill = (rows / (disp * self.batch_rows)) if disp else 0.0
            starved = idles > 0 or disp == 0

            before = (self._idx, self._flush_k, self._prefetch)
            mem_over = False
            if self._memory is not None:
                budget = _env_float("HEATMAP_SLO_MEM_BYTES", 0.0)
                mem_over = (budget > 0
                            and self._memory.watermark_bytes > budget)
            if mem_over:
                # memory guardrail outranks the SLO: cap prefetch x batch
                # growth and actively step both down
                self._prefetch = self.prefetch_min
                self._idx = max(0, self._idx - 1)
                reason, direction = "mem", "down"
            elif pressure:
                # the ring's growth-pressure flush already fired; hold
                # fewer batches so occupancy stats stay fresh
                self._flush_k = max(self.flush_k_min, self._flush_k // 2)
                reason, direction = "growth_pressure", "down"
            elif not fresh or p50_ms is None:
                reason, direction = "hold", None    # nothing measured
            elif p50_ms > slo_ms:
                if fill >= SAT_FILL and not starved:
                    # throughput-bound: shrinking would run away —
                    # grow capacity instead
                    self._idx = min(len(self.ladder) - 1, self._idx + 1)
                    self._prefetch = min(self.prefetch_max,
                                         self._prefetch + 1)
                    reason, direction = "saturated", "up"
                else:
                    # hold/padding staleness: multiplicative back-off
                    # toward latency — flush-K first (the ring hold is
                    # the dominant term), the bucket only once flush-K
                    # is exhausted and the fill says padding waste
                    if self._flush_k > self.flush_k_min:
                        self._flush_k = max(self.flush_k_min,
                                            self._flush_k // 2)
                    elif disp > 0 and fill < 0.5:
                        # bucket moves need fill EVIDENCE: an interval
                        # with zero dispatches (acks of earlier batches
                        # only) says nothing about padding waste
                        self._idx = max(0, self._idx - 1)
                        self._prefetch = max(self.prefetch_min,
                                             self._prefetch - 1)
                    reason, direction = "latency", "down"
            elif p50_ms < self.cfg.govern_healthy_frac * slo_ms:
                if starved:
                    # engine idle / queue empty: additive recovery
                    # toward throughput (idle polls force ring flushes,
                    # so growing costs no staleness while starved);
                    # flush-K/prefetch recover only to their configured
                    # initial values
                    self._idx = min(len(self.ladder) - 1, self._idx + 1)
                    self._flush_k = min(max(self._flush_k_initial,
                                            self.flush_k_min),
                                        self._flush_k + 1)
                    self._prefetch = min(self._prefetch_initial,
                                         self._prefetch + 1)
                    reason, direction = "starved", "up"
                elif fill >= SAT_FILL:
                    # full feed with SLO headroom: one additive step up
                    self._idx = min(len(self.ladder) - 1, self._idx + 1)
                    self._flush_k = min(self.flush_k_max,
                                        self._flush_k + 1)
                    self._prefetch = min(self.prefetch_max,
                                         self._prefetch + 1)
                    reason, direction = "headroom", "up"
                else:
                    reason, direction = "hold", None
            else:
                reason, direction = "hold", None

            changed = (self._idx, self._flush_k,
                       self._prefetch) != before
            if changed:
                self._last_adjust = now
                self.trail.append({
                    "t": now, "dir": direction, "reason": reason,
                    "batch_rows": self.batch_rows,
                    "flush_k": self._flush_k,
                    "prefetch": self._prefetch,
                    "p50_ms": (round(p50_ms, 3)
                               if p50_ms is not None else None),
                    "fill": round(fill, 4), "idles": idles,
                })
                self._adjust_inc(direction or "hold", reason)
                self._publish()
            return changed

    def force(self, batch_rows: int | None = None,
              flush_k: int | None = None, prefetch: int | None = None,
              reason: str = "forced") -> None:
        """Pin decisions directly (tests / operator tooling).  Batch
        values must be ladder buckets — the no-retrace guarantee only
        covers warmed shapes."""
        with self._lock:
            if batch_rows is not None:
                if batch_rows not in self.ladder:
                    raise ValueError(
                        f"{batch_rows} is not a ladder bucket "
                        f"{self.ladder}")
                self._idx = self.ladder.index(batch_rows)
            if flush_k is not None:
                self._flush_k = min(max(int(flush_k), self.flush_k_min),
                                    self.flush_k_max)
            if prefetch is not None:
                self._prefetch = min(max(int(prefetch),
                                         self.prefetch_min),
                                     self.prefetch_max)
            self._last_adjust = self.clock()
            self.trail.append({"t": self._last_adjust, "dir": "set",
                               "reason": reason,
                               "batch_rows": self.batch_rows,
                               "flush_k": self._flush_k,
                               "prefetch": self._prefetch})
            self._adjust_inc("set", reason)
            self._publish()

    def _publish(self) -> None:
        self._g_batch.set(self.batch_rows)
        self._g_flush.set(self._flush_k)
        self._g_prefetch.set(self._prefetch)
        self._g_frozen.set(1.0 if self.frozen else 0.0)
