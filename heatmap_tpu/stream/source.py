"""Pull sources with replayable offsets.

The reference's only source is the Kafka connector with Spark-managed
offsets (reference: heatmap_stream.py:79-86; README.md:131-133).  The Source
protocol here generalizes that: ``poll`` returns up to ``max_events`` events
past the current position, ``offset``/``seek`` expose a serializable
position for the checkpoint (resume = seek + idempotent replay,
SURVEY.md §5.4).
"""

from __future__ import annotations

import abc
import collections
import json
import math
import os
import time as _time
from typing import Any, Iterable, Sequence

import numpy as np

from heatmap_tpu.stream.events import (
    EventColumns, columns_from_arrays, parse_events,
)


class Source(abc.ABC):
    @abc.abstractmethod
    def poll(self, max_events: int) -> Sequence[dict] | EventColumns:
        """Up to max_events events at the current position (may be empty)."""

    def offset(self) -> Any:
        """JSON-serializable replay position."""
        return None

    def seek(self, offset: Any) -> None:
        pass

    @property
    def exhausted(self) -> bool:
        """True when no more data will ever arrive (bounded replays)."""
        return False

    @property
    def counters(self) -> dict:
        """Transport-health counters (fetch errors, timeouts, offset
        resets) merged into /metrics by the serving layer; sources with
        no transport report nothing."""
        return {}

    def take_spans(self) -> dict:
        """Sub-span seconds accumulated inside poll() since the last
        call — e.g. {"fetch": ..., "decode": ...} — and reset.  The
        runtime folds them into the per-batch span histograms
        (heatmap_batch_span_seconds{span="poll_fetch"|...}) so a feed
        wall is diagnosable from /metrics: wire-fetch-bound vs
        decode-bound vs feeder-wait-bound.  Sources with no meaningful
        split report nothing."""
        return {}

    def close(self) -> None:
        pass


def _decode_raw_values(dec, values: list[bytes], intern_p: dict,
                       intern_v: dict, fmt: str = "json"):
    """Raw event value byte-strings -> EventColumns, via the C++ decoder
    when available, else the Python codecs.  Both paths drop the same
    documents AND count them in n_dropped, so the events_invalid metric
    does not depend on whether a toolchain exists."""
    if not values:
        return []
    if fmt == "binary":
        from heatmap_tpu.stream import binfmt

        if dec is not None:
            cols, _ = dec.decode_binary(binfmt.frame_lp(values))
            return cols
        dicts, dropped = binfmt.decode_events(values)
        cols = parse_events(dicts, intern_p, intern_v)
        cols.n_dropped += dropped
        return cols
    if dec is not None:
        from heatmap_tpu.native import decode_lines

        return decode_lines(dec, values)
    out = []
    malformed = 0
    for v in values:
        try:
            out.append(json.loads(v))
        except (json.JSONDecodeError, UnicodeDecodeError):
            malformed += 1  # -> dropped (ref: filters)
    cols = parse_events(out, intern_p, intern_v)
    cols.n_dropped += malformed
    return cols


class MemorySource(Source):
    """Deque-fed source for hermetic tests (SURVEY.md §4(c))."""

    def __init__(self, events: Iterable[dict] = ()):
        self._q: collections.deque = collections.deque(events)
        self._consumed = 0
        self._done = False

    def push(self, events: Iterable[dict]) -> None:
        self._q.extend(events)

    def finish(self) -> None:
        self._done = True

    def poll(self, max_events: int):
        out = []
        while self._q and len(out) < max_events:
            out.append(self._q.popleft())
        self._consumed += len(out)
        return out

    def offset(self):
        return self._consumed

    def seek(self, offset) -> None:
        """Fast-forward to a committed offset (checkpoint resume over a
        freshly re-fed deque).  The deque is consume-once, so rewinding
        below the consumed position is impossible — refuse loudly
        rather than silently replaying rows a resume already covered."""
        target = int(offset or 0)
        if target < self._consumed:
            raise ValueError(
                f"MemorySource cannot rewind: consumed {self._consumed}, "
                f"seek target {target}; re-feed the deque from the start")
        while self._consumed < target and self._q:
            self._q.popleft()
            self._consumed += 1

    @property
    def exhausted(self) -> bool:
        return self._done and not self._q


class JsonlReplaySource(Source):
    """Replay a JSON-lines event capture; offset = line number.

    Parsing batches through the C++ decoder (heatmap_tpu.native) when a
    toolchain exists — the capture-replay path feeds the bench, so the
    per-line Python parse matters; falls back to json.loads otherwise."""

    def __init__(self, path: str, loop: bool = False):
        self.path = path
        self.loop = loop
        from heatmap_tpu.native import maybe_decoder

        self._fh = open(path, "rb")
        self._line = 0
        self._eof = False
        self._dec = maybe_decoder()
        self._intern_p: dict = {}
        self._intern_v: dict = {}

    def poll(self, max_events: int):
        raw: list[bytes] = []
        wrapped = False
        while len(raw) < max_events:
            line = self._fh.readline()
            if not line:
                if self.loop and not wrapped:
                    # at most one wrap per poll, so an empty/unparseable
                    # file can't spin this loop forever
                    self._fh.seek(0)
                    self._line = 0
                    wrapped = True
                    continue
                self._eof = not self.loop
                break
            self._line += 1
            line = line.strip()
            if not line:
                continue
            raw.append(line)
        return _decode_raw_values(self._dec, raw,
                                  self._intern_p, self._intern_v)

    def offset(self):
        return self._line

    def seek(self, offset) -> None:
        self._fh.seek(0)
        for _ in range(int(offset or 0)):
            self._fh.readline()
        self._line = int(offset or 0)
        self._eof = False

    @property
    def exhausted(self) -> bool:
        return self._eof and not self.loop

    def close(self) -> None:
        self._fh.close()


class SyntheticSource(Source):
    """Deterministic synthetic city traffic (BASELINE.json config #3).

    Every event is a pure function of its absolute index: vehicle
    ``i % n_vehicles`` follows a parametric orbit around a per-vehicle
    anchor inside the city box.  That makes ``seek`` exact and O(1) — a
    resumed replay is bit-identical regardless of batch chunking — and the
    generator is fully vectorized (no JSON on the bench hot path).
    Offset = number of events emitted.
    """

    def __init__(
        self,
        n_events: int | None = None,
        n_vehicles: int = 2000,
        center=(42.3601, -71.0589),      # Boston (reference default view)
        radius_deg: float = 0.15,
        t0: int = 1_700_000_000,
        events_per_second: int = 100_000,
        seed: int = 0,
    ):
        self.n_events = n_events  # None = unbounded
        self.n_vehicles = n_vehicles
        self.center = center
        self.radius = radius_deg
        self.t0 = t0
        self.eps = events_per_second
        self.seed = seed
        self._emitted = 0
        rng = np.random.default_rng(seed)  # init-time only: fixed draw order
        self._anchor = np.stack([
            center[0] + rng.uniform(-radius_deg, radius_deg, n_vehicles),
            center[1] + rng.uniform(-radius_deg, radius_deg, n_vehicles),
        ], axis=1)
        self._orbit_r = rng.uniform(0.002, 0.03, n_vehicles)      # deg
        self._speed = rng.uniform(10, 90, n_vehicles).astype(np.float32)
        # angular velocity (rad/s of sim time) consistent with the speed
        self._omega = (self._speed / 3.6) / (self._orbit_r * 111_000.0)
        self._phase = rng.uniform(0, 2 * math.pi, n_vehicles)
        self._vehicles = [f"veh-{i}" for i in range(n_vehicles)]

    def poll(self, max_events: int) -> EventColumns:
        n = max_events
        if self.n_events is not None:
            n = min(n, self.n_events - self._emitted)
        if n <= 0:
            return columns_from_arrays([], [], [], [])
        i = self._emitted + np.arange(n, dtype=np.int64)
        vid = (i % self.n_vehicles).astype(np.int32)
        sim_t = i / self.eps
        ang = self._omega[vid] * sim_t + self._phase[vid]
        lat = self._anchor[vid, 0] + self._orbit_r[vid] * np.cos(ang)
        lng = self._anchor[vid, 1] + self._orbit_r[vid] * np.sin(ang)
        # deterministic per-event speed jitter
        speed = np.maximum(
            self._speed[vid] + 2.0 * np.sin(0.7 * i).astype(np.float32), 0.0
        )
        ts = self.t0 + i // self.eps
        cols = columns_from_arrays(
            lat.astype(np.float32),
            lng.astype(np.float32),
            speed.astype(np.float32),
            ts.astype(np.int32),
            provider_id=np.zeros(n, np.int32),
            vehicle_id=vid,
            providers=["synthetic"],
            vehicles=self._vehicles,
        )
        self._emitted += n
        return cols

    def offset(self):
        return self._emitted

    def seek(self, offset) -> None:
        self._emitted = int(offset or 0)

    @property
    def exhausted(self) -> bool:
        return self.n_events is not None and self._emitted >= self.n_events


class RampSource(Source):
    """Piecewise offered-load schedule with a REAL backlog queue.

    The chaos/governor benches (tools/e2e_rate.py ``--ramp``,
    tests/test_govern.py) need a source whose staleness is honest: a
    producer emits events at a scheduled rate against the clock, and a
    consumer that falls behind receives genuinely OLD events — exactly
    the event-age signal the BatchGovernor (stream/govern.py) governs
    against.  ``poll`` returns ``min(requested, backlog)`` events whose
    timestamps are their PRODUCTION times, so event age == how long the
    engine left them queued.

    ``schedule`` is ``[(events_per_second, duration_s), ...]`` in the
    injected clock's units — tests drive it (and the runtime's lineage
    clock) with an accelerated virtual clock so second-resolution event
    timestamps resolve sub-second real dynamics.  Exhausted once the
    schedule has elapsed and the backlog drained.  Events cycle a small
    fixed vehicle/cell population (deterministic function of the event
    index), keeping state-slab occupancy flat so a governed soak can
    never trip a slab-growth retrace by itself.
    """

    def __init__(self, schedule, clock=_time.monotonic, t0: float = 0.0,
                 n_vehicles: int = 64,
                 center=(42.3601, -71.0589), radius_deg: float = 0.05):
        self.schedule = [(float(r), float(d)) for r, d in schedule]
        if not self.schedule or any(d <= 0 for _, d in self.schedule):
            raise ValueError("schedule must be non-empty (rate, "
                             "duration>0) pairs")
        self.clock = clock
        self._t0 = t0 or None      # anchored at the first poll
        self.n_vehicles = int(n_vehicles)
        rng = np.random.default_rng(7)
        self._lat = (center[0] + rng.uniform(-radius_deg, radius_deg,
                                             self.n_vehicles)
                     ).astype(np.float32)
        self._lng = (center[1] + rng.uniform(-radius_deg, radius_deg,
                                             self.n_vehicles)
                     ).astype(np.float32)
        self._speed = rng.uniform(10, 90, self.n_vehicles
                                  ).astype(np.float32)
        self._vehicles = [f"veh-{i}" for i in range(self.n_vehicles)]
        # cumulative produced-event counts / elapsed at phase starts
        self._phase_t = np.cumsum([0.0] + [d for _, d in self.schedule])
        self._phase_n = np.cumsum(
            [0.0] + [r * d for r, d in self.schedule])
        self._consumed = 0
        self._stopped = False

    def _elapsed(self) -> float:
        if self._t0 is None:
            self._t0 = self.clock()
        return max(0.0, self.clock() - self._t0)

    def _produced(self, elapsed: float) -> int:
        i = int(np.searchsorted(self._phase_t, elapsed, side="right")) - 1
        if i >= len(self.schedule):
            return int(self._phase_n[-1])
        rate, _ = self.schedule[i]
        return int(self._phase_n[i]
                   + rate * (elapsed - self._phase_t[i]))

    def _produce_times(self, i0: int, i1: int) -> np.ndarray:
        """Production clock time of events [i0, i1) — the inverse of
        the cumulative schedule, per phase."""
        idx = np.arange(i0, i1, dtype=np.float64)
        ph = np.searchsorted(self._phase_n[1:], idx, side="right")
        ph = np.minimum(ph, len(self.schedule) - 1)
        rates = np.array([r for r, _ in self.schedule])
        return (self._phase_t[ph]
                + (idx - self._phase_n[ph]) / rates[ph])

    def stop(self) -> None:
        """Give up on the remaining backlog: the source reads exhausted
        on the next poll.  The ramp bench's drain bound — a static
        config that fell 10x behind must not stretch the run by the
        whole backlog's drain time."""
        self._stopped = True

    def poll(self, max_events: int):
        if self._stopped:
            return None
        elapsed = self._elapsed()
        backlog = self._produced(elapsed) - self._consumed
        n = min(int(max_events), backlog)
        if n <= 0:
            return None
        i0, i1 = self._consumed, self._consumed + n
        t_prod = self._produce_times(i0, i1)
        idx = np.arange(i0, i1, dtype=np.int64)
        vid = (idx % self.n_vehicles).astype(np.int32)
        cols = columns_from_arrays(
            self._lat[vid], self._lng[vid], self._speed[vid],
            (self._t0 + t_prod).astype(np.int64).astype(np.int32),
            vehicle_id=vid, providers=["ramp"], vehicles=self._vehicles)
        self._consumed = i1
        return cols

    @property
    def backlog(self) -> int:
        return self._produced(self._elapsed()) - self._consumed

    def offset(self):
        return self._consumed

    def seek(self, offset) -> None:
        self._consumed = int(offset or 0)

    @property
    def exhausted(self) -> bool:
        return self._stopped or (self._elapsed() >= self._phase_t[-1]
                                 and self._consumed
                                 >= int(self._phase_n[-1]))


class KafkaSource(Source):
    """Kafka consumer source (the reference's ingress contract,
    mbta_to_kafka.py:33-39 / heatmap_stream.py:79-86).

    Default implementation is the framework's own wire-protocol client
    (heatmap_tpu.kafka) — zero external dependencies; confluent_kafka is
    preferred when installed (C client).  Set HEATMAP_KAFKA_IMPL to
    wire | confluent | kafka-python to pin one.  Offsets are tracked per
    partition and committed via the framework checkpoint, not the broker,
    mirroring the reference's Spark-side offset ownership
    (README.md:214-215).
    """

    def __init__(self, bootstrap: str, topic: str, group: str = "heatmap-tpu",
                 impl: str | None = None):
        import os

        impl = impl or os.environ.get("HEATMAP_KAFKA_IMPL", "auto")
        if impl in ("auto", "confluent"):
            try:
                self._impl = _ConfluentImpl(bootstrap, topic, group)
                return
            except ImportError:
                if impl == "confluent":
                    raise
        if impl == "kafka-python":
            self._impl = _KafkaPythonImpl(bootstrap, topic)
            return
        self._impl = _WireImpl(bootstrap, topic)

    def poll(self, max_events: int):
        return self._impl.poll(max_events)

    def offset(self):
        return self._impl.offset()

    def seek(self, offset) -> None:
        self._impl.seek(offset)

    @property
    def counters(self) -> dict:
        return dict(getattr(self._impl, "counters", None) or {})

    def take_spans(self) -> dict:
        fn = getattr(self._impl, "take_spans", None)
        return fn() if fn is not None else {}

    def close(self) -> None:
        self._impl.close()


def _value_decoder():
    """Per-message value -> list of event dicts (empty = drop), honoring
    HEATMAP_EVENT_FORMAT so every consumer impl speaks the same format as
    the publisher: stream/binfmt.py for "binary", stream/colfmt.py batch
    expansion for "columnar", JSON otherwise."""
    import os

    fmt = os.environ.get("HEATMAP_EVENT_FORMAT", "json")
    if fmt == "binary":
        from heatmap_tpu.stream.binfmt import decode_event

        def _bin(value):
            d = decode_event(value)
            return [] if d is None else [d]

        return _bin
    if fmt == "columnar":
        from heatmap_tpu.stream.colfmt import decode_batch_dicts

        return decode_batch_dicts

    def _json(value):
        try:
            return [json.loads(value)]
        except (json.JSONDecodeError, TypeError, UnicodeDecodeError):
            return []

    return _json


class _ConfluentImpl:
    def __init__(self, bootstrap, topic, group):
        import os

        from confluent_kafka import Consumer

        self.c = Consumer({
            "bootstrap.servers": bootstrap,
            "group.id": group,
            "enable.auto.commit": False,
            "auto.offset.reset": "latest",  # ref: startingOffsets=latest
        })
        self.c.subscribe([topic])
        self.topic = topic
        self._offsets: dict[int, int] = {}
        self._fmt = os.environ.get("HEATMAP_EVENT_FORMAT", "json")
        self._decode_value = _value_decoder()

    def poll(self, max_events):
        out = []
        # columnar: every message is a whole batch, and messages handed
        # out by consume() are consumed (no redelivery without a seek) —
        # so bound the expansion at the fetch, not with a mid-loop break
        n_msgs = 1 if self._fmt == "columnar" else max_events
        msgs = self.c.consume(num_messages=n_msgs, timeout=0.05)
        for m in msgs:
            if m.error():
                continue
            ds = self._decode_value(m.value())
            self._offsets[m.partition()] = m.offset() + 1
            out.extend(ds)
        return out

    def offset(self):
        return dict(self._offsets)

    def seek(self, offset):
        from confluent_kafka import TopicPartition

        if offset:
            self.c.assign([TopicPartition(self.topic, int(p), int(o))
                           for p, o in offset.items()])
            self._offsets = {int(p): int(o) for p, o in offset.items()}

    def close(self):
        self.c.close()


class _KafkaPythonImpl:
    def __init__(self, bootstrap, topic):
        from kafka import KafkaConsumer

        self.c = KafkaConsumer(
            topic,
            bootstrap_servers=bootstrap,
            enable_auto_commit=False,
            auto_offset_reset="latest",
            # decode (json or binary) happens in poll so a malformed value
            # is dropped rather than crashing the iterator
            consumer_timeout_ms=50,
        )
        self._offsets: dict[int, int] = {}
        self._decode_value = _value_decoder()

    def poll(self, max_events):
        out = []
        try:
            for m in self.c:
                ds = self._decode_value(m.value)
                self._offsets[m.partition] = m.offset + 1
                out.extend(ds)
                if len(out) >= max_events:
                    break
        except StopIteration:
            pass
        return out

    def offset(self):
        return dict(self._offsets)

    def seek(self, offset):
        pass  # assigned on rebalance; framework replay covers the gap

    def close(self):
        self.c.close()


class _WireImpl:
    """Consumer over the framework's own Kafka wire client (no deps).

    Starts at LATEST offsets like the reference (startingOffsets=latest,
    heatmap_stream.py:84); ``seek`` with a checkpointed {partition: offset}
    map overrides that on resume.  Round-robins partitions each poll so no
    partition starves under a small max_events.
    """

    # extra fetch sweeps per poll may start within this wall budget (the
    # first sweep always runs); see _poll_record_loop
    sweep_budget_s = 0.2

    def __init__(self, bootstrap, topic):
        import logging
        import os

        from heatmap_tpu.kafka import KafkaClient

        self.log = logging.getLogger(__name__)
        self.c = KafkaClient(bootstrap)
        self.topic = topic
        # event value encoding on this topic: "json" (reference contract),
        # "binary" (stream/binfmt.py — high-rate per-event), or "columnar"
        # (stream/colfmt.py — whole batches per value, memcpy decode)
        self._fmt = os.environ.get("HEATMAP_EVENT_FORMAT", "json")
        self._offsets: dict[int, int] = {}
        # transport-health counters (surfaced at /metrics via
        # Source.counters): every handled fetch/discovery error and
        # retention-forced offset reset counts, so a flapping broker is
        # visible without grepping warnings out of the logs
        self.counters = {"kafka_fetch_errors": 0,
                         "kafka_offset_resets": 0,
                         "kafka_discover_errors": 0}
        self._discover()
        self._rr = 0  # round-robin cursor
        # hot path: decode fetched record values to columnar arrays in C++
        # (heatmap_tpu.native) instead of per-record json.loads — the
        # per-row-Python cost is the reference's bottleneck #1
        # (SURVEY.md §3.3); falls back to Python when no toolchain
        from heatmap_tpu.native import maybe_decoder

        self._dec = maybe_decoder(self.log)
        self._intern_p: dict = {}
        self._intern_v: dict = {}
        self._col_cache: dict = {}  # colfmt LUT memo (same lifetime)
        # per-fetch response cap.  The protocol default (1 MiB) costs a
        # full request/response round trip per ~37k columnar events;
        # large micro-batches sweep partitions repeatedly to fill, and
        # the round-trip count was a measurable slice of the round-5
        # ingest profile.  4 MiB ≈ one 150k-event columnar record batch
        # per fetch.  Read here (not at import) so tools/tests setting
        # the env var after import are honored, like the neighboring
        # format/impl knobs.
        self.fetch_max_bytes = int(os.environ.get(
            "HEATMAP_FETCH_MAX_BYTES", str(4 << 20)))
        # poll sub-spans (Source.take_spans): wall spent in broker fetch
        # round trips vs value decode, drained by the runtime per batch
        self._spans = {"fetch": 0.0, "decode": 0.0}

    def take_spans(self) -> dict:
        out = {k: v for k, v in self._spans.items() if v > 0.0}
        self._spans = {"fetch": 0.0, "decode": 0.0}
        return out

    def _discover(self) -> None:
        """(Re)initialize offsets for newly visible partitions at LATEST.
        Tolerates a topic mid-auto-creation (empty partition set): poll
        retries until leaders exist."""
        from heatmap_tpu.kafka import KafkaError
        from heatmap_tpu.kafka.client import LATEST

        try:
            for p, off in self.c.list_offsets(self.topic, LATEST).items():
                self._offsets.setdefault(p, off)
        except (KafkaError, ConnectionError, OSError) as e:
            self.counters["kafka_discover_errors"] += 1
            self.log.warning("kafka partition discovery failed: %s", e)

    def _guarded_fetch(self, p: int, fn):
        """One fetch with the consumer's retriable-error policy; None on a
        handled error (the partition is retried next poll)."""
        from heatmap_tpu.kafka import KafkaError
        from heatmap_tpu.kafka.client import EARLIEST

        t0 = _time.monotonic()
        try:
            return fn()
        except KafkaError as e:
            if e.code == 1:  # OFFSET_OUT_OF_RANGE: retention truncated
                # past our checkpoint — resume from the log start
                self.counters["kafka_offset_resets"] += 1
                try:
                    earliest = self.c.list_offsets(self.topic, EARLIEST)
                    self.log.warning(
                        "offset %d for %s[%d] out of range; resetting "
                        "to earliest %d", self._offsets[p], self.topic,
                        p, earliest.get(p, 0))
                    self._offsets[p] = earliest.get(p, 0)
                except (KafkaError, ConnectionError, OSError) as e2:
                    self.log.warning("offset reset failed: %s", e2)
            else:
                self.counters["kafka_fetch_errors"] += 1
                self.log.warning("fetch %s[%d]: %s", self.topic, p, e)
        except (ConnectionError, OSError) as e:
            self.counters["kafka_fetch_errors"] += 1
            self.log.warning("fetch %s[%d]: %s", self.topic, p, e)
        finally:
            self._spans["fetch"] += _time.monotonic() - t0
        return None

    def poll(self, max_events):
        if self._fmt == "columnar":
            return self._poll_colfmt(max_events)
        if self._dec is not None:
            return self._poll_columnar(max_events)
        return self._poll_records(max_events)

    def _poll_record_loop(self, max_events, handle):
        """Shared per-record fetch skeleton: round-robin the partitions,
        guarded fetch, advance the offset past every record (tombstones
        too) and past skipped batches when a fetch is fully consumed.
        ``handle(p, r) -> n`` consumes one non-null record and returns how
        many events it contributed toward ``max_events``."""
        if not self._offsets:
            self._discover()
        parts = sorted(self._offsets)
        if not parts:
            return
        n_out = 0
        # Sweep the partitions REPEATEDLY until the request is filled or
        # a full sweep makes no progress: one fetch returns at most
        # ~max_bytes (1 MiB) of records, so a single round-robin pass
        # caps a poll at ~n_partitions MiB — far below a large
        # micro-batch, and the resulting partial polls made the runtime
        # pay carry/dispatch overhead per MiB instead of per batch.
        # Only the FIRST sweep's fetches wait (max_wait_ms); follow-up
        # sweeps use 0 so a drained topic never stalls the loop.  Extra
        # sweeps start only within ``sweep_budget_s``: on a LIVE tail a
        # trickle producer keeps every sweep barely progressing, and an
        # unbounded loop would sit here up to max_events/producer_rate —
        # stalling watermarks, emits, and the supervisor heartbeat —
        # instead of returning a partial batch like a streaming poll
        # must.  (A backfill replay fills from a full broker in a couple
        # of sweeps, well inside the budget.)
        sweep_wait = 50
        t0 = _time.monotonic()
        while n_out < max_events:
            progressed = False
            for k in range(len(parts)):
                if n_out >= max_events:
                    break
                p = parts[(self._rr + k) % len(parts)]
                fr = self._guarded_fetch(
                    p, lambda p=p, w=sweep_wait: self.c.fetch(
                        self.topic, p, self._offsets[p],
                        max_bytes=self.fetch_max_bytes, max_wait_ms=w))
                if fr is None:
                    continue
                if fr.skipped_batches:
                    self.log.warning(
                        "skipped %d undecodable batches on %s[%d]",
                        fr.skipped_batches, self.topic, p)
                taken = 0
                for r in fr.records:
                    if n_out >= max_events:
                        break
                    taken += 1
                    self._offsets[p] = r.offset + 1
                    if r.value is None:
                        continue
                    n_out += handle(p, r)
                if taken:
                    progressed = True
                if taken == len(fr.records):
                    # consumed everything fetched: also jump past skipped
                    # batches / trailing tombstones
                    self._offsets[p] = max(self._offsets[p], fr.next_offset)
            if not progressed:
                break
            if _time.monotonic() - t0 >= self.sweep_budget_s:
                break
            sweep_wait = 0
        self._rr = (self._rr + 1) % max(len(parts), 1)

    def _poll_colfmt(self, max_events):
        """HEATMAP_EVENT_FORMAT=columnar: each record value is a whole
        struct-of-arrays batch (stream/colfmt.py) — decode is numpy views,
        no per-event work.  Values are consumed at batch granularity (a
        poll may overshoot max_events by up to one batch)."""
        from heatmap_tpu.stream.colfmt import concat_columns, decode_batch

        out = []

        def handle(p, r):
            t0 = _time.monotonic()
            cols = decode_batch(r.value, self._intern_p, self._intern_v,
                                self._col_cache)
            self._spans["decode"] += _time.monotonic() - t0
            if cols is None:
                self.log.warning("dropping malformed columnar value at "
                                 "%s[%d]@%d", self.topic, p, r.offset)
                return 0
            if len(cols) or cols.n_dropped:
                out.append(cols)
            return len(cols)

        self._poll_record_loop(max_events, handle)
        if not out:
            return []
        return concat_columns(out, self._intern_p, self._intern_v)

    def _poll_records(self, max_events):
        """Portable path (no C++ toolchain): per-record Python decode."""
        out = []

        def handle(p, r):
            out.append(r.value)
            return 1

        self._poll_record_loop(max_events, handle)
        t0 = _time.monotonic()
        cols = _decode_raw_values(self._dec, out,
                                  self._intern_p, self._intern_v, self._fmt)
        self._spans["decode"] += _time.monotonic() - t0
        return cols

    def _poll_columnar(self, max_events):
        """Hot path: Fetch blobs decode to joined value buffers in C++
        (native.kafka_decode_values — newline framing for JSON,
        length-prefixed for binary events) and feed the columnar decoder
        directly — per-record Python only on the rare fallback (corrupt
        varints / newline-bearing JSON values), where values are re-framed
        into the same stream."""
        binary = self._fmt == "binary"
        framing = "lp" if binary else "newline"
        if not self._offsets:
            self._discover()
        parts = sorted(self._offsets)
        if not parts:
            return []
        blobs: list[bytes] = []
        n_out = 0
        pre_dropped = 0
        for k in range(len(parts)):
            if n_out >= max_events:
                break
            p = parts[(self._rr + k) % len(parts)]
            res = self._guarded_fetch(
                p, lambda p=p: self.c.fetch_values(
                    self.topic, p, self._offsets[p],
                    max_bytes=self.fetch_max_bytes, max_wait_ms=50,
                    framing=framing))
            if res is None:
                continue
            _hw, fv = res
            skipped = getattr(fv, "skipped_batches", 0)
            if skipped:
                self.log.warning("skipped %d undecodable batches on %s[%d]",
                                 skipped, self.topic, p)
            if hasattr(fv, "blob"):  # native KafkaValues
                room = max_events - n_out
                nv = len(fv)
                if nv <= room:
                    if nv:
                        blobs.append(fv.blob)
                        n_out += nv
                    # next_offset covers every value, null, and skipped batch
                    self._offsets[p] = max(self._offsets[p], fv.next_offset)
                else:
                    blobs.append(fv.blob[:int(fv.val_pos[room])])
                    # resume at the first untaken value, so nulls/skipped
                    # batches between the last taken and first untaken
                    # value aren't re-fetched (and re-warned) next poll
                    self._offsets[p] = int(fv.val_off[room])
                    n_out += room
            else:  # FetchResult fallback for this blob
                taken = 0
                for r in fv.records:
                    if n_out >= max_events:
                        break
                    taken += 1
                    self._offsets[p] = r.offset + 1
                    if r.value is None:
                        continue
                    if binary:
                        from heatmap_tpu.stream.binfmt import frame_lp

                        blobs.append(frame_lp([r.value]))
                        n_out += 1
                        continue
                    try:
                        blobs.append(
                            json.dumps(json.loads(r.value)).encode() + b"\n")
                        n_out += 1
                    except (ValueError, UnicodeDecodeError):
                        pre_dropped += 1  # malformed → dropped (ref filters)
                if taken == len(fv.records):
                    self._offsets[p] = max(self._offsets[p], fv.next_offset)
        self._rr = (self._rr + 1) % max(len(parts), 1)
        if not blobs:
            if pre_dropped:
                cols = columns_from_arrays([], [], [], [])
                cols.n_dropped = pre_dropped
                return cols
            return []
        t0 = _time.monotonic()
        joined = b"".join(blobs)
        if binary:
            cols, _ = self._dec.decode_binary(joined)
        else:
            cols, _ = self._dec.decode(joined, final=True)
        self._spans["decode"] += _time.monotonic() - t0
        cols.n_dropped += pre_dropped
        return cols

    def offset(self):
        return dict(self._offsets)

    def seek(self, offset):
        if offset:
            self._offsets.update({int(p): int(o) for p, o in offset.items()})

    def close(self):
        self.c.close()
