"""Offsets + device-state checkpointing (replaces Spark's checkpointLocation).

The reference delegates offsets and windowed-aggregation state to Spark's
checkpoint directory (reference: heatmap_stream.py:37,244; resume semantics
SURVEY.md §5.4).  Here the framework owns both:

- ``meta.json``  — source offset, watermark high-ts, epoch counter
  (written atomically via rename).
- ``state-<res>-<win>.npz`` — the aggregation slabs, one per configured
  (resolution, window) pair.

Commit ordering (SURVEY.md §7 hard part #5): the runtime drains the sink
writer *before* committing, so a crash replays only events whose upserts
are idempotent by deterministic _id — same correctness backstop the
reference relies on (heatmap_stream.py:173,188).
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

from heatmap_tpu.engine.state import TileState


class CheckpointManager:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.meta_path = os.path.join(directory, "meta.json")

    # --- meta -----------------------------------------------------------
    def load_meta(self) -> dict | None:
        if not os.path.exists(self.meta_path):
            return None
        with open(self.meta_path, encoding="utf-8") as fh:
            return json.load(fh)

    def commit(self, offset: Any, max_event_ts: int, epoch: int,
               states: dict[tuple[int, int], TileState] | None = None) -> None:
        if states:
            for (res, win), st in states.items():
                path = os.path.join(self.dir, f"state-{res}-{win}.npz")
                tmp = path + ".tmp.npz"
                np.savez(tmp, **{k: np.asarray(v) for k, v in st._asdict().items()})
                os.replace(tmp, path)
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"offset": offset, "max_event_ts": int(max_event_ts),
                       "epoch": int(epoch)}, fh)
        os.replace(tmp, self.meta_path)

    def load_state(self, res: int, win: int) -> TileState | None:
        path = os.path.join(self.dir, f"state-{res}-{win}.npz")
        if not os.path.exists(path):
            return None
        with np.load(path) as z:
            return TileState(**{k: z[k] for k in TileState._fields})
