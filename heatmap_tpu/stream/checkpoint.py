"""Offsets + device-state checkpointing (replaces Spark's checkpointLocation).

The reference delegates offsets and windowed-aggregation state to Spark's
checkpoint directory (reference: heatmap_stream.py:37,244; resume semantics
SURVEY.md §5.4).  Here the framework owns both.

Atomicity: every commit writes a fresh ``commit-<epoch>/`` directory holding
``meta.json`` (source offset, watermark high-ts, epoch) plus one
``state-<res>-<win>.npz`` per configured (resolution, window) pair, then
atomically renames the single ``LATEST`` pointer file at it.  A crash at any
point leaves LATEST referencing a complete older commit — offsets and state
can never be torn against each other (a torn pair would double-count
replayed events into restored state).  Older commit dirs are pruned after
the pointer moves.

Commit ordering (SURVEY.md §7 hard part #5): the runtime drains the sink
writer *before* committing, so a crash replays only events whose upserts
are idempotent by deterministic _id — same correctness backstop the
reference relies on (heatmap_stream.py:173,188).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any

import numpy as np

from heatmap_tpu.engine.state import TileState

KEEP_COMMITS = 2  # current + previous, for post-mortem debugging


class CheckpointManager:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.latest_path = os.path.join(directory, "LATEST")

    def _commit_dir(self, epoch: int | None = None) -> str | None:
        if epoch is not None:
            path = os.path.join(self.dir, f"commit-{epoch:012d}")
            return path if os.path.isdir(path) else None
        if not os.path.exists(self.latest_path):
            return None
        with open(self.latest_path, encoding="utf-8") as fh:
            name = fh.read().strip()
        path = os.path.join(self.dir, name)
        return path if os.path.isdir(path) else None

    def available_epochs(self) -> list[int]:
        """Epochs with a complete retained commit dir (ascending)."""
        out = []
        for n in sorted(os.listdir(self.dir)) if os.path.isdir(self.dir) else []:
            if n.startswith("commit-") and not n.endswith(".tmp"):
                if os.path.exists(os.path.join(self.dir, n, "meta.json")):
                    out.append(int(n[len("commit-"):]))
        return out

    # --- read -----------------------------------------------------------
    def load_meta(self, epoch: int | None = None) -> dict | None:
        """Latest commit's meta, or a specific retained epoch's (multi-host
        resume agreement loads the common min epoch — stream.runtime)."""
        d = self._commit_dir(epoch)
        if d is None:
            return None
        with open(os.path.join(d, "meta.json"), encoding="utf-8") as fh:
            return json.load(fh)

    def load_state(self, res: int, win: int,
                   epoch: int | None = None) -> TileState | None:
        d = self._commit_dir(epoch)
        if d is None:
            return None
        path = os.path.join(d, f"state-{res}-{win}.npz")
        if not os.path.exists(path):
            return None
        with np.load(path) as z:
            missing = [k for k in TileState._fields if k not in z.files]
            if missing:
                # pre-anchor checkpoints hold ABSOLUTE sums; the current
                # state holds residual sums about per-group anchors that
                # an old snapshot simply doesn't have — synthesizing them
                # would corrupt every resumed average, so refuse loudly
                raise ValueError(
                    f"checkpoint {path} was written by an older state "
                    f"layout (missing {missing}); it cannot be resumed by "
                    f"this version — restart from empty state (the sink "
                    f"is idempotent) or replay with the writing version")
            return TileState(**{k: z[k] for k in TileState._fields})

    def load_extra(self, name: str, epoch: int | None = None) -> dict | None:
        """A named extras payload committed alongside the window state
        (``extra-<name>.npz``), or None when the commit predates it —
        e.g. the inference engine's entity table (infer.engine).  Extras
        are auxiliary: absence never blocks a resume."""
        d = self._commit_dir(epoch)
        if d is None:
            return None
        path = os.path.join(d, f"extra-{name}.npz")
        if not os.path.exists(path):
            return None
        with np.load(path) as z:
            return {k: z[k] for k in z.files}

    # --- write ----------------------------------------------------------
    def commit(self, offset: Any, max_event_ts: int, epoch: int,
               states: dict[tuple[int, int], TileState] | None = None,
               shards: int | None = None,
               snap_impl: str | None = None,
               mesh_mode: str | None = None,
               extras: dict[str, dict] | None = None) -> None:
        """``shards``: the writer's local shard-block count.  Recorded so
        a restart can tell a capacity change (absorbable: pad/grow) from a
        shard-count change (NOT absorbable: rows would be reinterpreted as
        the wrong shard blocks and keys would land off their owner).

        ``snap_impl``: the H3 snap implementation ("native" host C++ vs
        "xla" in-program) that keyed the checkpointed state.  The two
        agree everywhere except f32-rounded points lying exactly on a
        cell edge, so a resume pins the same impl (runtime._maybe_resume)
        rather than letting a backend failover re-key edge events
        mid-stream (ADVICE r4 #1).

        ``mesh_mode``: how the shard blocks were KEYED on a mesh run —
        "shuffle" (mix32 key hash, parallel.sharded.ShardedAggregator)
        vs "partitioned" (H3 parent cell, PartitionedAggregator).  Same
        shape, different key ownership: restoring one into the other
        would silently duplicate groups across devices, so the resume
        refuses a mismatch (stream.runtime._maybe_resume).

        ``extras``: named auxiliary payloads ({name: {key: array}}) —
        reducer state riding the same atomic commit as the window state
        it must stay consistent with (torn against each other, a resume
        would re-fold replayed batches into already-folded filter
        state)."""
        name = f"commit-{epoch:012d}"
        cdir = os.path.join(self.dir, name)
        tmp = cdir + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for (res, win), st in (states or {}).items():
            np.savez(os.path.join(tmp, f"state-{res}-{win}.npz"),
                     **{k: np.asarray(v) for k, v in st._asdict().items()})
        for ename, payload in (extras or {}).items():
            np.savez(os.path.join(tmp, f"extra-{ename}.npz"),
                     **{k: np.asarray(v) for k, v in payload.items()})
        meta = {"offset": offset, "max_event_ts": int(max_event_ts),
                "epoch": int(epoch)}
        if shards is not None:
            meta["shards"] = int(shards)
        if snap_impl is not None:
            meta["snap_impl"] = snap_impl
        if mesh_mode is not None:
            meta["mesh_mode"] = mesh_mode
        with open(os.path.join(tmp, "meta.json"), "w", encoding="utf-8") as fh:
            json.dump(meta, fh)
        shutil.rmtree(cdir, ignore_errors=True)
        os.replace(tmp, cdir)

        # the atomic pointer flip
        ptmp = self.latest_path + ".tmp"
        with open(ptmp, "w", encoding="utf-8") as fh:
            fh.write(name)
        os.replace(ptmp, self.latest_path)
        self._prune(keep=name)

    def _prune(self, keep: str) -> None:
        commits = sorted(
            n for n in os.listdir(self.dir)
            if n.startswith("commit-") and not n.endswith(".tmp")
        )
        for n in commits[:-KEEP_COMMITS]:
            if n != keep:
                shutil.rmtree(os.path.join(self.dir, n), ignore_errors=True)
