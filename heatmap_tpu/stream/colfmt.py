"""Columnar batch event values (``HEATMAP_EVENT_FORMAT=columnar``).

One Kafka record value carries N events in struct-of-arrays form plus a
batch-local string table.  Decoding is numpy views over the value bytes
plus one intern pass over the (small) string table: measured ~18M
ev/s/core cold and ~44M ev/s/core steady-state (the LUT cache skips the
intern pass when producers resend the same vehicle set) at 100k-event
batches with 5k vehicles — vs ~10M ev/s/core for the per-event binary
layout (stream/binfmt.py, C++) and ~0.2M for JSON (SURVEY.md §7 hard
part #3's end state).  At the 5M ev/s north star, ingest decode costs
~0.1 cores.

Layout (little-endian), after the 16-byte header:

    u8   magic    = 0xB2
    u8   version  = 1
    u16  flags    = 0 (reserved)
    u32  n              events in the batch
    u32  n_strings      entries in the batch string table
    u32  strtab_bytes   byte length of the string-table blob
    f32  lat[n]         degrees
    f32  lon[n]         degrees
    f32  speed[n]       km/h
    f32  bearing[n]
    f32  accuracy[n]
    i64  ts[n]          epoch seconds
    u32  provider_id[n] index into the batch string table
    u32  vehicle_id[n]  index into the batch string table
    string table: per entry u16 byte length + UTF-8 bytes, concatenated

Validation semantics on decode match parse_events exactly (vectorized):
rows with out-of-range lat/lon/ts, non-finite coordinates, or ids past
the string table are dropped and counted; non-finite speed becomes 0.

Trade-off vs the reference's per-event keying (mbta_to_kafka.py:79): a
batch value cannot be partitioned by vehicleId, so columnar publishers
spread batches round-robin.  The aggregation re-shards by (cell, window)
on device and the positions fold is a per-vehicle max-ts guard — both
order- and partition-insensitive — so affinity is not load-bearing in
this framework.
"""

from __future__ import annotations

import struct

import numpy as np

MAGIC = 0xB2
VERSION = 1
_HEAD = struct.Struct("<BBHIII")
HEADER_SIZE = _HEAD.size  # 16
# sentinel key for the session bytes->str memo stashed inside the
# caller-owned lut_cache (cannot collide with the (blob, n) tuple keys)
_BYTES_MEMO_KEY = ("__strtab_bytes_memo__",)

from heatmap_tpu.stream.events import EventColumns, parse_ts  # noqa: E402

_D2R = np.float32(np.pi / 180.0)


def encode_batch(events) -> bytes:
    """Canonical event dicts -> one columnar batch value.

    Events missing required fields or with unparseable ts are skipped
    (producers validate upstream; this mirrors binfmt.encode_event's
    strictness without failing the whole batch)."""
    lat, lon, speed, bearing, acc, ts = [], [], [], [], [], []
    pid, vid = [], []
    strings: dict[str, int] = {}

    def fnum(v):
        try:
            v = float(v) if v is not None else 0.0
        except (TypeError, ValueError):
            return 0.0
        return v if np.isfinite(v) else 0.0

    for e in events:
        try:
            la, lo = float(e["lat"]), float(e["lon"])
            if e["provider"] is None or e["vehicleId"] is None:
                continue  # parse_events drops null identities
            provider = str(e["provider"])
            vehicle = str(e["vehicleId"])
        except (KeyError, TypeError, ValueError):
            continue
        t = parse_ts(e.get("ts"))
        # skip what i64 can't carry — one poison ts must never wedge the
        # publisher's whole retry buffer
        if t is None or not np.isfinite(t) or not (-2**62 <= t < 2**62):
            continue
        lat.append(la)
        lon.append(lo)
        speed.append(fnum(e.get("speedKmh")))
        bearing.append(fnum(e.get("bearing")))
        acc.append(fnum(e.get("accuracyM")))
        ts.append(int(t))
        pid.append(strings.setdefault(provider, len(strings)))
        vid.append(strings.setdefault(vehicle, len(strings)))

    n = len(lat)
    # canonicalize the table: ids above were assigned first-seen, so the
    # SAME name set arriving in a different row order (live pollers,
    # rotating replay windows) would produce a different blob record
    # after record — defeating the decoder's blob-keyed LUT cache, whose
    # misses (a ~5k-name Python parse + re-intern per record) were the
    # top term of the round-5 ingest profile.  Sorted names make the
    # blob a pure function of the name SET, so steady-state decode does
    # no per-string work at all.
    order = sorted(range(len(strings)), key=list(strings).__getitem__)
    remap = np.empty(max(len(strings), 1), "<u4")
    remap[np.asarray(order, np.int64)] = np.arange(len(order), dtype="<u4")
    names = sorted(strings)
    tab = _encode_strtab(names)
    pid_arr = remap[np.asarray(pid, np.int64)] if pid else \
        np.zeros(0, "<u4")
    vid_arr = remap[np.asarray(vid, np.int64)] if vid else \
        np.zeros(0, "<u4")
    head = _HEAD.pack(MAGIC, VERSION, 0, n, len(strings), len(tab))
    return b"".join([
        head,
        np.asarray(lat, "<f4").tobytes(),
        np.asarray(lon, "<f4").tobytes(),
        np.asarray(speed, "<f4").tobytes(),
        np.asarray(bearing, "<f4").tobytes(),
        np.asarray(acc, "<f4").tobytes(),
        np.asarray(ts, "<i8").tobytes(),
        pid_arr.astype("<u4", copy=False).tobytes(),
        vid_arr.astype("<u4", copy=False).tobytes(),
        tab,
    ])


def encode_batch_columns(cols: EventColumns) -> bytes:
    """EventColumns -> one columnar batch value, array-native.

    The high-rate path for replay/backfill producers: no per-event
    Python.  Assumes the rows are already validated (they came from
    parse_events / a decoder).  Only the strings this batch actually
    references go on the wire (ids are remapped compactly) — session
    intern tables are cumulative, and embedding them whole would grow
    every record with vehicle churn until the broker rejects it."""
    n = len(cols)
    pid_in = np.asarray(cols.provider_id, np.int64)
    vid_in = np.asarray(cols.vehicle_id, np.int64)
    if n and (pid_in.min() < 0 or pid_in.max() >= len(cols.providers)
              or vid_in.min() < 0 or vid_in.max() >= len(cols.vehicles)):
        # silent whole-batch drops at decode are worse than failing here
        raise ValueError("provider_id/vehicle_id out of string-table range")
    up = np.unique(pid_in) if n else np.zeros(0, np.int64)
    uv = np.unique(vid_in) if n else np.zeros(0, np.int64)
    strings = ([str(cols.providers[i]) for i in up]
               + [str(cols.vehicles[i]) for i in uv])
    remap_p = np.zeros(int(up[-1]) + 1 if len(up) else 1, "<u4")
    remap_p[up] = np.arange(len(up), dtype="<u4")
    remap_v = np.zeros(int(uv[-1]) + 1 if len(uv) else 1, "<u4")
    remap_v[uv] = np.arange(len(uv), dtype="<u4") + np.uint32(len(up))
    pid = remap_p[pid_in]
    vid = remap_v[vid_in]
    tab = _encode_strtab(strings)
    zeros = np.zeros(n, "<f4")
    head = _HEAD.pack(MAGIC, VERSION, 0, n, len(strings), len(tab))
    return b"".join([
        head,
        cols.lat_deg.astype("<f4", copy=False).tobytes(),
        cols.lng_deg.astype("<f4", copy=False).tobytes(),
        cols.speed_kmh.astype("<f4", copy=False).tobytes(),
        zeros.tobytes(),   # bearing (not carried in EventColumns)
        zeros.tobytes(),   # accuracy
        cols.ts_s.astype("<i8").tobytes(),
        pid.tobytes(),
        vid.tobytes(),
        tab,
    ])


def _encode_strtab(strings) -> bytes:
    """String table blob: per entry u16 byte length + UTF-8 bytes."""
    parts = []
    for s in strings:
        b = s.encode("utf-8")[:0xFFFF]
        parts.append(struct.pack("<H", len(b)))
        parts.append(b)
    return b"".join(parts)


def _parse_strtab(blob: bytes, n_strings: int,
                  bytes_memo: dict | None = None) -> list[str] | None:
    """Strtab blob -> list of strings.

    ``bytes_memo`` (session-lifetime, caller-owned) maps raw utf-8
    entries to their decoded strings: producers resend mostly the same
    names record after record but with drifting record boundaries the
    whole-blob memo in decode_batch misses, and decoding ~5k names per
    record was the top term of the round-5 ingest profile.  A bytes-key
    dict hit skips the decode (and reuses the one str object, which also
    makes the downstream intern setdefault a pointer-compare hit).  The
    entry offsets come from the C++ one-pass parser when a toolchain
    exists (decoder.cpp cf_strtab_offsets), replacing the per-entry
    struct.unpack_from loop."""
    offs = None
    try:
        from heatmap_tpu.native import strtab_offsets_native

        res = strtab_offsets_native(blob, n_strings)
        if res is not None:
            offs = res[0].tolist()
            lens = res[1].tolist()
    except ValueError:  # entry runs past the blob: same reject as below
        return None
    out = []
    memo_get = bytes_memo.get if bytes_memo is not None else None
    if offs is not None:
        for i in range(n_strings):
            o = offs[i]
            raw = blob[o:o + lens[i]]
            s = memo_get(raw) if memo_get is not None else None
            if s is None:
                s = raw.decode("utf-8", "replace")
                if bytes_memo is not None:
                    if len(bytes_memo) >= 1 << 20:  # unbounded-name safety
                        bytes_memo.clear()
                    bytes_memo[raw] = s
            out.append(s)
        return out
    off = 0
    for _ in range(n_strings):
        if off + 2 > len(blob):
            return None
        (ln,) = struct.unpack_from("<H", blob, off)
        off += 2
        if off + ln > len(blob):
            return None
        raw = blob[off:off + ln]
        s = memo_get(raw) if memo_get is not None else None
        if s is None:
            s = raw.decode("utf-8", "replace")
            if bytes_memo is not None:
                if len(bytes_memo) >= 1 << 20:
                    bytes_memo.clear()
                bytes_memo[raw] = s
        out.append(s)
        off += ln
    return out


def decode_batch(value: bytes, intern_p: dict, intern_v: dict,
                 lut_cache: dict | None = None,
                 extras: dict | None = None) -> EventColumns | None:
    """One columnar value -> EventColumns (session-interned ids).

    Returns None when the envelope (magic/version/lengths) is invalid;
    row-level validation drops rows into ``n_dropped`` exactly like
    parse_events.  ``lut_cache`` (owned by the caller, same lifetime as
    the intern maps) memoizes the string-table parse and the
    batch-id->session-id LUTs keyed by the table blob: producers resend
    the same vehicle set batch after batch, so the steady state does no
    per-string Python work at all.  ``extras``, when given, receives the
    wire columns EventColumns does not carry (``bearing``, ``accuracy``
    f32 arrays, row-filtered like the rest) — the dict-expansion
    fallback uses this to report the encoded values instead of zeros."""
    if len(value) < HEADER_SIZE:
        return None
    magic, ver, _flags, n, n_strings, tab_bytes = _HEAD.unpack_from(value)
    if magic != MAGIC or ver != VERSION:
        return None
    body = n * (5 * 4 + 8 + 2 * 4)
    if len(value) != HEADER_SIZE + body + tab_bytes:
        return None
    off = HEADER_SIZE

    def arr(dtype, count):
        nonlocal off
        a = np.frombuffer(value, dtype, count, off)
        off += a.nbytes
        return a

    lat = arr("<f4", n)
    lon = arr("<f4", n)
    speed = arr("<f4", n)
    bearing = arr("<f4", n)   # unused by the device path (EventColumns
    accuracy = arr("<f4", n)  # drops them); surfaced via ``extras``
    ts = arr("<i8", n)
    pid = arr("<u4", n)
    vid = arr("<u4", n)
    blob = value[off:off + tab_bytes]
    # key includes n_strings: the same blob under a different claimed count
    # parses (or fails) differently, and a hit must never skip the
    # envelope rejection the uncached path guarantees
    key = (blob, n_strings)
    cached = lut_cache.get(key) if lut_cache is not None else None
    if cached is None:
        bytes_memo = (lut_cache.setdefault(_BYTES_MEMO_KEY, {})
                      if lut_cache is not None else None)
        strings = _parse_strtab(blob, n_strings, bytes_memo)
        if strings is None:
            return None
        # role-split LUTs, filled lazily as ids are seen in each role
        cached = (strings, np.full(max(n_strings, 1), -1, np.int32),
                  np.full(max(n_strings, 1), -1, np.int32))
        if lut_cache is not None:
            if len(lut_cache) >= 128:  # bounded: vehicle churn makes new blobs
                lut_cache.clear()
            lut_cache[key] = cached
    strings, lut_p, lut_v = cached

    # vectorized validation, parse_events semantics
    ok = (
        np.isfinite(lat) & np.isfinite(lon)
        & (lat >= -90.0) & (lat <= 90.0)
        & (lon >= -180.0) & (lon <= 180.0)
        & (ts >= 0) & (ts < 2**31)
        & (pid < n_strings) & (vid < n_strings)
    )
    n_dropped = int(n - ok.sum())
    if n_dropped:
        lat, lon, speed = lat[ok], lon[ok], speed[ok]
        ts, pid, vid = ts[ok], pid[ok], vid[ok]
        if extras is not None:
            bearing, accuracy = bearing[ok], accuracy[ok]
    speed = np.where(np.isfinite(speed), speed, np.float32(0.0))
    if extras is not None:
        extras["bearing"] = bearing
        extras["accuracy"] = accuracy

    # batch-local string ids -> session intern ids, split by ROLE: only
    # strings actually referenced as providers enter the provider intern
    # map (and likewise vehicles), so the session tables stay clean.
    # Cached LUTs skip already-mapped ids (intern maps are grow-only, so
    # existing entries never invalidate).
    if len(pid):
        for i in np.unique(pid[lut_p[pid] < 0]):
            lut_p[i] = intern_p.setdefault(strings[i], len(intern_p))
    if len(vid):
        for i in np.unique(vid[lut_v[vid] < 0]):
            lut_v[i] = intern_v.setdefault(strings[i], len(intern_v))

    lat32 = lat.astype(np.float32, copy=False)
    lon32 = lon.astype(np.float32, copy=False)
    return EventColumns(
        lat_rad=lat32 * _D2R,
        lng_rad=lon32 * _D2R,
        lat_deg=lat32,
        lng_deg=lon32,
        speed_kmh=speed.astype(np.float32, copy=False),
        ts_s=ts.astype(np.int32),
        provider_id=lut_p[pid],
        vehicle_id=lut_v[vid],
        providers=list(intern_p),
        vehicles=list(intern_v),
        n_dropped=n_dropped,
    )


def concat_columns(parts: list[EventColumns], intern_p: dict,
                   intern_v: dict) -> EventColumns:
    """Concatenate batches that share the SAME session intern maps."""
    if len(parts) == 1:
        return parts[0]
    return EventColumns(
        lat_rad=np.concatenate([p.lat_rad for p in parts]),
        lng_rad=np.concatenate([p.lng_rad for p in parts]),
        lat_deg=np.concatenate([p.lat_deg for p in parts]),
        lng_deg=np.concatenate([p.lng_deg for p in parts]),
        speed_kmh=np.concatenate([p.speed_kmh for p in parts]),
        ts_s=np.concatenate([p.ts_s for p in parts]),
        provider_id=np.concatenate([p.provider_id for p in parts]),
        vehicle_id=np.concatenate([p.vehicle_id for p in parts]),
        providers=list(intern_p),
        vehicles=list(intern_v),
        n_dropped=sum(p.n_dropped for p in parts),
    )


def decode_batch_dicts(value: bytes) -> list[dict]:
    """One columnar value -> event dicts (portable consumer fallback for
    the optional confluent/kafka-python impls; the wire impl consumes
    EventColumns directly and never pays this expansion)."""
    p_map: dict = {}
    v_map: dict = {}
    extras: dict = {}
    cols = decode_batch(value, p_map, v_map, extras=extras)
    if cols is None:
        return []
    providers = list(p_map)
    vehicles = list(v_map)
    return [{
        "provider": providers[int(cols.provider_id[i])],
        "vehicleId": vehicles[int(cols.vehicle_id[i])],
        "lat": float(cols.lat_deg[i]),
        "lon": float(cols.lng_deg[i]),
        "speedKmh": float(cols.speed_kmh[i]),
        "bearing": float(extras["bearing"][i]),
        "accuracyM": float(extras["accuracy"][i]),
        "ts": int(cols.ts_s[i]),
    } for i in range(len(cols))]
