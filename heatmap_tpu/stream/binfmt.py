"""Fixed-layout binary event encoding (``HEATMAP_EVENT_FORMAT=binary``).

SURVEY.md §7 hard part #3: sustaining millions of events/sec makes
per-event JSON the ingest ceiling — the fix it prescribes is a
"fixed-layout binary" event format.  This module defines that format and
its portable codec; the C++ decoder (native/decoder.cpp
``dec_decode_binary``) consumes the same layout at memory speed.

One event value (little-endian, 32 bytes + strings):

    u8   magic      = 0xB1
    u8   version    = 1
    u8   P          provider byte length
    u8   V          vehicleId byte length
    f32  lat        degrees
    f32  lon        degrees
    f32  speedKmh
    f32  bearing
    f32  accuracyM
    i64  ts         epoch seconds
    P bytes         provider (UTF-8)
    V bytes         vehicleId (UTF-8)

The JSON format stays the default and the reference contract
(README.md:191-204); binary is a framework extension both ends opt into
via the same env knob.  Validation semantics on decode are identical to
the JSON path (stream/events.py): bad magic/layout, out-of-range
lat/lon/ts → dropped; non-finite speed → 0.
"""

from __future__ import annotations

import math
import struct

from heatmap_tpu.stream.events import parse_ts

MAGIC = 0xB1
VERSION = 1
_HEAD = struct.Struct("<BBBB5fq")
HEADER_SIZE = _HEAD.size  # 32


def encode_event(e: dict) -> bytes:
    """Canonical event dict -> binary value bytes.  Raises KeyError /
    ValueError on events missing required fields (producers validate)."""
    provider = str(e["provider"]).encode("utf-8")
    vehicle = str(e["vehicleId"]).encode("utf-8")
    if len(provider) > 255 or len(vehicle) > 255:
        raise ValueError("provider/vehicleId longer than 255 bytes")
    ts = parse_ts(e.get("ts"))
    if ts is None:
        raise ValueError(f"unparseable ts: {e.get('ts')!r}")

    def f(key):
        v = e.get(key)
        try:
            v = float(v) if v is not None else 0.0
        except (TypeError, ValueError):
            v = 0.0
        return v if math.isfinite(v) else 0.0

    return _HEAD.pack(MAGIC, VERSION, len(provider), len(vehicle),
                      float(e["lat"]), float(e["lon"]), f("speedKmh"),
                      f("bearing"), f("accuracyM"),
                      int(ts)) + provider + vehicle


def decode_event(b: bytes) -> dict | None:
    """Binary value bytes -> event dict; None when the envelope is invalid
    (bad magic/version/length).  Field-level validation is left to
    parse_events so drop semantics match the JSON path exactly."""
    if len(b) < HEADER_SIZE:
        return None
    magic, ver, pn, vn, lat, lon, speed, bearing, acc, ts = \
        _HEAD.unpack_from(b)
    if magic != MAGIC or ver != VERSION or len(b) != HEADER_SIZE + pn + vn:
        return None
    try:
        provider = b[HEADER_SIZE:HEADER_SIZE + pn].decode("utf-8")
        vehicle = b[HEADER_SIZE + pn:HEADER_SIZE + pn + vn].decode("utf-8")
    except UnicodeDecodeError:
        return None
    return {"provider": provider, "vehicleId": vehicle, "lat": lat,
            "lon": lon, "speedKmh": speed, "bearing": bearing,
            "accuracyM": acc, "ts": ts}


def decode_events(values) -> tuple[list[dict], int]:
    """(event dicts, n_envelope_dropped) for a batch of binary values."""
    out, dropped = [], 0
    for v in values:
        d = decode_event(v)
        if d is None:
            dropped += 1
        else:
            out.append(d)
    return out, dropped


def frame_lp(values) -> bytes:
    """Length-prefix (u32 LE) and join values — the framing
    dec_decode_binary consumes (and kafka_codec emits in mode 1)."""
    parts = []
    for v in values:
        parts.append(struct.pack("<I", len(v)))
        parts.append(v)
    return b"".join(parts)
