"""H3-parent stream partitioning: which runtime shard owns an event.

GeoFlink's grid-based spatial stream partitioning (PAPERS.md) is the
template: the event stream is split by the H3 PARENT cell of each
event's snapped location, so N runtime shards each fold a DISJOINT cell
space and the merged view is a plain union (upsert-only fan-in at the
materialized view — no cross-shard conflicts by construction).

The assignment must be a pure, stable function of the cell index alone:
every producer, shard, and tool that ever partitions the same stream
must agree, across processes and runs (Python's salted ``hash`` is
exactly what this must NOT be).  ``shard_of_cells`` therefore derives
the parent by H3 index bit surgery (the same exact, geometry-free
operation the query pyramid uses) and maps it through a fixed 64-bit
integer mix (murmur3 fmix64) mod N.

Knobs (flat env, read by ``config.load_config``):

- ``HEATMAP_SHARDS``       total shard count N (1 = unsharded, default)
- ``HEATMAP_SHARD_INDEX``  this process's shard in ``0..N-1``
- ``HEATMAP_SHARD_RES``    parent resolution of the partition key
  (coarser = better locality per shard, finer = better balance).
  Default -1 = the snap resolution itself (parent == cell: maximal
  balance, still exact).  Must not exceed the snap resolution.

Exactness contract (what the differential test pins): the partitioner
snaps each event at the COARSEST configured fold resolution with the
same host snap the fold itself uses, so for single-resolution configs
(any window set) every (cell, window) group lands wholly in one shard
and the N-shard merged emits are byte-identical to the 1-shard fold.
Multi-resolution pyramids partition by the coarsest resolution's cell
space; finer-resolution cells straddling a partition-parent boundary
(H3 children are not geometrically contained in their parents) may
split across shards — the merged view then upserts per shard, which is
bounded drift on boundary slivers, not corruption, and is documented
in ARCHITECTURE.md §Sharded runtime.
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger(__name__)

RES_SHIFT = 52
RES_MASK = np.uint64(0xF) << np.uint64(RES_SHIFT)

ENV_SHARDS = "HEATMAP_SHARDS"
ENV_SHARD_INDEX = "HEATMAP_SHARD_INDEX"
ENV_SHARD_RES = "HEATMAP_SHARD_RES"
ENV_SHARD_OVERSAMPLE = "HEATMAP_SHARD_OVERSAMPLE"


def parent_cells(cells: np.ndarray, res: int, parent_res: int) -> np.ndarray:
    """Vectorized H3 parent at ``parent_res`` for uint64 cell indices of
    uniform resolution ``res`` — the index bit surgery of
    query.pyramid.cell_to_parent (resolution field lowered, freed digits
    set to the invalid marker 7), exact for pentagons too."""
    if parent_res > res:
        raise ValueError(
            f"parent res {parent_res} finer than cell res {res}")
    cells = np.asarray(cells, np.uint64)
    out = (cells & ~RES_MASK) | (np.uint64(parent_res) << np.uint64(RES_SHIFT))
    for r in range(parent_res + 1, res + 1):
        out = out | (np.uint64(0x7) << np.uint64(3 * (15 - r)))
    return out


def _fmix64(x: np.ndarray) -> np.ndarray:
    """murmur3's 64-bit finalizer: a fixed, process-independent integer
    mix (no salted hashing anywhere near a partition key)."""
    x = np.asarray(x, np.uint64).copy()
    x ^= x >> np.uint64(33)
    x *= np.uint64(0xFF51AFD7ED558CCD)
    x ^= x >> np.uint64(33)
    x *= np.uint64(0xC4CEB9FE1A85EC53)
    x ^= x >> np.uint64(33)
    return x


def _snap_cells(lat_rad: np.ndarray, lng_rad: np.ndarray, res: int,
                host_snap) -> np.ndarray:
    """uint64 H3 cells at ``res`` for f32-radian coordinates — C++ host
    snap when a toolchain exists, else the exact Python host oracle
    (slow; tests and toolchain-less hosts only)."""
    lat_rad = np.asarray(lat_rad, np.float32)
    lng_rad = np.asarray(lng_rad, np.float32)
    if host_snap is not None:
        hi, lo = host_snap(lat_rad, lng_rad, res)
        return (hi.astype(np.uint64) << np.uint64(32)) \
            | lo.astype(np.uint64)
    from heatmap_tpu.hexgrid.host import latlng_to_cell_int

    return np.fromiter(
        (latlng_to_cell_int(float(la), float(lo_), res)
         for la, lo_ in zip(lat_rad, lng_rad)),
        np.uint64, count=len(lat_rad))


class ShardMap:
    """Stable H3-parent → shard assignment for one runtime shard.

    ``snap_res`` is the resolution events are snapped at for
    partitioning (the coarsest fold resolution); ``parent_res`` is the
    partition-key resolution (<= snap_res; -1 = snap_res)."""

    def __init__(self, n_shards: int, index: int, snap_res: int,
                 parent_res: int = -1):
        if n_shards < 1:
            raise ValueError(f"HEATMAP_SHARDS must be >= 1, got {n_shards}")
        if not 0 <= index < n_shards:
            raise ValueError(
                f"HEATMAP_SHARD_INDEX must be in 0..{n_shards - 1}, "
                f"got {index}")
        if not 0 <= snap_res <= 15:
            raise ValueError(f"snap res {snap_res} out of range")
        if parent_res == -1:
            parent_res = snap_res
        if not 0 <= parent_res <= snap_res:
            raise ValueError(
                f"HEATMAP_SHARD_RES must be in 0..{snap_res} (the snap "
                f"resolution), got {parent_res}")
        self.n_shards = int(n_shards)
        self.index = int(index)
        self.snap_res = int(snap_res)
        self.parent_res = int(parent_res)
        self._host_snap = None
        # the same host snap the fold's native path uses, so the
        # partition key derives from the very cell the fold will key on
        from heatmap_tpu.hexgrid import native_snap

        if native_snap.available():
            self._host_snap = native_snap.snap_arrays

    @classmethod
    def from_config(cls, cfg) -> "ShardMap | None":
        """The runtime's shard map, or None when unsharded."""
        if cfg.shards <= 1:
            return None
        return cls(cfg.shards, cfg.shard_index, min(cfg.resolutions),
                   cfg.shard_res)

    # ------------------------------------------------------------- keys
    def cells_of(self, lat_rad: np.ndarray, lng_rad: np.ndarray
                 ) -> np.ndarray:
        """uint64 H3 cells at ``snap_res`` for f32-radian coordinates —
        C++ host snap when a toolchain exists, else the exact Python
        host oracle (slow; tests and toolchain-less hosts only)."""
        return _snap_cells(lat_rad, lng_rad, self.snap_res,
                           self._host_snap)

    def shard_of_cells(self, cells: np.ndarray,
                       res: int | None = None) -> np.ndarray:
        """int32 shard id per uint64 cell (uniform resolution ``res``,
        default snap_res).  Pure function of (cell, n_shards): stable
        across runs, processes, and hosts."""
        parents = parent_cells(cells, self.snap_res if res is None else res,
                               self.parent_res)
        return (_fmix64(parents) % np.uint64(self.n_shards)).astype(np.int32)

    def owned_mask(self, lat_rad: np.ndarray, lng_rad: np.ndarray
                   ) -> np.ndarray:
        """bool mask of the rows this shard folds."""
        if len(np.asarray(lat_rad)) == 0:
            return np.zeros(0, bool)
        return self.shard_of_cells(self.cells_of(lat_rad, lng_rad)) \
            == self.index

    def filter_columns(self, cols):
        """(owned-rows EventColumns, n_out_of_shard, owned_cells).  Row
        order is preserved (the per-group f32 accumulation order is what
        the 1-vs-N differential byte-identity rests on); a fully-owned
        batch is returned untouched.

        The runtime accounts every filtered row under a CLOSED drop
        reason (``out_of_shard``, or ``oversample`` in
        HEATMAP_SHARD_OVERSAMPLE mode where foreign rows are the
        expected majority of each poll — stream.metrics.DROP_REASONS):
        an untagged drop here would be a permanent conservation-ledger
        residual at the feed/fold boundary (obs/audit.py).

        ``owned_cells`` are the surviving rows' uint64 H3 cells at
        ``snap_res`` when the NATIVE host snap computed the partition
        key, else None.  The runtime reuses them as the fold's pre-snap
        keys for that resolution (the same ``native_snap.snap_arrays``
        bits, just split back into hi/lo) — without the handoff a
        sharded feed pays the coarsest-resolution host snap twice per
        row, and the feed stage is the measured bottleneck."""
        if len(cols) == 0:
            return cols, 0, (np.zeros(0, np.uint64)
                             if self._host_snap is not None else None)
        cells = self.cells_of(cols.lat_rad, cols.lng_rad)
        mask = self.shard_of_cells(cells) == self.index
        n_foreign = int(len(mask) - np.count_nonzero(mask))
        owned_cells = cells if self._host_snap is not None else None
        if n_foreign == 0:
            return cols, 0, owned_cells
        keep = np.flatnonzero(mask)
        if owned_cells is not None:
            owned_cells = owned_cells[keep]
        from heatmap_tpu.stream.events import take_columns

        return take_columns(cols, keep), n_foreign, owned_cells

    def describe(self) -> str:
        return (f"shard {self.index}/{self.n_shards} "
                f"(snap res {self.snap_res}, partition parent res "
                f"{self.parent_res}, "
                f"{'native' if self._host_snap else 'python'} host snap)")


class MeshPartition:
    """Stable H3-parent → mesh-device assignment for the partitioned
    mesh fast path (parallel.sharded.PartitionedAggregator).

    Same exactness contract as :class:`ShardMap` — the partition key is
    the H3 parent (bit surgery) of the event's cell snapped at the
    COARSEST fold resolution with the fold's own host snap, mapped
    through murmur3 fmix64: a pure, stable function of the cell index,
    so every (cell, window) group lands wholly on one device and the
    merged per-device emits are byte-identical to the single-device
    fold (single-resolution configs; multi-res pyramids carry the same
    bounded boundary-sliver caveat ShardMap documents).

    ``outer_shards`` composes with PROCESS-level H3 sharding
    (HEATMAP_SHARDS): a shard process already filtered its rows by
    ``fmix64(parent) % N``, so the device key must consume DIFFERENT
    hash bits — the quotient ``fmix64(parent) // N`` feeds the device
    modulus.  With correlated moduli (e.g. N == D == 2) the naive
    same-hash assignment would park every one of a process's rows on
    its first device."""

    def __init__(self, n_devices: int, snap_res: int,
                 parent_res: int = -1, outer_shards: int = 1):
        if n_devices < 1:
            raise ValueError(f"mesh device count must be >= 1, "
                             f"got {n_devices}")
        if not 0 <= snap_res <= 15:
            raise ValueError(f"snap res {snap_res} out of range")
        if parent_res == -1:
            parent_res = snap_res
        if not 0 <= parent_res <= snap_res:
            raise ValueError(
                f"mesh partition parent res must be in 0..{snap_res} "
                f"(the snap resolution), got {parent_res}")
        self.n_devices = int(n_devices)
        self.snap_res = int(snap_res)
        self.parent_res = int(parent_res)
        self.outer_shards = max(1, int(outer_shards))
        self._host_snap = None
        from heatmap_tpu.hexgrid import native_snap

        if native_snap.available():
            self._host_snap = native_snap.snap_arrays

    @property
    def native(self) -> bool:
        """True when the C++ host snap computes the partition key — the
        runtime then reuses the cells as the fold's pre-snap keys for
        the coarsest resolution (the PR 7 handoff, per device)."""
        return self._host_snap is not None

    def cells_of(self, lat_rad: np.ndarray, lng_rad: np.ndarray
                 ) -> np.ndarray:
        return _snap_cells(lat_rad, lng_rad, self.snap_res,
                           self._host_snap)

    def device_of_cells(self, cells: np.ndarray,
                        res: int | None = None) -> np.ndarray:
        """int32 mesh-device id per uint64 cell.  Pure function of
        (cell, outer_shards, n_devices): stable across runs/processes."""
        parents = parent_cells(
            cells, self.snap_res if res is None else res, self.parent_res)
        mix = _fmix64(parents) // np.uint64(self.outer_shards)
        return (mix % np.uint64(self.n_devices)).astype(np.int32)

    def partition(self, lat_rad: np.ndarray, lng_rad: np.ndarray,
                  cells: np.ndarray | None = None):
        """(device ids, cells) for a batch's rows.  ``cells`` may be the
        process-level ownership filter's already-snapped cells (same
        snap_res by construction — both partition at the coarsest fold
        resolution), in which case no second snap is paid."""
        if cells is None:
            cells = self.cells_of(lat_rad, lng_rad)
        return self.device_of_cells(cells), cells

    def describe(self) -> str:
        return (f"{self.n_devices}-device mesh partition (snap res "
                f"{self.snap_res}, parent res {self.parent_res}, "
                f"outer shards {self.outer_shards}, "
                f"{'native' if self._host_snap else 'python'} host snap)")
