"""Canonical GPS event schema and columnar parsing.

The reference's event is an 8-field JSON object (reference:
heatmap_stream.py:52-61; README.md:194-204):

    provider, vehicleId, lat, lon, speedKmh, bearing, accuracyM, ts

``parse_events`` converts a list of event dicts into struct-of-arrays form
with the reference's validation folded in (null provider/vehicleId dropped,
lat/lon bounds, unparseable ts dropped — heatmap_stream.py:96-108).  The
numeric columns go to the device; provider/vehicleId stay host-side as
interned int ids + string tables (needed only for positions_latest).
"""

from __future__ import annotations

import datetime as dt
from dataclasses import dataclass, field

import numpy as np

UTC = dt.timezone.utc
_D2R = np.float32(np.pi / 180.0)


def parse_ts(value) -> float | None:
    """ISO-8601 (Z or offset) string or epoch number -> epoch seconds."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, dt.datetime):
        if value.tzinfo is None:
            value = value.replace(tzinfo=UTC)
        return value.timestamp()
    try:
        s = str(value)
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        d = dt.datetime.fromisoformat(s)
        if d.tzinfo is None:
            d = d.replace(tzinfo=UTC)
        return d.timestamp()
    except (ValueError, TypeError):
        return None


@dataclass
class EventColumns:
    """Struct-of-arrays batch of validated events (host side)."""

    lat_rad: np.ndarray      # float32
    lng_rad: np.ndarray      # float32
    lat_deg: np.ndarray      # float32 (kept for positions docs)
    lng_deg: np.ndarray      # float32
    speed_kmh: np.ndarray    # float32 (missing -> 0, like the ref's avg of nulls)
    ts_s: np.ndarray         # int32 epoch seconds
    provider_id: np.ndarray  # int32 index into providers
    vehicle_id: np.ndarray   # int32 index into vehicles
    providers: list[str] = field(default_factory=list)
    vehicles: list[str] = field(default_factory=list)
    n_dropped: int = 0       # failed validation

    def __len__(self) -> int:
        return len(self.lat_rad)


def parse_events(events, intern_p=None, intern_v=None) -> EventColumns:
    """Validate + columnarize a list of event dicts.

    ``intern_p``/``intern_v`` are optional persistent {str: int} intern maps
    (the runtime passes its own so ids are stable across batches)."""
    lat, lng, spd, ts, pid, vid = [], [], [], [], [], []
    p_map = intern_p if intern_p is not None else {}
    v_map = intern_v if intern_v is not None else {}
    dropped = 0
    for e in events:
        try:
            la = float(e["lat"])
            lo = float(e["lon"])
            provider = e.get("provider")
            vehicle = e.get("vehicleId")
            t = parse_ts(e.get("ts"))
        except (KeyError, TypeError, ValueError):
            dropped += 1
            continue
        # the reference's filters (heatmap_stream.py:96-104), plus ts sanity:
        # NaN/inf and out-of-epoch-seconds-range (e.g. milliseconds) dropped
        if (provider is None or vehicle is None or t is None
                or not np.isfinite(t) or not (0.0 <= t < 2**31)
                or not (-90.0 <= la <= 90.0) or not (-180.0 <= lo <= 180.0)
                or not np.isfinite(la) or not np.isfinite(lo)):
            dropped += 1
            continue
        s = e.get("speedKmh")
        try:
            s = float(s) if s is not None else 0.0
            if not np.isfinite(s):
                s = 0.0
        except (TypeError, ValueError):
            s = 0.0
        lat.append(la)
        lng.append(lo)
        spd.append(s)
        ts.append(int(t))
        pid.append(p_map.setdefault(str(provider), len(p_map)))
        vid.append(v_map.setdefault(str(vehicle), len(v_map)))

    lat_deg = np.asarray(lat, np.float32)
    lng_deg = np.asarray(lng, np.float32)
    return EventColumns(
        lat_rad=lat_deg * _D2R,
        lng_rad=lng_deg * _D2R,
        lat_deg=lat_deg,
        lng_deg=lng_deg,
        speed_kmh=np.asarray(spd, np.float32),
        ts_s=np.asarray(ts, np.int32),
        provider_id=np.asarray(pid, np.int32),
        vehicle_id=np.asarray(vid, np.int32),
        providers=list(p_map),
        vehicles=list(v_map),
        n_dropped=dropped,
    )


def columns_from_arrays(lat_deg, lng_deg, speed_kmh, ts_s,
                        provider_id=None, vehicle_id=None,
                        providers=None, vehicles=None) -> EventColumns:
    """Zero-parse path for columnar sources (synthetic/native decoder)."""
    lat_deg = np.asarray(lat_deg, np.float32)
    lng_deg = np.asarray(lng_deg, np.float32)
    n = len(lat_deg)
    z = np.zeros(n, np.int32)
    return EventColumns(
        lat_rad=lat_deg * _D2R,
        lng_rad=lng_deg * _D2R,
        lat_deg=lat_deg,
        lng_deg=lng_deg,
        speed_kmh=np.asarray(speed_kmh, np.float32),
        ts_s=np.asarray(ts_s, np.int32),
        provider_id=np.asarray(provider_id, np.int32) if provider_id is not None else z,
        vehicle_id=np.asarray(vehicle_id, np.int32) if vehicle_id is not None else z,
        providers=providers or ["synthetic"],
        vehicles=vehicles or [],
    )


def empty_columns(providers=None, vehicles=None) -> EventColumns:
    """A zero-row batch (shared string tables passed through, NOT the
    defaulted ones columns_from_arrays would substitute)."""
    import dataclasses

    cols = columns_from_arrays([], [], [], [])
    return dataclasses.replace(
        cols,
        providers=providers if providers is not None else [],
        vehicles=vehicles if vehicles is not None else [],
    )


def take_columns(cols: EventColumns, idx: np.ndarray) -> EventColumns:
    """Row subset of a batch by index array, order preserved (string
    tables shared; n_dropped stays with the subset — validation counts
    were booked before any ownership filter ran)."""
    return EventColumns(
        lat_rad=cols.lat_rad[idx],
        lng_rad=cols.lng_rad[idx],
        lat_deg=cols.lat_deg[idx],
        lng_deg=cols.lng_deg[idx],
        speed_kmh=cols.speed_kmh[idx],
        ts_s=cols.ts_s[idx],
        provider_id=cols.provider_id[idx],
        vehicle_id=cols.vehicle_id[idx],
        providers=cols.providers,
        vehicles=cols.vehicles,
        n_dropped=cols.n_dropped,
    )


def slice_columns(cols: EventColumns, start: int, stop: int) -> EventColumns:
    """Row slice of a batch (string tables shared, n_dropped stays with
    the head slice so counts aren't double-booked)."""
    return EventColumns(
        lat_rad=cols.lat_rad[start:stop],
        lng_rad=cols.lng_rad[start:stop],
        lat_deg=cols.lat_deg[start:stop],
        lng_deg=cols.lng_deg[start:stop],
        speed_kmh=cols.speed_kmh[start:stop],
        ts_s=cols.ts_s[start:stop],
        provider_id=cols.provider_id[start:stop],
        vehicle_id=cols.vehicle_id[start:stop],
        providers=cols.providers,
        vehicles=cols.vehicles,
        n_dropped=cols.n_dropped if start == 0 else 0,
    )
